"""conformance plugin (plugins/conformance/conformance.go:42-59): never evict
critical pods — system-cluster-critical / system-node-critical priority
classes or anything in kube-system."""

from __future__ import annotations

from typing import List

from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.types import CRITICAL_NAMESPACE, CRITICAL_PRIORITY_CLASSES
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework import session as fw


class ConformancePlugin(Plugin):
    name = "conformance"

    def on_session_open(self, ssn: fw.Session) -> None:
        def evictable(evictor: TaskInfo, evictees: List[TaskInfo]) -> List[TaskInfo]:
            victims = []
            for ee in evictees:
                if (
                    ee.pod.priority_class in CRITICAL_PRIORITY_CLASSES
                    or ee.namespace == CRITICAL_NAMESPACE
                ):
                    continue
                victims.append(ee)
            return victims

        ssn.add_fn(fw.PREEMPTABLE, self.name, evictable)
        ssn.add_fn(fw.RECLAIMABLE, self.name, evictable)
