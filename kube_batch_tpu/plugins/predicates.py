"""predicates plugin (plugins/predicates/predicates.go) — node filtering.

The device solve evaluates these same predicates as bitset tensor ops
(ops/feasibility.py); this host fn is the authoritative per-(task, node)
form used by the host-path actions (preempt/reclaim/backfill) and by tests.

Checks, mirroring predicates.go:154-298:
  max-pods (:162-166), CheckNodeCondition/Unschedulable (:169-192),
  MatchNodeSelector incl. required node-affinity terms (:194-205),
  PodFitsHostPorts (:207-218), PodToleratesNodeTaints (:220-231), and the
  optional Memory/Disk/PID pressure gates driven by plugin arguments
  (:233-276; arg keys :34-41). Inter-pod affinity is not yet modeled (the
  snapshot carries no pod-affinity terms); tracked for a later round.
"""

from __future__ import annotations

from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.snapshot import HARD_TAINT_EFFECTS
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework import session as fw

# plugin argument keys (predicates.go:34-41)
MEMORY_PRESSURE_KEY = "predicate.MemoryPressureEnable"
DISK_PRESSURE_KEY = "predicate.DiskPressureEnable"
PID_PRESSURE_KEY = "predicate.PIDPressureEnable"


def match_node_selector(task: TaskInfo, node: NodeInfo) -> bool:
    labels = node.node.labels if node.node else {}
    for k, v in task.pod.node_selector.items():
        if labels.get(k) != v:
            return False
    if task.pod.affinity is not None:
        terms = task.pod.affinity.node_terms
        if terms:
            def term_ok(term):
                for key, op, values in term:
                    has = key in labels
                    if op == "In" and labels.get(key) not in values:
                        return False
                    if op == "NotIn" and labels.get(key) in values:
                        return False
                    if op == "Exists" and not has:
                        return False
                    if op == "DoesNotExist" and has:
                        return False
                return True

            if not any(term_ok(t) for t in terms):
                return False
    return True


def tolerates_taints(task: TaskInfo, node: NodeInfo) -> bool:
    for taint in node.node.taints if node.node else []:
        if taint.effect not in HARD_TAINT_EFFECTS:
            continue
        if not any(tol.tolerates(taint) for tol in task.pod.tolerations):
            return False
    return True


def fits_host_ports(task: TaskInfo, node: NodeInfo) -> bool:
    wanted = set(task.pod.host_ports)
    if not wanted:
        return True
    for other in node.tasks.values():
        if wanted & set(other.pod.host_ports):
            return False
    return True


class PredicatesPlugin(Plugin):
    name = "predicates"

    def on_session_open(self, ssn: fw.Session) -> None:
        check_mem = self.arguments.get_bool(MEMORY_PRESSURE_KEY, False)
        check_disk = self.arguments.get_bool(DISK_PRESSURE_KEY, False)
        check_pid = self.arguments.get_bool(PID_PRESSURE_KEY, False)

        def predicate(task: TaskInfo, node: NodeInfo) -> None:
            if node.node is None or not node.node.ready:
                raise fw.FitFailure("node(s) were not ready")
            if node.node.unschedulable:
                raise fw.FitFailure("node(s) were unschedulable")
            if node.pod_count + 1 > int(node.allocatable.pods):
                raise fw.FitFailure("node(s) pod number exceeded")
            if not match_node_selector(task, node):
                raise fw.FitFailure("node(s) didn't match node selector")
            if not fits_host_ports(task, node):
                raise fw.FitFailure("node(s) didn't have free ports")
            if not tolerates_taints(task, node):
                raise fw.FitFailure("node(s) had taints that the pod didn't tolerate")
            conds = node.node.conditions
            if check_mem and conds.get("MemoryPressure"):
                raise fw.FitFailure("node(s) had memory pressure")
            if check_disk and conds.get("DiskPressure"):
                raise fw.FitFailure("node(s) had disk pressure")
            if check_pid and conds.get("PIDPressure"):
                raise fw.FitFailure("node(s) had pid pressure")

        ssn.add_fn(fw.PREDICATE, self.name, predicate)
