"""predicates plugin (plugins/predicates/predicates.go) — node filtering.

The device solve evaluates these same predicates as bitset tensor ops
(ops/feasibility.py); this host fn is the authoritative per-(task, node)
form used by the host-path actions (preempt/reclaim/backfill) and by tests.

Checks, mirroring predicates.go:154-298:
  max-pods (:162-166), CheckNodeCondition/Unschedulable (:169-192),
  MatchNodeSelector incl. required node-affinity terms (:194-205),
  PodFitsHostPorts (:207-218), PodToleratesNodeTaints (:220-231), and the
  optional Memory/Disk/PID pressure gates driven by plugin arguments
  (:233-276; arg keys :34-41), and required inter-pod affinity/anti-affinity
  with the affinity-only fast path (:278-296). The device mask carries a
  snapshot-time approximation of the inter-pod terms (build_snapshot's
  correction mask); this host predicate re-validates every proposed
  placement against LIVE session state, so two anti-affine tasks placed in
  one device round can't both commit.
"""

from __future__ import annotations

from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.pod import node_selector_terms_match
from kube_batch_tpu.api.snapshot import HARD_TAINT_EFFECTS
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework import session as fw

# plugin argument keys (predicates.go:34-41)
MEMORY_PRESSURE_KEY = "predicate.MemoryPressureEnable"
DISK_PRESSURE_KEY = "predicate.DiskPressureEnable"
PID_PRESSURE_KEY = "predicate.PIDPressureEnable"


def match_node_selector(task: TaskInfo, node: NodeInfo) -> bool:
    labels = node.node.labels if node.node else {}
    for k, v in task.pod.node_selector.items():
        if labels.get(k) != v:
            return False
    if task.pod.affinity is not None:
        terms = task.pod.affinity.node_terms
        # shared evaluator (api/pod.py) — also the PV ledger's reachability
        # check; adds Gt/Lt and fails closed on unknown operators (the old
        # inline check silently passed them)
        if terms and not node_selector_terms_match(terms, labels):
            return False
    return True


def tolerates_taints(task: TaskInfo, node: NodeInfo) -> bool:
    for taint in node.node.taints if node.node else []:
        if taint.effect not in HARD_TAINT_EFFECTS:
            continue
        if not any(tol.tolerates(taint) for tol in task.pod.tolerations):
            return False
    return True


def _topology_domain(node: NodeInfo, topology_key: str, all_nodes) -> list:
    """Nodes in `node`'s topology domain (hostname ⇒ just the node)."""
    from kube_batch_tpu.api.pod import HOSTNAME_TOPOLOGY

    if topology_key == HOSTNAME_TOPOLOGY:
        return [node]
    labels = node.node.labels if node.node else {}
    value = labels.get(topology_key)
    if value is None:
        return [node]
    return [
        n for n in all_nodes
        if n.node is not None and n.node.labels.get(topology_key) == value
    ]


def pod_affinity_ok(task: TaskInfo, node: NodeInfo, all_nodes) -> bool:
    """InterPodAffinityMatches (predicates.go:278-296): required affinity
    terms need a matching existing pod in the node's topology domain —
    unless NO pod matches anywhere (the affinity-only fast path, letting a
    group's first pod land); anti-affinity terms must have no match in the
    domain. Placements made earlier in this session count — node.tasks is
    live session state."""
    aff = task.pod.affinity
    if aff is None:
        return True
    for term in aff.pod_affinity:
        domain = _topology_domain(node, term.topology_key, all_nodes)
        if any(
            term.matches(t.pod.labels)
            for n in domain for t in n.tasks.values()
        ):
            continue
        # fast path: a term no pod satisfies cluster-wide doesn't block
        if any(
            term.matches(t.pod.labels)
            for n in all_nodes for t in n.tasks.values()
        ):
            return False
    for term in aff.pod_anti_affinity:
        domain = _topology_domain(node, term.topology_key, all_nodes)
        if any(
            term.matches(t.pod.labels) and t.key() != task.key()
            for n in domain for t in n.tasks.values()
        ):
            return False
    return True


def fits_host_ports(task: TaskInfo, node: NodeInfo) -> bool:
    wanted = set(task.pod.host_ports)
    if not wanted:
        return True
    for other in node.tasks.values():
        if wanted & set(other.pod.host_ports):
            return False
    return True


class PredicatesPlugin(Plugin):
    name = "predicates"

    def on_session_open(self, ssn: fw.Session) -> None:
        check_mem = self.arguments.get_bool(MEMORY_PRESSURE_KEY, False)
        check_disk = self.arguments.get_bool(DISK_PRESSURE_KEY, False)
        check_pid = self.arguments.get_bool(PID_PRESSURE_KEY, False)
        # pressure gates are task-independent node vetoes
        # (predicates.go:233-276): encode them as a session-level node
        # exclusion both snapshot builders fold into node_sched — the device
        # mask stays exact and no job is demoted to the host replay for them
        if check_mem or check_disk or check_pid:
            for node in ssn.nodes.values():
                obj = node.node
                if obj is None:
                    continue
                conds = obj.conditions
                if (
                    (check_mem and conds.get("MemoryPressure"))
                    or (check_disk and conds.get("DiskPressure"))
                    or (check_pid and conds.get("PIDPressure"))
                ):
                    ssn.session_excluded_nodes.add(node.name)

        def predicate(task: TaskInfo, node: NodeInfo) -> None:
            if node.node is None or not node.node.ready:
                raise fw.FitFailure("node(s) were not ready")
            if node.node.unschedulable:
                raise fw.FitFailure("node(s) were unschedulable")
            if node.pod_count + 1 > int(node.allocatable.pods):
                raise fw.FitFailure("node(s) pod number exceeded")
            if not match_node_selector(task, node):
                raise fw.FitFailure("node(s) didn't match node selector")
            if not fits_host_ports(task, node):
                raise fw.FitFailure("node(s) didn't have free ports")
            if not tolerates_taints(task, node):
                raise fw.FitFailure("node(s) had taints that the pod didn't tolerate")
            if not pod_affinity_ok(task, node, ssn.nodes.values()):
                raise fw.FitFailure(
                    "node(s) didn't satisfy inter-pod affinity/anti-affinity"
                )
            conds = node.node.conditions
            if check_mem and conds.get("MemoryPressure"):
                raise fw.FitFailure("node(s) had memory pressure")
            if check_disk and conds.get("DiskPressure"):
                raise fw.FitFailure("node(s) had disk pressure")
            if check_pid and conds.get("PIDPressure"):
                raise fw.FitFailure("node(s) had pid pressure")

        ssn.add_fn(fw.PREDICATE, self.name, predicate)
