"""Version info (pkg/version/version.go; injected via LD_FLAGS in the
reference's Makefile:7-10 — a plain constant here)."""

VERSION = "0.1.0"
GIT_SHA = "dev"


def version_string() -> str:
    return f"kube-batch-tpu {VERSION} ({GIT_SHA})"
