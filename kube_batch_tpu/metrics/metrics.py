"""Prometheus-compatible metrics (pkg/scheduler/metrics/metrics.go:27-121).

Same metric names and label sets under the `volcano` subsystem, with the
reference's 5·2^k exponential buckets, rendered in the Prometheus text
exposition format. Implemented standalone (no prometheus_client dependency);
serve render_prometheus() from any HTTP endpoint to match the reference's
`/metrics` (server.go:96-99)."""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict
from typing import Dict, List, Tuple

# 5·2^k, k=0..9 (metrics.go:38-72)
EXP_BUCKETS = [5.0 * (2**k) for k in range(10)]


class Histogram:
    def __init__(self, name: str, help_text: str, labels: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_text
        self.label_names = labels
        self._lock = threading.Lock()
        self._buckets: Dict[Tuple[str, ...], List[int]] = defaultdict(
            lambda: [0] * (len(EXP_BUCKETS) + 1)
        )
        self._sum: Dict[Tuple[str, ...], float] = defaultdict(float)
        self._count: Dict[Tuple[str, ...], int] = defaultdict(int)

    def observe(self, value: float, *label_values: str) -> None:
        self.observe_many(value, 1, *label_values)

    def observe_many(self, value: float, count: int, *label_values: str) -> None:
        """Record `count` samples of `value` in one update — the vectorized
        cycle's amortized per-task observations (50k individual observe()
        calls per cycle would be pure lock churn)."""
        if count <= 0:
            return
        with self._lock:
            b = self._buckets[label_values]
            b[bisect.bisect_left(EXP_BUCKETS, value)] += count
            self._sum[label_values] += value * count
            self._count[label_values] += count

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            for labels, buckets in self._buckets.items():
                base = ",".join(
                    f'{n}="{v}"' for n, v in zip(self.label_names, labels)
                )
                cum = 0
                for le, cnt in zip(EXP_BUCKETS, buckets):
                    cum += cnt
                    sep = "," if base else ""
                    lines.append(f'{self.name}_bucket{{{base}{sep}le="{le:g}"}} {cum}')
                cum += buckets[-1]
                sep = "," if base else ""
                lines.append(f'{self.name}_bucket{{{base}{sep}le="+Inf"}} {cum}')
                lines.append(f"{self.name}_sum{{{base}}} {self._sum[labels]:g}")
                lines.append(f"{self.name}_count{{{base}}} {self._count[labels]}")
        return "\n".join(lines)


class Counter:
    #: Prometheus exposition type — Gauge overrides (a counter that goes
    #: down reads as a reset to Prometheus clients)
    prom_type = "counter"

    def __init__(self, name: str, help_text: str, labels: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_text
        self.label_names = labels
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], float] = defaultdict(float)

    def add(self, value: float, *label_values: str) -> None:
        with self._lock:
            self._values[label_values] += value

    def inc(self, *label_values: str) -> None:
        self.add(1.0, *label_values)

    def set(self, value: float, *label_values: str) -> None:
        with self._lock:
            self._values[label_values] = value

    def remove(self, *label_values: str) -> None:
        """Drop a labeled series — per-job series are pruned when the job
        is collected, or long-running servers grow /metrics unboundedly."""
        with self._lock:
            self._values.pop(label_values, None)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.prom_type}"]
        with self._lock:
            for labels, v in self._values.items():
                base = ",".join(f'{n}="{val}"' for n, val in zip(self.label_names, labels))
                lines.append(f"{self.name}{{{base}}} {v:g}")
        return "\n".join(lines)


class Gauge(Counter):
    """A settable series rendered with TYPE gauge (Counter already carries
    set(); only the exposition type differs — Prometheus clients treat a
    counter that goes down as a reset, so shares/versions must not render
    as counters)."""

    prom_type = "gauge"


_SUBSYSTEM = "volcano"

E2E_LATENCY = Histogram(
    f"{_SUBSYSTEM}_e2e_scheduling_latency_milliseconds",
    "E2E scheduling latency in milliseconds",
)
PLUGIN_LATENCY = Histogram(
    f"{_SUBSYSTEM}_plugin_scheduling_latency_microseconds",
    "Plugin scheduling latency in microseconds",
    ("plugin", "OnSession"),
)
ACTION_LATENCY = Histogram(
    f"{_SUBSYSTEM}_action_scheduling_latency_microseconds",
    "Action scheduling latency in microseconds",
    ("action",),
)
TASK_LATENCY = Histogram(
    f"{_SUBSYSTEM}_task_scheduling_latency_microseconds",
    "Task scheduling latency in microseconds",
)
SCHEDULE_ATTEMPTS = Counter(
    f"{_SUBSYSTEM}_schedule_attempts_total",
    "Number of attempts to schedule pods, by the result",
    ("result",),
)
POD_PREEMPTION_VICTIMS = Counter(
    f"{_SUBSYSTEM}_pod_preemption_victims",
    "Number of selected preemption victims",
)
PREEMPTION_ATTEMPTS = Counter(
    f"{_SUBSYSTEM}_total_preemption_attempts",
    "Total preemption attempts in the cluster till now",
)
UNSCHEDULE_TASK_COUNT = Counter(
    f"{_SUBSYSTEM}_unschedule_task_count",
    "Number of tasks could not be scheduled",
    ("job_id",),
)
UNSCHEDULE_JOB_COUNT = Counter(
    f"{_SUBSYSTEM}_unschedule_job_count",
    "Number of jobs could not be scheduled",
)
# metrics.go:113-121 — declared by the reference (never incremented there);
# here it counts jobs re-entering a cycle still unschedulable
JOB_RETRY_COUNTS = Counter(
    f"{_SUBSYSTEM}_job_retry_counts",
    "Number of retry attempts per job",
    ("job_id",),
)
# fallback-pressure counters (round-3): how much of the allocate replay ran
# outside the vectorized bulk path
SLOW_REPLAY_JOBS = Counter(
    f"{_SUBSYSTEM}_slow_replay_jobs_total",
    "Jobs replayed through the sequential Statement path",
)
HOST_FALLBACK_TASKS = Counter(
    f"{_SUBSYSTEM}_host_fallback_tasks_total",
    "Tasks placed by the O(nodes) host fallback scan",
)
# fault-hardening counters (robustness PR): classified transport retries,
# per-host circuit-breaker state, degraded-cycle parking/shedding, failover
TRANSPORT_RETRIES = Counter(
    f"{_SUBSYSTEM}_transport_retries_total",
    "Apiserver transport retries by endpoint class and error kind",
    ("endpoint_class", "kind"),
)
BREAKER_TRANSITIONS = Counter(
    f"{_SUBSYSTEM}_circuit_breaker_transitions_total",
    "Circuit breaker state transitions",
    ("host", "state"),
)
BREAKER_OPEN = Counter(
    f"{_SUBSYSTEM}_circuit_breaker_open",
    "1 while the named host's circuit breaker is open",
    ("host",),
)
RESYNC_PARKED = Counter(
    f"{_SUBSYSTEM}_resync_parked_total",
    "Failed bind/evict decisions parked in the resync queue, by reason",
    ("reason",),
)
RESYNC_DEPTH = Counter(
    f"{_SUBSYSTEM}_resync_queue_depth",
    "Tasks currently awaiting resync repair",
)
RESYNC_QUARANTINED = Counter(
    f"{_SUBSYSTEM}_resync_quarantined",
    "Tasks shelved after exhausting their resync budget",
)
STATUS_WRITES_SHED = Counter(
    f"{_SUBSYSTEM}_status_writes_shed_total",
    "Status writebacks skipped or made async by a degraded cycle",
)
CYCLE_BUDGET_EXCEEDED = Counter(
    f"{_SUBSYSTEM}_cycle_budget_exceeded_total",
    "Cycles whose soft time budget elapsed before close",
)
LEADER_FAILOVER = Counter(
    f"{_SUBSYSTEM}_leader_failover_total",
    "Leadership takeovers, by resident-cache outcome (warm|cold)",
    ("mode",),
)
# query-plane counters (serve/): the amortization story is readable straight
# off /metrics — requests_total vs device_dispatches_total is the
# requests-per-dispatch ratio the serving bench asserts
WHATIF_REQUESTS = Counter(
    f"{_SUBSYSTEM}_whatif_requests_total",
    "What-if probe requests, by verdict (feasible|infeasible|error)",
    ("verdict",),
)
WHATIF_DISPATCHES = Counter(
    f"{_SUBSYSTEM}_whatif_device_dispatches_total",
    "Batched probe device dispatches (one per flush window)",
)
WHATIF_BATCH_SIZE = Histogram(
    f"{_SUBSYSTEM}_whatif_batch_size",
    "Requests amortized into one probe dispatch",
)
WHATIF_QUEUE_DEPTH = Histogram(
    f"{_SUBSYSTEM}_whatif_queue_depth",
    "Whatif requests still queued at flush time",
)
WHATIF_LATENCY = Histogram(
    f"{_SUBSYSTEM}_whatif_request_latency_milliseconds",
    "Whatif request latency (enqueue to verdict) in milliseconds",
)
WHATIF_SNAPSHOT_VERSION = Gauge(
    f"{_SUBSYSTEM}_whatif_snapshot_version",
    "Dirty-tracker version token of the published snapshot lease",
)
# pipelined-cycle metrics (the event-driven loop): the latency the pipeline
# exists to optimize (pod ARRIVAL → bind DECISION, not just cycle ms), what
# woke each cycle, and how much egress the writeback stage hid behind the
# next cycle's compute
DECISION_LATENCY = Histogram(
    f"{_SUBSYSTEM}_arrival_to_decision_latency_milliseconds",
    "Pod-arrival to bind-decision latency in milliseconds",
)
TRIGGER_WAKES = Counter(
    f"{_SUBSYSTEM}_cycle_trigger_wakes_total",
    "Scheduling-cycle wakeups, by trigger (ingest|floor)",
    ("trigger",),
)
PIPELINE_OVERLAP = Histogram(
    f"{_SUBSYSTEM}_pipeline_writeback_overlap_milliseconds",
    "Writeback-stage time overlapped behind the next cycle (ms)",
)
STAGED_INGEST = Counter(
    f"{_SUBSYSTEM}_staged_ingest_events_total",
    "Ingest events applied through the staged (one-lock) drain",
)
# longitudinal fairness surfaced live (sim runner + any caller with
# per-queue share samples): dominant share vs weight entitlement per queue
QUEUE_SHARE = Gauge(
    f"{_SUBSYSTEM}_queue_dominant_share",
    "Per-queue dominant share of cluster capacity (0..1)",
    ("queue",),
)
QUEUE_ENTITLEMENT = Gauge(
    f"{_SUBSYSTEM}_queue_share_entitlement",
    "Per-queue weight entitlement (weight / Σ weights)",
    ("queue",),
)
# result-integrity guard plane (kube_batch_tpu/guard): sentinel trips /
# fail-closed solves, shadow-oracle audit outcomes, and per-fast-path
# demotion state — the runtime twin of the KB_* oracle knobs
GUARD_TRIPS = Counter(
    f"{_SUBSYSTEM}_guard_trips_total",
    "Result-integrity trips (condemned solves), by action and reason "
    "(invariant|audit)",
    ("action", "reason"),
)
GUARD_AUDITS = Counter(
    f"{_SUBSYSTEM}_guard_audits_total",
    "Shadow-oracle audit comparisons, by result (match|mismatch)",
    ("result",),
)
GUARD_PATH_DEMOTED = Gauge(
    f"{_SUBSYSTEM}_guard_path_demoted",
    "1 while a fast path is demoted to its oracle (topk|shard_map|pallas)",
    ("path",),
)
# cycle tracing plane (kube_batch_tpu/obs): per-stage latency straight off
# the span recorder (the histogram twin of the trace tree), flight-recorder
# dumps by trigger reason, and the guard trip-rate SLO alerts
STAGE_LATENCY = Histogram(
    f"{_SUBSYSTEM}_cycle_stage_latency_milliseconds",
    "Per-stage scheduling-cycle latency (span recorder) in milliseconds",
    ("stage",),
)
FLIGHT_DUMPS = Counter(
    f"{_SUBSYSTEM}_flight_recorder_dumps_total",
    "Flight-recorder trace dumps, by trigger reason",
    ("reason",),
)
ALERTS_FIRING = Gauge(
    f"{_SUBSYSTEM}_alerts_firing",
    "1 while the named SLO alert fires (guard trip-rate thresholds)",
    ("alert",),
)
# replicated follower read plane (kube_batch_tpu/replicate): the leader's
# published stream (records/bytes by kind), the follower's apply/resync
# outcomes, and its live lag behind the stream head in cycles
REPLICATION_RECORDS = Counter(
    f"{_SUBSYSTEM}_replication_records_total",
    "Replication records published, by kind (full|delta|heartbeat)",
    ("kind",),
)
REPLICATION_BYTES = Counter(
    f"{_SUBSYSTEM}_replication_bytes_total",
    "Replication wire bytes published (encoded frames)",
)
REPLICATION_APPLIED = Counter(
    f"{_SUBSYSTEM}_replication_applied_total",
    "Replication records applied by this follower, by kind (full|delta)",
    ("kind",),
)
REPLICATION_RESYNCS = Counter(
    f"{_SUBSYSTEM}_replication_resyncs_total",
    "Delta-chain gaps that escalated this follower to a full resync",
)
REPLICATION_LAG = Gauge(
    f"{_SUBSYSTEM}_replication_lag_cycles",
    "Cycles this follower's applied state trails the stream head",
)
WHATIF_SWEEPS = Counter(
    f"{_SUBSYSTEM}_whatif_sweeps_total",
    "Capacity sweeps (/v1/whatif/sweep) served",
)

METRICS = [
    E2E_LATENCY,
    PLUGIN_LATENCY,
    ACTION_LATENCY,
    TASK_LATENCY,
    SCHEDULE_ATTEMPTS,
    POD_PREEMPTION_VICTIMS,
    PREEMPTION_ATTEMPTS,
    UNSCHEDULE_TASK_COUNT,
    UNSCHEDULE_JOB_COUNT,
    JOB_RETRY_COUNTS,
    SLOW_REPLAY_JOBS,
    HOST_FALLBACK_TASKS,
    TRANSPORT_RETRIES,
    BREAKER_TRANSITIONS,
    BREAKER_OPEN,
    RESYNC_PARKED,
    RESYNC_DEPTH,
    RESYNC_QUARANTINED,
    STATUS_WRITES_SHED,
    CYCLE_BUDGET_EXCEEDED,
    LEADER_FAILOVER,
    WHATIF_REQUESTS,
    WHATIF_DISPATCHES,
    WHATIF_BATCH_SIZE,
    WHATIF_QUEUE_DEPTH,
    WHATIF_LATENCY,
    WHATIF_SNAPSHOT_VERSION,
    DECISION_LATENCY,
    TRIGGER_WAKES,
    PIPELINE_OVERLAP,
    STAGED_INGEST,
    QUEUE_SHARE,
    QUEUE_ENTITLEMENT,
    GUARD_TRIPS,
    GUARD_AUDITS,
    GUARD_PATH_DEMOTED,
    STAGE_LATENCY,
    FLIGHT_DUMPS,
    ALERTS_FIRING,
    REPLICATION_RECORDS,
    REPLICATION_BYTES,
    REPLICATION_APPLIED,
    REPLICATION_RESYNCS,
    REPLICATION_LAG,
    WHATIF_SWEEPS,
]


def observe_e2e_latency(ms: float) -> None:
    E2E_LATENCY.observe(ms)


def observe_action_latency(action: str, us: float) -> None:
    ACTION_LATENCY.observe(us, action)


def observe_plugin_latency(plugin: str, on_session: str, us: float) -> None:
    PLUGIN_LATENCY.observe(us, plugin, on_session)


def observe_task_latency(us: float) -> None:
    TASK_LATENCY.observe(us)


def observe_task_latencies(us_each: float, count: int) -> None:
    """Amortized per-task latency for `count` placements of one cycle —
    the vectorized analog of the reference's per-task observation
    (metrics.go:66-72, session.go:321)."""
    TASK_LATENCY.observe_many(us_each, count)


def register_schedule_attempt(result: str) -> None:
    SCHEDULE_ATTEMPTS.inc(result)


def update_preemption_victims(count: int) -> None:
    POD_PREEMPTION_VICTIMS.add(count)


def register_preemption_attempt() -> None:
    PREEMPTION_ATTEMPTS.inc()


def update_unschedule_task_count(job_id: str, count: int) -> None:
    UNSCHEDULE_TASK_COUNT.set(count, job_id)


def update_unschedule_job_count(count: int) -> None:
    UNSCHEDULE_JOB_COUNT.set(count)


def register_job_retry(job_id: str) -> None:
    JOB_RETRY_COUNTS.inc(job_id)


def prune_job_series(job_id: str) -> None:
    """Forget a collected job's labeled series (job_retry_counts,
    unschedule_task_count) — the cardinality bound for per-job labels."""
    JOB_RETRY_COUNTS.remove(job_id)
    UNSCHEDULE_TASK_COUNT.remove(job_id)


def register_slow_replay_jobs(count: int) -> None:
    if count:
        SLOW_REPLAY_JOBS.add(count)


def register_host_fallback_tasks(count: int) -> None:
    if count:
        HOST_FALLBACK_TASKS.add(count)


def register_transport_retry(endpoint_class: str, kind: str) -> None:
    TRANSPORT_RETRIES.inc(endpoint_class, kind)


def register_breaker_transition(host: str, state: str) -> None:
    BREAKER_TRANSITIONS.inc(host, state)


def set_breaker_open(host: str, is_open: int) -> None:
    BREAKER_OPEN.set(float(is_open), host)


def register_resync_parked(reason: str) -> None:
    RESYNC_PARKED.inc(reason)


def set_resync_depth(depth: int, quarantined: int) -> None:
    RESYNC_DEPTH.set(float(depth))
    RESYNC_QUARANTINED.set(float(quarantined))


def register_status_writes_shed(count: int) -> None:
    if count:
        STATUS_WRITES_SHED.add(count)


def register_cycle_budget_exceeded() -> None:
    CYCLE_BUDGET_EXCEEDED.inc()


def register_leader_failover(mode: str) -> None:
    LEADER_FAILOVER.inc(mode)


def register_guard_trip(action: str, reason: str) -> None:
    GUARD_TRIPS.inc(action, reason)


def register_guard_audit(result: str) -> None:
    GUARD_AUDITS.inc(result)


def set_guard_path_demoted(path: str, demoted: int) -> None:
    GUARD_PATH_DEMOTED.set(demoted, path)


def observe_stage_latency(stage: str, ms: float) -> None:
    STAGE_LATENCY.observe(ms, stage)


def register_flight_dump(reason: str) -> None:
    FLIGHT_DUMPS.inc(reason)


def set_alert_firing(alert: str, firing: int) -> None:
    ALERTS_FIRING.set(float(firing), alert)


def register_whatif_request(verdict: str) -> None:
    WHATIF_REQUESTS.inc(verdict)


def register_whatif_dispatch() -> None:
    WHATIF_DISPATCHES.inc()


def observe_whatif_batch(size: int, queue_depth: int) -> None:
    WHATIF_BATCH_SIZE.observe(float(size))
    WHATIF_QUEUE_DEPTH.observe(float(queue_depth))


def observe_whatif_latency(ms: float) -> None:
    WHATIF_LATENCY.observe(ms)


def set_whatif_snapshot_version(version: int) -> None:
    WHATIF_SNAPSHOT_VERSION.set(float(version))


def register_replication_record(kind: str, nbytes: int) -> None:
    REPLICATION_RECORDS.inc(kind)
    if nbytes:
        REPLICATION_BYTES.add(float(nbytes))


def register_replication_applied(kind: str) -> None:
    REPLICATION_APPLIED.inc(kind)


def register_replication_resync() -> None:
    REPLICATION_RESYNCS.inc()


def set_replication_lag(lag: int) -> None:
    REPLICATION_LAG.set(float(lag))


def register_whatif_sweep() -> None:
    WHATIF_SWEEPS.inc()


# optional exact-sample sink for the decision-latency stream: the bench
# needs true p50/p99 over the raw samples, which the 5·2^k histogram
# buckets are far too coarse for — a registered list receives every ms
# value alongside the histogram observation
_decision_sink = None


def set_decision_latency_sink(sink) -> None:
    """Register (or clear, sink=None) a list that receives every raw
    arrival→decision latency sample in ms."""
    global _decision_sink
    _decision_sink = sink


def observe_decision_latencies(ms_values) -> None:
    """Record arrival→decision latencies for one cycle's bind decisions."""
    for ms in ms_values:
        DECISION_LATENCY.observe(ms)
    sink = _decision_sink
    if sink is not None:
        sink.extend(ms_values)


def register_trigger_wake(trigger: str) -> None:
    TRIGGER_WAKES.inc(trigger)


def observe_pipeline_overlap(ms: float) -> None:
    PIPELINE_OVERLAP.observe(ms)


def register_staged_ingest(count: int) -> None:
    if count:
        STAGED_INGEST.add(count)


def set_queue_shares(shares: dict) -> None:
    """Export per-queue {share, entitlement} samples as live gauges — the
    sim runner's longitudinal fairness series surfaced through /metrics
    (and usable by any caller with the same sample shape).  Queues absent
    from the sample are pruned: a deleted queue must not export a phantom
    share forever."""
    live = {(q,) for q in shares}
    for gauge in (QUEUE_SHARE, QUEUE_ENTITLEMENT):
        for stale in [k for k in list(gauge._values) if k not in live]:
            gauge.remove(*stale)
    for queue, s in shares.items():
        QUEUE_SHARE.set(float(s.get("share", 0.0)), queue)
        QUEUE_ENTITLEMENT.set(float(s.get("entitlement", 0.0)), queue)


def render_prometheus() -> str:
    return "\n".join(m.render() for m in METRICS) + "\n"
