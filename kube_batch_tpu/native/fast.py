"""ctypes loader (+ lazy auto-build) for the native resource ops.

Exposes `resource_lib` — a ctypes CDLL with typed signatures, or None when
the library can't be built/loaded. api/resources.py consults it per call;
all semantics have a numpy twin so behavior is identical either way (the
test suite runs both paths — tests/test_native.py)."""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

logger = logging.getLogger("kube_batch_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libresource_ops.so")
_SRC = os.path.join(_DIR, "resource_ops.c")
_FAIL_STAMP = os.path.join(_DIR, ".build-failed")

# raw addresses (int) are passed for speed — a cached arr.ctypes.data beats
# building a POINTER object per call by ~2 us
_D = ctypes.c_void_p


def _build() -> bool:
    """Build via the Makefile (single source of truth for the recipe; its
    tmp-then-mv keeps concurrent builders atomic). A failure stamp keyed on
    the source mtime prevents re-running a broken toolchain every import."""
    src_mtime = str(os.path.getmtime(_SRC))
    try:
        with open(_FAIL_STAMP) as f:
            if f.read() == src_mtime:
                return False  # this exact source already failed to build
    except OSError:
        pass
    try:
        subprocess.run(
            ["make", "-C", _DIR, "libresource_ops.so"],
            check=True,
            capture_output=True,
            timeout=60,
        )
        try:
            os.unlink(_FAIL_STAMP)
        except OSError:
            pass
        return True
    except (OSError, subprocess.SubprocessError) as e:
        logger.debug("native resource_ops build failed (%s); using numpy", e)
        try:
            with open(_FAIL_STAMP, "w") as f:
                f.write(src_mtime)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    if os.environ.get("KB_NO_NATIVE"):  # escape hatch / fallback testing
        return None
    if not os.path.exists(_SO) or (
        os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
    ):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        logger.debug("native resource_ops load failed (%s); using numpy", e)
        return None
    n = ctypes.c_ssize_t  # ptrdiff_t
    lib.kb_add_.argtypes = [_D, _D, n]
    lib.kb_add_.restype = None
    lib.kb_sub_clamped_.argtypes = [_D, _D, n]
    lib.kb_sub_clamped_.restype = None
    lib.kb_less_equal.argtypes = [_D, _D, _D, n]
    lib.kb_less_equal.restype = ctypes.c_int
    lib.kb_less_equal_strict.argtypes = [_D, _D, n]
    lib.kb_less_equal_strict.restype = ctypes.c_int
    lib.kb_set_max_.argtypes = [_D, _D, n]
    lib.kb_set_max_.restype = None
    lib.kb_share.argtypes = [_D, _D, _D, n]
    lib.kb_share.restype = ctypes.c_double
    return lib


resource_lib = _load()
