/* Native fast path for Resource-vector arithmetic.
 *
 * The host replay path (Statement verbs + plugin event handlers) performs
 * thousands of tiny R-dimensional vector ops per scheduling cycle
 * (node_info AddTask/RemoveTask algebra, job/queue aggregate updates —
 * resource_info.go:130-360 in the reference). numpy dispatch overhead
 * (~2-5 us per op, several ops per verb) dominates at that size; these
 * routines do the same arithmetic in one C call over the numpy buffers.
 *
 * Build: `make -C kube_batch_tpu/native` (or the auto-build in fast.py).
 * The Python layer falls back to numpy when the library is unavailable —
 * semantics are identical; tests/test_native.py runs every op on both paths.
 */

#include <stddef.h>

#define KB_API __attribute__((visibility("default")))

/* a += b */
KB_API void kb_add_(double *a, const double *b, ptrdiff_t n) {
    for (ptrdiff_t i = 0; i < n; i++) a[i] += b[i];
}

/* a = max(a - b, 0). Underflow validation (assert semantics,
 * resource_info.go:180-190) happens in the Python caller via kb_less_equal
 * BEFORE mutating, so the pre-mutation state is available for the error. */
KB_API void kb_sub_clamped_(double *a, const double *b, ptrdiff_t n) {
    for (ptrdiff_t i = 0; i < n; i++) {
        double v = a[i] - b[i];
        a[i] = v > 0.0 ? v : 0.0;
    }
}

/* tolerant a <= b (resource_info.go:269-284) */
KB_API int kb_less_equal(const double *a, const double *b,
                         const double *quanta, ptrdiff_t n) {
    for (ptrdiff_t i = 0; i < n; i++) {
        if (!(a[i] <= b[i] || a[i] - b[i] < quanta[i])) return 0;
    }
    return 1;
}

/* strict a <= b in every dim */
KB_API int kb_less_equal_strict(const double *a, const double *b,
                                ptrdiff_t n) {
    for (ptrdiff_t i = 0; i < n; i++)
        if (a[i] > b[i]) return 0;
    return 1;
}

/* a = max(a, b) elementwise (SetMaxResource, resource_info.go:205-221) */
KB_API void kb_set_max_(double *a, const double *b, ptrdiff_t n) {
    for (ptrdiff_t i = 0; i < n; i++)
        if (b[i] > a[i]) a[i] = b[i];
}

/* dominant share: max over masked dims of a[i]/total[i] (helpers.go:28-60).
 * mask is one byte per dim (numpy bool buffer; semantic dims only). */
KB_API double kb_share(const double *a, const double *total,
                       const unsigned char *mask, ptrdiff_t n) {
    double best = 0.0;
    for (ptrdiff_t i = 0; i < n; i++) {
        if (mask[i] && total[i] > 0.0) {
            double r = a[i] / total[i];
            if (r > best) best = r;
        }
    }
    return best;
}
