/* The reference allocate loop's inner per-task pass — predicate every node
 * then score (LeastRequested + BalancedResourceAllocation) and argmax
 * (allocate.go:151-159, scheduler_helper.go:34-129, nodeorder.go:188-227) —
 * at compiled-native speed, single-threaded and 16-way chunked.
 *
 * Purpose: MEASURE the "numpy is a floor" argument in
 * testing/go_baseline.py.  The Go loop runs this pass per task through a
 * 16-worker ParallelizeUntil; compiled C is the speed class of compiled Go,
 * so timing (a) the numpy vector pass, (b) this C pass single-threaded, and
 * (c) this C pass on a persistent 16-thread pool with per-pass barriers
 * (the fork/join chunking workqueue.ParallelizeUntil pays per call) bounds
 * what the reference could achieve — testing/go_pass_bench.py reports all
 * three.
 *
 * Semantics mirror go_baseline.go_loop_allocate's inner pass exactly:
 * epsilon-tolerant fit over all R dims, cpu/mem scoring with capacity
 * clamped to >= 1, first-max argmax.
 */
#include <math.h>
#include <pthread.h>
#include <stdint.h>

typedef struct {
    const double *req, *idle, *alloc, *quanta;
    int64_t N, R;
} pass_args_t;

static void pass_range(const pass_args_t *a, int64_t lo, int64_t hi,
                       double *best_score, int64_t *best_idx) {
    const int64_t R = a->R;
    double best = -1e300;
    int64_t besti = -1;
    for (int64_t n = lo; n < hi; n++) {
        const double *idle = a->idle + n * R;
        int ok = 1;
        for (int64_t r = 0; r < R; r++) {
            if (a->req[r] > idle[r] + a->quanta[r]) { ok = 0; break; }
        }
        if (!ok) continue;
        const double *al = a->alloc + n * R;
        double cap_c = al[0] > 1.0 ? al[0] : 1.0;
        double cap_m = al[1] > 1.0 ? al[1] : 1.0;
        double used_c = al[0] - idle[0] + a->req[0];
        double used_m = al[1] - idle[1] + a->req[1];
        double fr_c = (cap_c - used_c) / cap_c;
        double fr_m = (cap_m - used_m) / cap_m;
        /* associate exactly like the numpy pass (lr and bal as separate
         * terms, then summed) — a different association drifts by ULPs and
         * can flip the argmax between near-tied nodes */
        double lr = (fr_c + fr_m) * 5.0;
        double bal = 10.0 - fabs(fr_c - fr_m) * 10.0;
        double s = lr + bal;
        if (s > best) { best = s; besti = n; }
    }
    *best_score = best;
    *best_idx = besti;
}

int64_t go_pass_single(const double *req, const double *idle,
                       const double *alloc, const double *quanta,
                       int64_t N, int64_t R) {
    pass_args_t a = {req, idle, alloc, quanta, N, R};
    double bs;
    int64_t bi;
    pass_range(&a, 0, N, &bs, &bi);
    return bi;
}

/* ---- persistent worker pool (ParallelizeUntil analog) ---------------- */

#define MAX_THREADS 64

static struct {
    pass_args_t args;
    double best_score[MAX_THREADS];
    int64_t best_idx[MAX_THREADS];
    int nthreads;
    int running;
    volatile int shutdown;
    pthread_barrier_t start, done;
    pthread_t threads[MAX_THREADS];
} P;

static void *pool_worker(void *argp) {
    intptr_t id = (intptr_t)argp;
    for (;;) {
        pthread_barrier_wait(&P.start);
        if (P.shutdown) return 0;
        int64_t per = (P.args.N + P.nthreads - 1) / P.nthreads;
        int64_t lo = id * per;
        int64_t hi = lo + per < P.args.N ? lo + per : P.args.N;
        if (lo > P.args.N) lo = P.args.N;
        pass_range(&P.args, lo, hi, &P.best_score[id], &P.best_idx[id]);
        pthread_barrier_wait(&P.done);
    }
}

static int pool_poisoned;

int go_pass_pool_init(int nthreads) {
    if (pool_poisoned || P.running || nthreads < 1 || nthreads > MAX_THREADS)
        return -1;
    P.nthreads = nthreads;
    P.shutdown = 0;
    pthread_barrier_init(&P.start, 0, (unsigned)nthreads + 1);
    pthread_barrier_init(&P.done, 0, (unsigned)nthreads + 1);
    for (intptr_t i = 0; i < nthreads; i++) {
        if (pthread_create(&P.threads[i], 0, pool_worker, (void *)i)) {
            /* Partial failure: the start barrier's waiter count is fixed at
             * nthreads+1, so the i parked workers cannot be released (one
             * more main-side wait would still be short of the count) and
             * re-initializing a barrier with waiters is UB.  Poison the
             * pool instead: the parked threads leak — pthread_create only
             * fails on thread exhaustion, an already-degenerate state —
             * and every future init refuses, so the barriers are never
             * touched again.  Callers fall back to the single-thread pass. */
            pool_poisoned = 1;
            return -1;
        }
    }
    P.running = 1;
    return 0;
}

int64_t go_pass_pooled(const double *req, const double *idle,
                       const double *alloc, const double *quanta,
                       int64_t N, int64_t R) {
    if (!P.running) return -2;
    P.args = (pass_args_t){req, idle, alloc, quanta, N, R};
    pthread_barrier_wait(&P.start);  /* release the workers */
    pthread_barrier_wait(&P.done);   /* join the pass */
    double best = -1e300;
    int64_t besti = -1;
    for (int i = 0; i < P.nthreads; i++) {
        /* first-max across ordered chunks == global first-max */
        if (P.best_idx[i] >= 0 && P.best_score[i] > best) {
            best = P.best_score[i];
            besti = P.best_idx[i];
        }
    }
    return besti;
}

void go_pass_pool_shutdown(void) {
    if (!P.running) return;
    P.shutdown = 1;
    pthread_barrier_wait(&P.start);
    for (int i = 0; i < P.nthreads; i++) pthread_join(P.threads[i], 0);
    pthread_barrier_destroy(&P.start);
    pthread_barrier_destroy(&P.done);
    P.running = 0;
}

/* ---- the FULL sequential allocate loop at compiled speed -------------
 * go_baseline.go_loop_allocate's exact control flow (itself mirroring
 * allocate.go:95-200): walk tasks grouped by job, run the per-task pass,
 * place on the argmax node (mutating idle for the next task), commit the
 * gang iff its placement count reaches minAvailable else roll back in
 * reverse.  `use_pool` selects the 16-way chunked pass (the reference's
 * ParallelizeUntil shape; pool must be initialized) over the single-thread
 * pass.  Returns the number of placed tasks; assigned[t] = node or -1. */
int64_t go_loop_run(const double *task_req, const int64_t *task_job,
                    const int64_t *job_min, double *node_idle,
                    const double *node_alloc, const double *quanta,
                    int64_t T, int64_t N, int64_t R, int use_pool,
                    int64_t *assigned, int64_t *scratch /* [T] */) {
    int64_t placed_total = 0;
    for (int64_t t = 0; t < T; t++) assigned[t] = -1;
    int64_t i = 0;
    while (i < T) {
        int64_t j = task_job[i];
        int64_t lo = i;
        while (i < T && task_job[i] == j) i++;
        int64_t nplaced = 0;
        for (int64_t t = lo; t < i; t++) {
            const double *req = task_req + t * R;
            int64_t best;
            if (use_pool) {
                best = go_pass_pooled(req, node_idle, node_alloc, quanta, N, R);
            } else {
                best = go_pass_single(req, node_idle, node_alloc, quanta, N, R);
            }
            if (best < 0) continue;
            double *idle = node_idle + best * R;
            for (int64_t r = 0; r < R; r++) idle[r] -= req[r];
            scratch[nplaced] = t;
            assigned[t] = best;
            nplaced++;
        }
        if (nplaced >= job_min[j]) {
            placed_total += nplaced;
        } else {
            for (int64_t k = nplaced - 1; k >= 0; k--) {  /* reverse rollback */
                int64_t t = scratch[k];
                const double *req = task_req + t * R;
                double *idle = node_idle + assigned[t] * R;
                for (int64_t r = 0; r < R; r++) idle[r] += req[r];
                assigned[t] = -1;
            }
        }
    }
    return placed_total;
}
