"""Native (C) fast paths for the host runtime.

The device compute path is JAX/XLA; the host runtime around it keeps its hot
inner loops native, like the reference keeps its whole runtime in compiled Go.
Currently: resource-vector arithmetic (fast.py), used by api.resources when
the shared library is present (auto-built on first import when a C compiler
is available; silent numpy fallback otherwise)."""

from kube_batch_tpu.native.fast import resource_lib

__all__ = ["resource_lib"]
