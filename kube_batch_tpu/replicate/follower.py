"""The follower side of the replication stream.

A follower process pulls ``GET /v1/replicate?since=<applied>`` frames
from the leader over :class:`~kube_batch_tpu.k8s.transport.ApiTransport`
(the same retry/breaker machinery every apiserver call rides), applies
them to its own host snapshot copy, refreshes its own device-resident
per-cycle cache with the SAME scatter discipline the leader uses
(api/resident.py — the wire rows ARE the scatter rows), and publishes a
SnapshotLease into its own serve/ stack.  The full query plane — lease
broker, micro-batcher, probe kernel — then answers ``/v1/whatif`` (and
``/v1/whatif/sweep``) byte-identically to the leader for the same
applied state.

Chain discipline mirrors WarmTableState's escalate-to-cold: a delta
whose ``prev_seq``/``prev_version`` does not name exactly the applied
state is REFUSED, counted as a gap, and the next pull forces
``since=-1`` — the leader answers with a synthesized full snapshot.  A
full frame re-adopts WARM: each field is diffed in place against the
copy already held, so unchanged device buffers (and the resident
cache's compiled scatter specializations) survive the resync — the
follower-side analog of ``ColumnStore.revalidate_resident``.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from kube_batch_tpu import metrics
from kube_batch_tpu.replicate import stream

logger = logging.getLogger("kube_batch_tpu")

#: idle poll cadence when the leader answers heartbeats
POLL_S_DEFAULT = 0.02


def _poll_s() -> float:
    from kube_batch_tpu.serve.batcher import _env_float

    return _env_float("KB_REPL_POLL_S", POLL_S_DEFAULT)


class FollowerColumns:
    """Just enough ColumnStore surface for QueryPlane to attach: the
    plane installs its broker's swap guard here, and the applier runs
    its resident swaps inside that guard — the same exclusion contract
    the leader's per_cycle_resident honors."""

    def __init__(self) -> None:
        self.resident_swap_guard = None


class FollowerCache:
    """The read-only cache shim a follower process serves from: enough
    SchedulerCache surface for ``make_handler`` + QueryPlane + the
    observability accessors (tracer_of/guard_of/alerts_of attach to any
    object), with every ingest mutator rejecting — cluster state enters
    a follower ONLY through the replication stream."""

    _INGEST = (
        "update_pod", "delete_pod", "add_node", "delete_node",
        "add_pod_group", "delete_pod_group", "add_queue", "delete_queue",
        "add_priority_class", "delete_priority_class", "add_pdb",
        "delete_pdb",
    )

    def __init__(self, spec=None) -> None:
        from kube_batch_tpu.api.resources import ResourceSpec

        # replaced by the wire spec on the first applied record; the
        # default only parses requests until a lease exists (which the
        # batcher answers 503 anyway)
        self.spec = spec if spec is not None else ResourceSpec()
        self.columns = FollowerColumns()
        self._lock = threading.Lock()
        self.queues: dict = {}
        self.jobs: dict = {}
        self.volume_binder = None
        self.query_plane = None
        for name in self._INGEST:
            setattr(self, name, self._read_only)

    def _read_only(self, *_a, **_k):
        raise ValueError(
            "follower is a read-only replica; ingest on the leader")

    def ingest_batch(self, ops):
        self._read_only()

    def mark_synced(self) -> None:
        pass


class FollowerApplier:
    """Applies decoded replication records: host-array scatter/full
    apply, meta-table patching, device residency, lease publish."""

    def __init__(self, cache: FollowerCache, query_plane, tracer=None) -> None:
        from kube_batch_tpu.api.resident import PerCycleDeviceCache

        self.cache = cache
        self.qp = query_plane
        self.tracer = tracer
        self.fields: Dict[str, np.ndarray] = {}
        self.tables: Optional[dict] = None
        self.applied_seq = 0
        self.applied_version = 0
        self.head_seq = 0
        self.head_version = 0
        self.resident = PerCycleDeviceCache()
        self._static_dev: Dict[str, Tuple[int, object]] = {}
        self._stamp: Dict[str, int] = {}
        self._spec_cache: Tuple[tuple, object] = ((), None)
        # diagnostics (tests/smoke evidence)
        self.applied_records = 0
        self.heartbeats = 0
        self.gaps = 0
        self.full_adoptions = 0
        query_plane.head_fn = self.head

    def head(self) -> Tuple[int, int]:
        """The leader head as of the last fetched frame — the staleness
        bound every verdict this plane serves carries."""
        return (self.head_seq, self.head_version)

    # ---- record application ---------------------------------------------
    def apply(self, frame: bytes) -> str:
        """Consume one wire frame; returns ``"applied"``, ``"heartbeat"``
        or ``"resync"`` (the caller's next pull must force a full)."""
        rec = stream.decode_record(frame)
        self.head_seq = max(self.head_seq, rec.head_seq)
        self.head_version = max(self.head_version, rec.head_version)
        metrics.set_replication_lag(max(0, self.head_seq - self.applied_seq))
        if rec.kind == stream.HEARTBEAT:
            self.heartbeats += 1
            return "heartbeat"
        try:
            if rec.kind == stream.DELTA:
                if (not self.fields
                        or rec.prev_seq != self.applied_seq
                        or rec.prev_version != self.applied_version):
                    # the WarmTableState escalation analog: a chain gap
                    # (missed record, version skip, reconnect) demotes to
                    # a full-snapshot resync instead of guessing
                    self.gaps += 1
                    metrics.register_replication_resync()
                    return "resync"
                self._apply_delta(rec)
            else:
                self._adopt_full(rec)
        except (KeyError, IndexError, ValueError) as e:
            logger.warning("replication apply failed (%s); forcing resync", e)
            self.gaps += 1
            metrics.register_replication_resync()
            return "resync"
        self.applied_seq = rec.seq
        self.applied_version = rec.version
        self.applied_records += 1
        self._publish(rec)
        metrics.register_replication_applied(rec.kind)
        metrics.set_replication_lag(max(0, self.head_seq - self.applied_seq))
        return "applied"

    def _bump(self, field: str) -> None:
        self._stamp[field] = self._stamp.get(field, 0) + 1

    def _apply_delta(self, rec) -> None:
        for field, arr in rec.full.items():
            self.fields[field] = arr
            self._bump(field)
        for field, (rows, vals) in rec.delta.items():
            tgt = self.fields[field]
            if rows.size and (rows.min() < 0 or rows.max() >= tgt.shape[0]):
                raise ValueError(f"delta rows out of range for {field}")
            tgt[rows] = vals
            self._bump(field)
        self.tables = stream.apply_meta_patch(self.tables, rec.meta)

    def _adopt_full(self, rec) -> None:
        """Warm re-adoption: diff each incoming full field against the
        copy already held so unchanged fields keep their stamps (and the
        resident cache keeps their device buffers) — the follower-side
        revalidate_resident."""
        from kube_batch_tpu.api.resident import changed_rows
        from kube_batch_tpu.api.snapshot import DeviceSnapshot

        missing = [f for f in DeviceSnapshot._fields if f not in rec.full]
        if missing:
            raise ValueError(f"full record missing fields {missing[:3]}")
        for field, arr in rec.full.items():
            cur = self.fields.get(field)
            if (cur is None or cur.shape != arr.shape
                    or cur.dtype != arr.dtype):
                self.fields[field] = arr
                self._bump(field)
                continue
            rows = changed_rows(cur, arr)
            if rows.size:
                cur[rows] = arr[rows]
                self._bump(field)
        self.tables = rec.meta
        self.full_adoptions += 1

    # ---- residency + lease publish --------------------------------------
    def _spec_for(self, lease_wire):
        from kube_batch_tpu.api.resources import ResourceSpec

        names = tuple(lease_wire.get("scalar_names", ()))
        cached_names, cached = self._spec_cache
        if cached is None or cached_names != names:
            cached = ResourceSpec(names)
            self._spec_cache = (names, cached)
        return cached

    def _publish(self, rec) -> None:
        import jax

        from kube_batch_tpu.api.resident import PER_CYCLE_FIELDS
        from kube_batch_tpu.api.snapshot import DeviceSnapshot
        from kube_batch_tpu.serve.lease import SnapshotLease

        spec = self._spec_for(rec.lease)
        meta = stream.build_snapshot_meta(self.tables, spec)
        config = stream.config_from_wire(rec.lease["config"])
        evict_config = stream.config_from_wire(rec.lease["evict_config"])
        host_snap = DeviceSnapshot(
            **{f: self.fields[f] for f in DeviceSnapshot._fields})
        span = (self.tracer.span("replicate_apply", seq=rec.seq,
                                 kind=rec.kind)
                if self.tracer is not None else None)
        with self.qp.broker.swap_guard():
            if span is not None:
                span.__enter__()
            try:
                dev_snap = self.resident.swap(host_snap)
                updates = {}
                for field in DeviceSnapshot._fields:
                    if field in PER_CYCLE_FIELDS:
                        continue
                    stamp = self._stamp.get(field, 0)
                    cached = self._static_dev.get(field)
                    if cached is None or cached[0] != stamp:
                        cached = (stamp, jax.device_put(self.fields[field]))
                        self._static_dev[field] = cached
                    updates[field] = cached[1]
                dev_snap = dev_snap._replace(**updates)
            finally:
                if span is not None:
                    span.__exit__(None, None, None)
        lease = SnapshotLease(
            snap=dev_snap, meta=meta, version=rec.version, config=config,
            evict_config=evict_config, mesh=None,
            probe_rows=tuple(int(r) for r in rec.lease["probe_rows"]),
            queue_rows={k: int(v)
                        for k, v in rec.lease["queue_rows"].items()},
            unmodeled_gates=tuple(rec.lease["unmodeled_gates"]),
            seq=rec.seq,
        )
        self.cache.spec = spec
        self.qp.broker.publish(lease)
        metrics.set_whatif_snapshot_version(rec.version)

    def revalidate_resident(self) -> dict:
        """Re-adoption check after a pull-loop restart — the
        ColumnStore.revalidate_resident contract: a resident cache that
        has synced at least one snapshot is KEPT (buffers + compiled
        scatter specializations survive; the next swap absorbs residual
        divergence as ordinary deltas), anything else drops to cold."""
        from kube_batch_tpu.api.resident import PerCycleDeviceCache

        if self.resident.version > 0 and self.fields:
            return {"mode": "warm",
                    "resident_version": self.resident.version}
        self.resident = PerCycleDeviceCache()
        self._static_dev.clear()
        return {"mode": "cold", "resident_version": 0}


class ReplicationFollower:
    """The pull loop: transport + applier + the follower's query plane.
    ``start()`` runs it on a daemon thread; tests drive :meth:`run_once`
    synchronously."""

    def __init__(self, leader_url: str, cache: Optional[FollowerCache] = None,
                 query_plane=None, poll_s: Optional[float] = None,
                 transport=None, tracer=None, timeout: float = 30.0) -> None:
        from kube_batch_tpu.k8s.transport import ApiTransport

        self.cache = cache if cache is not None else FollowerCache()
        if query_plane is None:
            from kube_batch_tpu.serve.plane import QueryPlane

            query_plane = QueryPlane(self.cache)
        self.qp = query_plane
        self.applier = FollowerApplier(self.cache, query_plane, tracer=tracer)
        self.transport = transport if transport is not None \
            else ApiTransport(leader_url, role="replicate")
        self.poll_s = _poll_s() if poll_s is None else poll_s
        self.timeout = timeout
        self._force_full = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.pull_errors = 0

    def run_once(self) -> str:
        """One pull + apply; returns the applier outcome (or ``"error"``
        on a transport failure — the loop just polls again; the breaker
        and retry policy inside the transport do the pacing)."""
        since = -1 if self._force_full else self.applier.applied_seq
        try:
            frame = self.transport.get_bytes(
                f"/v1/replicate?since={since}", timeout=self.timeout)
        except Exception as e:  # noqa: BLE001 — transport already classified
            self.pull_errors += 1
            logger.debug("replication pull failed: %s", e)
            return "error"
        outcome = self.applier.apply(frame)
        if outcome == "resync":
            self._force_full = True
        elif outcome == "applied":
            self._force_full = False
        return outcome

    def _loop(self) -> None:
        # on (re)start, decide warm-vs-cold residency exactly once — the
        # warm-standby re-adoption contract
        mode = self.applier.revalidate_resident()
        logger.info("replication follower loop starting (%s residency)",
                    mode["mode"])
        while not self._stop.is_set():
            outcome = self.run_once()
            if outcome in ("heartbeat", "error"):
                # kbt: allow[KBT011] idle poll cadence — caught-up (or
                # disconnected) followers pace their next pull; applied
                # records loop immediately to drain the backlog
                self._stop.wait(self.poll_s)

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="kb-follower-pull")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def join(self) -> None:
        if self._thread is not None:
            self._thread.join()
