"""Replicated follower read plane (ISSUE 16).

The leader publishes each cycle's resident swap as a wire-format
replication record — per-field row/value scatter payloads with the same
full-upload escalation discipline as api/resident.py, plus the
dirty-tracker version token — and follower processes apply the deltas to
their own device-resident snapshot copy and run the full serve/ stack
(lease broker, micro-batcher, probe kernel, prewarm) against it.

- :mod:`.stream`    — the KBR1 frame format: encode/decode, config wire.
- :mod:`.publisher` — the leader side: host mirrors, deferred encode,
  ring buffer, ``record_for(since)`` serving.
- :mod:`.follower`  — the follower side: pull loop over k8s/transport,
  applier (delta apply + resync escalation), FollowerCache shim.
"""

from kube_batch_tpu.replicate.stream import (  # noqa: F401
    ReplicationRecord, decode_record, encode_record,
)
from kube_batch_tpu.replicate.publisher import ReplicationPublisher  # noqa: F401
from kube_batch_tpu.replicate.follower import (  # noqa: F401
    FollowerApplier, FollowerCache, ReplicationFollower,
)
