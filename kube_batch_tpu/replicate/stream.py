"""KBR1 — the replication stream's wire format.

One frame per cycle:

    b"KBR1" | u32 header length (big-endian) | UTF-8 JSON header | payload

The header carries the record identity (seq / version / prev chain / the
leader's head at send time), the decode tables (SnapshotMeta name lists
and bit maps — full on ``kind="full"``, patches on ``kind="delta"``),
the lease extras a follower needs to rebuild a byte-identical
SnapshotLease (config, evict config, probe rows, queue rows, unmodeled
gates, the resource-spec scalar names), and an array directory: for each
payload array its name, dtype, shape and byte offset into the payload.

Array naming mirrors the resident cache's scatter discipline
(api/resident.py): a field arrives either FULL (``f:<field>``) or as a
row-exact scatter pair (``d:<field>:rows`` int32 + ``d:<field>:vals``);
a clean field is simply absent.  A delta frame whose payload would reach
the full array's bytes is escalated to full by the publisher — the same
break-even the device scatter path uses.

Record kinds:

- ``"full"``      — every field full, full decode tables.  Sent for the
  first cycle, and synthesized from the leader's mirrors for any
  follower whose ``since`` token falls off the ring (the resync path).
- ``"delta"``     — changed rows only, table patches; ``prev_seq`` /
  ``prev_version`` name the exact predecessor state it applies to.
- ``"heartbeat"`` — no payload; carries the leader head so an idle
  follower still reports fresh staleness.

Configs cross the wire as tagged NamedTuple dicts via a closed registry
(AllocateConfig / EvictConfig / ScoreWeights) — ``ScoreWeights.extra_rows``
holds host callables and is forced empty by the publisher before encode.
This module is jax-free: framing is pure numpy + json.
"""

from __future__ import annotations

import json
from typing import Dict, List, NamedTuple, Tuple

import numpy as np

MAGIC = b"KBR1"

#: record kinds (header ``kind`` field)
FULL, DELTA, HEARTBEAT = "full", "delta", "heartbeat"


class ReplicationRecord(NamedTuple):
    """One decoded frame — the publisher builds these, the follower
    applies them."""

    kind: str           # "full" | "delta" | "heartbeat"
    seq: int            # this record's cycle sequence number
    version: int        # dirty-tracker version token at this cycle
    prev_seq: int       # delta chain predecessor (-1 for full/heartbeat)
    prev_version: int
    head_seq: int       # leader head at send time (staleness source)
    head_version: int
    full: Dict[str, np.ndarray]                       # field → full array
    delta: Dict[str, Tuple[np.ndarray, np.ndarray]]   # field → (rows, vals)
    meta: dict          # decode tables (full) or table patches (delta)
    lease: dict         # config/evict/probe_rows/queue_rows/gates/spec


# ---- config wire ---------------------------------------------------------

def _config_registry():
    """The closed set of NamedTuple config types that may cross the wire.
    Imported lazily — the registry members pull in jax-adjacent modules."""
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.ops.eviction import EvictConfig
    from kube_batch_tpu.ops.scoring import ScoreWeights

    return {t.__name__: t for t in (AllocateConfig, EvictConfig, ScoreWeights)}


def config_to_wire(cfg):
    """Tagged-dict encoding of a registered config NamedTuple (recursing
    into nested registered members); scalars pass through."""
    reg = _config_registry()
    if type(cfg).__name__ in reg and isinstance(cfg, tuple):
        fields = {}
        for name, val in zip(cfg._fields, cfg):
            fields[name] = config_to_wire(val)
        return {"__cfg__": type(cfg).__name__, "fields": fields}
    if isinstance(cfg, tuple):
        return {"__tuple__": [config_to_wire(v) for v in cfg]}
    if isinstance(cfg, (bool, int, float, str)) or cfg is None:
        return cfg
    raise TypeError(f"config value {cfg!r} is not wire-serializable")


def config_from_wire(obj):
    """Inverse of :func:`config_to_wire`."""
    if isinstance(obj, dict) and "__cfg__" in obj:
        cls = _config_registry()[obj["__cfg__"]]
        kwargs = {k: config_from_wire(v) for k, v in obj["fields"].items()}
        return cls(**kwargs)
    if isinstance(obj, dict) and "__tuple__" in obj:
        return tuple(config_from_wire(v) for v in obj["__tuple__"])
    return obj


# ---- frame encode / decode ----------------------------------------------

def encode_record(rec: ReplicationRecord) -> bytes:
    """Serialize a record to one KBR1 frame."""
    arrays: List[dict] = []
    buffers: List[bytes] = []
    offset = 0

    def add(name: str, arr: np.ndarray) -> None:
        nonlocal offset
        a = np.ascontiguousarray(arr)
        buf = a.tobytes()
        arrays.append({"name": name, "dtype": a.dtype.str,
                       "shape": list(a.shape), "offset": offset,
                       "nbytes": len(buf)})
        buffers.append(buf)
        offset += len(buf)

    for field in sorted(rec.full):
        add(f"f:{field}", rec.full[field])
    for field in sorted(rec.delta):
        rows, vals = rec.delta[field]
        add(f"d:{field}:rows", np.asarray(rows, np.int32))
        add(f"d:{field}:vals", vals)

    header = {
        "kind": rec.kind, "seq": rec.seq, "version": rec.version,
        "prev_seq": rec.prev_seq, "prev_version": rec.prev_version,
        "head_seq": rec.head_seq, "head_version": rec.head_version,
        "meta": rec.meta, "lease": rec.lease, "arrays": arrays,
    }
    hbytes = json.dumps(header, separators=(",", ":")).encode()
    return b"".join([MAGIC, len(hbytes).to_bytes(4, "big"), hbytes, *buffers])


def decode_record(buf: bytes) -> ReplicationRecord:
    """Parse one KBR1 frame.  Decoded arrays are fresh writable copies —
    the follower applies scatters in place on the full-field arrays it
    adopted, so views into the network buffer would be a trap."""
    if len(buf) < 8 or buf[:4] != MAGIC:
        raise ValueError("not a KBR1 replication frame")
    hlen = int.from_bytes(buf[4:8], "big")
    if len(buf) < 8 + hlen:
        raise ValueError("truncated KBR1 header")
    header = json.loads(buf[8:8 + hlen].decode())
    payload = buf[8 + hlen:]

    decoded: Dict[str, np.ndarray] = {}
    for ent in header["arrays"]:
        start, n = ent["offset"], ent["nbytes"]
        if start + n > len(payload):
            raise ValueError(f"truncated KBR1 payload at {ent['name']}")
        arr = np.frombuffer(payload[start:start + n],
                            dtype=np.dtype(ent["dtype"]))
        decoded[ent["name"]] = arr.reshape(ent["shape"]).copy()

    full: Dict[str, np.ndarray] = {}
    delta: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    for name, arr in decoded.items():
        if name.startswith("f:"):
            full[name[2:]] = arr
        elif name.startswith("d:") and name.endswith(":rows"):
            field = name[2:-5]
            delta[field] = (arr, decoded[f"d:{field}:vals"])

    return ReplicationRecord(
        kind=header["kind"], seq=header["seq"], version=header["version"],
        prev_seq=header["prev_seq"], prev_version=header["prev_version"],
        head_seq=header["head_seq"], head_version=header["head_version"],
        full=full, delta=delta, meta=header["meta"], lease=header["lease"],
    )


# ---- meta tables ---------------------------------------------------------

_NAME_LISTS = ("task_keys", "node_names", "job_uids", "queue_names")


def meta_tables(meta) -> dict:
    """SnapshotMeta → the JSON-clean decode tables a follower needs to
    rebuild it (object references and host-side caches excluded)."""
    return {
        "task_keys": list(meta.task_keys),
        "node_names": list(meta.node_names),
        "job_uids": list(meta.job_uids),
        "queue_names": list(meta.queue_names),
        "label_pair_bit": [[k, v, b] for (k, v), b
                           in sorted(meta.label_pair_bit.items())],
        "taint_bit": [[k, v, e, b] for (k, v, e), b
                      in sorted(meta.taint_bit.items())],
        "counts": [meta.n_tasks, meta.n_nodes, meta.n_jobs, meta.n_queues],
    }


def meta_patch(prev: dict, cur: dict) -> dict:
    """The delta-record table patch taking ``prev`` tables to ``cur``:
    name lists ship only their changed entries (+ the new length); the
    bit maps ship whole whenever they changed at all — bit REUSE after a
    churn-out would silently corrupt selector decoding otherwise, and
    the maps are small."""
    patch: dict = {"counts": cur["counts"]}
    for key in _NAME_LISTS:
        p, c = prev[key], cur[key]
        changed = {str(i): v for i, v in enumerate(c)
                   if i >= len(p) or p[i] != v}
        patch[key] = {"len": len(c), "set": changed}
    for key in ("label_pair_bit", "taint_bit"):
        if prev[key] != cur[key]:
            patch[key] = cur[key]
    return patch


def apply_meta_patch(tables: dict, patch: dict) -> dict:
    """Apply a :func:`meta_patch` to a follower's current tables."""
    out = dict(tables)
    out["counts"] = patch["counts"]
    for key in _NAME_LISTS:
        ent = patch[key]
        lst = list(out[key])[:ent["len"]]
        lst.extend([""] * (ent["len"] - len(lst)))
        for i, v in ent["set"].items():
            lst[int(i)] = v
        out[key] = lst
    for key in ("label_pair_bit", "taint_bit"):
        if key in patch:
            out[key] = patch[key]
    return out


def build_snapshot_meta(tables: dict, spec):
    """Follower-side SnapshotMeta from wire tables: decode tables only —
    the host object references (task_objs/job_objs/node_objs) and the
    64-bit host shadows stay empty, which is exactly the subset the
    probe/decode path consumes."""
    from kube_batch_tpu.api.snapshot import SnapshotMeta

    n_tasks, n_nodes, n_jobs, n_queues = tables["counts"]
    return SnapshotMeta(
        spec=spec,
        task_keys=list(tables["task_keys"]),
        node_names=list(tables["node_names"]),
        job_uids=list(tables["job_uids"]),
        queue_names=list(tables["queue_names"]),
        label_pair_bit={(k, v): b for k, v, b in tables["label_pair_bit"]},
        taint_bit={(k, v, e): b for k, v, e, b in tables["taint_bit"]},
        n_tasks=n_tasks, n_nodes=n_nodes, n_jobs=n_jobs, n_queues=n_queues,
    )
