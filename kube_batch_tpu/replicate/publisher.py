"""The leader side of the replication stream.

:class:`ReplicationPublisher` hangs off the scheduler cache
(``cache.replication``); :meth:`QueryPlane.publish_session` calls
:meth:`publish_cycle` right after the resident swap, BEFORE the broker
publish, so the lease it installs carries the record's sequence number.

The call is two-phase, overlapped exactly like the scheduler's staged
writeback: the cycle thread only allocates the sequence number, captures
the host array references (the cycle never mutates a captured snapshot)
and joins the PREVIOUS cycle's encode; the diff + frame encode runs on a
one-worker executor while the next cycle solves.  ``drain_pipeline``
joins the in-flight encode through :meth:`barrier`.

The publisher keeps its own host mirrors of ALL snapshot fields (not
just the device cache's per-cycle set) and diffs them with the SAME
:func:`~kube_batch_tpu.api.resident.changed_rows` the scatter refresh
uses — so the wire deltas are row-exact and independent of
KB_DEVICE_CACHE / mesh choice.  For the per-cycle fields it trusts the
resident swap's own delta record as a fast path whenever the dirty
tracker advanced by exactly one (``ColumnStore.export_delta_record``);
any other cadence falls back to the self-diff.  The mirrors double as
the source for synthesized full-snapshot resync frames when a
follower's ``since`` token falls off the ring.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional, Tuple

import numpy as np

from kube_batch_tpu import metrics
from kube_batch_tpu.envutil import env_int
from kube_batch_tpu.replicate import stream

logger = logging.getLogger("kube_batch_tpu")

#: encoded frames retained for delta serving; a follower further behind
#: than the ring gets a synthesized full-snapshot frame instead
RING_SIZE = env_int("KB_REPL_RING", 64)


def _lease_wire(lease) -> dict:
    """The SnapshotLease extras a follower cannot derive from the arrays:
    configs, probe rows, queue rows, unmodeled gates, the resource axis."""
    config = lease.config
    evict = lease.evict_config
    weights = config.weights
    if weights.extra_rows:
        # host callables cannot cross the wire; publish_session already
        # strips them for its own lease, so this is belt-and-braces
        config = config._replace(weights=weights._replace(extra_rows=()))
    if evict.weights.extra_rows:
        evict = evict._replace(
            weights=evict.weights._replace(extra_rows=()))
    return {
        "config": stream.config_to_wire(config),
        "evict_config": stream.config_to_wire(evict),
        "probe_rows": [int(r) for r in lease.probe_rows],
        "queue_rows": {k: int(v) for k, v in lease.queue_rows.items()},
        "unmodeled_gates": list(lease.unmodeled_gates),
        "scalar_names": list(lease.meta.spec.names[3:]),
    }


class ReplicationPublisher:
    def __init__(self, ring_size: Optional[int] = None, tracer=None) -> None:
        self.ring_size = RING_SIZE if ring_size is None else ring_size
        self.tracer = tracer
        self._lock = threading.RLock()
        self._mirror: Dict[str, np.ndarray] = {}
        self._meta_tables: Optional[dict] = None
        self._lease_wire: Optional[dict] = None
        self._ring: deque = deque()     # (seq, frame bytes)
        self._full_cache: Optional[Tuple[int, bytes]] = None
        self._next_seq = 0              # allocated on the cycle thread
        self._head_seq = 0              # advanced when the encode lands
        self._head_version = 0
        self._last_cache_version = 0    # dirty-tracker token at last publish
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kb-replicate")
        self._pending: Optional[Future] = None
        self._closed = False
        # diagnostics (smoke/bench evidence)
        self.records = {stream.FULL: 0, stream.DELTA: 0}
        self.heartbeats = 0
        self.bytes_published = 0
        self.hint_fields = 0            # per-cycle fields served by the
        self.diff_fields = 0            # resident delta record vs self-diff
        self.encode_errors = 0

    # ---- cycle thread ----------------------------------------------------
    def publish_cycle(self, snap, meta, lease, delta_hint=None,
                      cache_version: int = 0) -> int:
        """Allocate and return this cycle's record seq; the diff + encode
        is deferred to the worker (joined by the NEXT publish_cycle or by
        :meth:`barrier`).  ``delta_hint`` is the resident swap's own delta
        record (field → rows | None-for-full) with its version token."""
        self.barrier()
        with self._lock:
            if self._closed:
                return self._head_seq
            self._next_seq += 1
            seq = self._next_seq
            hint_ok = (
                delta_hint is not None
                and bool(self._mirror)
                and cache_version == self._last_cache_version + 1
            )
            self._last_cache_version = cache_version
        fields = {f: np.asarray(getattr(snap, f))
                  for f in type(snap)._fields}
        tables = stream.meta_tables(meta)
        lease_wire = _lease_wire(lease)
        version = int(lease.version)
        hint = dict(delta_hint) if hint_ok else None
        self._pending = self._pool.submit(
            self._encode_cycle, seq, version, fields, tables, lease_wire,
            hint)
        return seq

    def barrier(self) -> None:
        """Join the in-flight encode (the scheduler's drain hook — the
        replication analog of awaiting the staged writeback)."""
        fut, self._pending = self._pending, None
        if fut is not None:
            fut.result()

    def close(self) -> None:
        self.barrier()
        with self._lock:
            self._closed = True
        self._pool.shutdown(wait=True)

    def invalidate(self) -> None:
        """Drop the mirrors — the next record is a full snapshot (the
        guard plane's demotion hook: state the leader no longer trusts
        must not keep feeding deltas)."""
        with self._lock:
            self._mirror.clear()
            self._meta_tables = None
            self._full_cache = None

    # ---- worker ----------------------------------------------------------
    def _encode_cycle(self, seq, version, fields, tables, lease_wire, hint):
        try:
            span = (self.tracer.span("replicate_encode", seq=seq)
                    if self.tracer is not None else None)
            if span is not None:
                with span:
                    self._encode_locked(seq, version, fields, tables,
                                        lease_wire, hint)
            else:
                self._encode_locked(seq, version, fields, tables,
                                    lease_wire, hint)
        except Exception:
            # a half-updated mirror must never feed another delta — drop
            # everything so the next record is a clean full snapshot
            with self._lock:
                self.encode_errors += 1
            logger.exception("replication encode failed; next record full")
            self.invalidate()

    def _encode_locked(self, seq, version, fields, tables, lease_wire, hint):
        from kube_batch_tpu.api.resident import PerCycleDeviceCache, changed_rows

        with self._lock:
            cold = not self._mirror
            full: Dict[str, np.ndarray] = {}
            delta: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
            for field, host in fields.items():
                mirror = self._mirror.get(field)
                if (mirror is None or mirror.shape != host.shape
                        or mirror.dtype != host.dtype):
                    full[field] = host
                    self._mirror[field] = host.copy()
                    continue
                if hint is not None and field in stream_per_cycle():
                    if field not in hint:
                        self.hint_fields += 1
                        continue  # the swap proved this field clean
                    rows = hint[field]
                    if (isinstance(rows, np.ndarray)
                            and (rows.size == 0
                                 or (0 <= rows.min()
                                     and rows.max() < host.shape[0]))):
                        self.hint_fields += 1
                        changed = rows.astype(np.int64, copy=False)
                    else:
                        changed = changed_rows(mirror, host)
                        self.diff_fields += 1
                else:
                    changed = changed_rows(mirror, host)
                    self.diff_fields += 1
                if changed.size == 0:
                    continue
                slots = int(changed.size)
                payload = PerCycleDeviceCache._payload_bytes(slots, host)
                if payload >= host.nbytes:
                    full[field] = host
                    self._mirror[field] = host.copy()
                else:
                    vals = np.ascontiguousarray(host[changed])
                    delta[field] = (changed.astype(np.int32), vals)
                    mirror[changed] = vals
            if cold or self._meta_tables is None:
                kind, meta_out = stream.FULL, tables
            else:
                kind = stream.DELTA
                meta_out = stream.meta_patch(self._meta_tables, tables)
            rec = stream.ReplicationRecord(
                kind=kind, seq=seq, version=version,
                prev_seq=(-1 if kind == stream.FULL else self._head_seq),
                prev_version=(-1 if kind == stream.FULL
                              else self._head_version),
                head_seq=seq, head_version=version,
                full=full, delta=delta, meta=meta_out, lease=lease_wire)
            frame = stream.encode_record(rec)
            self._meta_tables = tables
            self._lease_wire = lease_wire
            self._ring.append((seq, frame))
            while len(self._ring) > self.ring_size:
                self._ring.popleft()
            self._full_cache = None
            self._head_seq = seq
            self._head_version = version
            self.records[kind] += 1
            self.bytes_published += len(frame)
        metrics.register_replication_record(kind, len(frame))

    # ---- serving (HTTP threads) -----------------------------------------
    def record_for(self, since: int) -> bytes:
        """The frame a follower at applied-seq ``since`` should consume
        next: its exact successor delta when the ring still holds it, a
        heartbeat when it is caught up, a synthesized full snapshot
        otherwise (cold start, ring fall-off, or an explicit ``since=-1``
        resync request)."""
        with self._lock:
            head_seq, head_version = self._head_seq, self._head_version
            if head_seq == 0 or since >= head_seq:
                self.heartbeats += 1
                return self._heartbeat(head_seq, head_version)
            if since >= 0:
                for seq, frame in self._ring:
                    if seq == since + 1:
                        return frame
            return self._full_frame(head_seq, head_version)

    def _heartbeat(self, head_seq: int, head_version: int) -> bytes:
        rec = stream.ReplicationRecord(
            kind=stream.HEARTBEAT, seq=head_seq, version=head_version,
            prev_seq=-1, prev_version=-1,
            head_seq=head_seq, head_version=head_version,
            full={}, delta={}, meta={}, lease={})
        return stream.encode_record(rec)

    def _full_frame(self, head_seq: int, head_version: int) -> bytes:
        # caller holds the lock; cache per head so a herd of resyncing
        # followers pays one encode
        if self._full_cache is not None and self._full_cache[0] == head_seq:
            return self._full_cache[1]
        rec = stream.ReplicationRecord(
            kind=stream.FULL, seq=head_seq, version=head_version,
            prev_seq=-1, prev_version=-1,
            head_seq=head_seq, head_version=head_version,
            full=dict(self._mirror), delta={},
            meta=self._meta_tables or {}, lease=self._lease_wire or {})
        frame = stream.encode_record(rec)
        self._full_cache = (head_seq, frame)
        self.records[stream.FULL] += 1
        self.bytes_published += len(frame)
        metrics.register_replication_record(stream.FULL, len(frame))
        return frame

    def counters(self) -> dict:
        with self._lock:
            return {
                "head_seq": self._head_seq,
                "head_version": self._head_version,
                "records_full": self.records[stream.FULL],
                "records_delta": self.records[stream.DELTA],
                "heartbeats": self.heartbeats,
                "bytes_published": self.bytes_published,
                "hint_fields": self.hint_fields,
                "diff_fields": self.diff_fields,
                "encode_errors": self.encode_errors,
                "ring": len(self._ring),
            }


def stream_per_cycle():
    """The device cache's per-cycle field set (lazy import — resident.py
    pulls jitstats)."""
    from kube_batch_tpu.api.resident import PER_CYCLE_FIELDS

    return PER_CYCLE_FIELDS
