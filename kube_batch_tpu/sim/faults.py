"""Fault injection for the simulator: node crash/re-add, binder failure
windows, watch-stream flaps, and eviction-termination delay.

Faults are ordinary `SimEvent`s on the heap; the runner hands the fault
kinds here. Each handler mutates the cluster through the same ingest
surface a real failure would use (delete_node / update_pod / binder
errors), so the scheduler sees faults exactly as it would in production —
then schedules the deterministic fallout (pod losses, node return).
"""

from __future__ import annotations

import dataclasses
from typing import List

from kube_batch_tpu.api.pod import Node
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.sim import events as ev

# resolve-at-apply-time crash target: the node carrying the most resident
# sim pods when the fault fires (ties break by name) — guarantees the crash
# actually displaces work regardless of where the solver placed it
BUSIEST = "@busiest"


def node_crash_script(t: float, node: str = BUSIEST, down_for: float = 10.0,
                      pod_fail_after: float = 1.0) -> List[ev.SimEvent]:
    """Crash `node` at t; its residents are lost pod_fail_after later (the
    node-lifecycle controller's pod GC analog); the node returns at
    t + down_for (re-add is scheduled at apply time, once the target
    resolves)."""
    return [ev.SimEvent(t, ev.NODE_CRASH, {
        "node": node, "down_for": down_for,
        "pod_fail_after": pod_fail_after,
    })]


def bind_fail_script(t: float, count: int) -> List[ev.SimEvent]:
    return [ev.SimEvent(t, ev.BIND_FAIL, {"count": count})]


def watch_flap_script(t: float) -> List[ev.SimEvent]:
    return [ev.SimEvent(t, ev.WATCH_FLAP, {})]


def brownout_script(t: float, duration: float = 8.0) -> List[ev.SimEvent]:
    """Apiserver brownout: every egress call (bind/evict) fails from t to
    t + duration — the circuit breaker opens, the degraded cycle parks
    decisions in the resync queue, and the loop must keep ticking."""
    return [ev.SimEvent(t, ev.BROWNOUT, {"duration": duration})]


def leader_failover_script(t: float) -> List[ev.SimEvent]:
    """Leadership loss mid-run: the warm standby takes over — the cache
    rebuilds from the pod store and revalidates (keeps) the resident
    device cache (cache.failover_recover)."""
    return [ev.SimEvent(t, ev.LEADER_FAILOVER, {})]


def corruption_script(t: float, kind: str) -> List[ev.SimEvent]:
    """Flip a word in a resident DEVICE column at t — the HBM-bit-flip /
    silent-divergence model the guard plane exists to catch.  The host
    columns (the truth) stay intact; only the device copy the solves
    consume is corrupted, and the mirror is left agreeing with the host so
    the scatter-delta diff does NOT silently heal it.  Kinds:

    - ``ledger``: zero a live node's ``node_alloc`` capacity word in the
      static feature cache (node features only re-upload on a node-change
      version bump, so the flip persists) — the sentinel's capacity
      cross-check (idle+used ≤ allocatable) condemns the next solve;
    - ``score``: NaN a live node's ``node_releasing`` ledger word (a
      fit/score input) — the sentinel's all-finite sweep condemns the
      next solve;
    - ``pending``: flip a long-lived RUNNING row's ``task_pending`` on —
      the device would re-bid an already-placed task (a duplicate bind if
      dispatched); the host eligibility-checksum cross-check condemns the
      solve even when a fairness gate blocks the phantom bid."""
    return [ev.SimEvent(t, ev.CORRUPT, {"kind": kind})]


class FaultInjector:
    """Applies fault events against a running simulation. The runner owns
    the clock/heap/trace; this class owns what a fault *means*."""

    def __init__(self, runner):
        self.runner = runner
        self.crashed_nodes = {}   # name -> Node object to re-add
        self.displaced_jobs = set()  # job uids that lost pods to crashes
        self.corruptions_applied = 0  # resident-corrupt faults that landed

    def apply(self, event: ev.SimEvent) -> None:
        handler = {
            ev.NODE_CRASH: self._node_crash,
            ev.NODE_READD: self._node_readd,
            ev.BIND_FAIL: self._bind_fail,
            ev.WATCH_FLAP: self._watch_flap,
            ev.BROWNOUT: self._brownout,
            ev.BROWNOUT_END: self._brownout_end,
            ev.LEADER_FAILOVER: self._leader_failover,
            ev.CORRUPT: self._corrupt,
        }[event.kind]
        handler(event)

    # ---- handlers --------------------------------------------------------
    def _resolve_node(self, name: str) -> str:
        if name != BUSIEST:
            return name
        counts = {}
        for pod in self.runner.cache.pods.values():
            if pod.node_name:
                counts[pod.node_name] = counts.get(pod.node_name, 0) + 1
        if not counts:  # nothing placed yet — crash the first node
            return next(iter(self.runner.cache.nodes), "")
        return max(counts, key=lambda n: (counts[n], n))

    def _node_crash(self, event: ev.SimEvent) -> None:
        runner = self.runner
        name = self._resolve_node(event.data["node"])
        node_info = runner.cache.nodes.get(name)
        if node_info is None or node_info.node is None:
            return
        # keep the Node spec for the re-add; record resolved target in trace
        self.crashed_nodes[name] = dataclasses.replace(node_info.node)
        residents = sorted(
            pod.key() for pod in runner.cache.pods.values()
            if pod.node_name == name and pod.phase in (PodPhase.PENDING,
                                                       PodPhase.RUNNING)
        )
        runner.trace.record(ev.SimEvent(event.time, ev.NODE_CRASH, {
            "node": name, "residents": residents,
        }))
        runner.cache.delete_node(name)
        t = event.time
        for key in residents:
            job = runner.job_of_pod(key)
            if job is not None:
                self.displaced_jobs.add(job)
            runner.heap.push(ev.SimEvent(
                t + event.data.get("pod_fail_after", 1.0), ev.POD_FAILED,
                {"key": key, "node": name},
            ))
        runner.heap.push(ev.SimEvent(
            t + event.data.get("down_for", 10.0), ev.NODE_READD, {"node": name}
        ))

    def _node_readd(self, event: ev.SimEvent) -> None:
        name = event.data["node"]
        node = self.crashed_nodes.pop(name, None)
        if node is None:
            return
        self.runner.trace.record(event)
        self.runner.cache.add_node(Node(
            name=node.name, allocatable=dict(node.allocatable),
            capacity=dict(node.capacity), labels=dict(node.labels),
            taints=list(node.taints),
        ))

    def _bind_fail(self, event: ev.SimEvent) -> None:
        self.runner.trace.record(event)
        self.runner.kubelet.fail_next_binds(event.data["count"])

    def _brownout(self, event: ev.SimEvent) -> None:
        runner = self.runner
        duration = float(event.data.get("duration", 8.0))
        runner.trace.record(ev.SimEvent(event.time, ev.BROWNOUT,
                                        {"duration": duration}))
        runner.kubelet.set_brownout(True)
        runner.heap.push(ev.SimEvent(event.time + duration,
                                     ev.BROWNOUT_END, {}))

    def _brownout_end(self, event: ev.SimEvent) -> None:
        self.runner.trace.record(event)
        self.runner.kubelet.set_brownout(False)

    def _leader_failover(self, event: ev.SimEvent) -> None:
        """Leadership loss: the warm standby takes over through the real
        recovery path (SchedulerCache.failover_recover — pod-store rebuild
        + resident-cache revalidation), exactly what cmd/server.py's
        run_warm_standby does on LostLeadership."""
        runner = self.runner
        report = runner.failover()
        runner.trace.record(ev.SimEvent(event.time, ev.LEADER_FAILOVER, {
            "mode": report["mode"],
        }))

    def _corrupt(self, event: ev.SimEvent) -> None:
        """Flip a word in a resident DEVICE column (corruption_script) —
        the host columns stay intact, the mirror keeps agreeing with the
        host, so only the device copy the solves consume diverges, exactly
        like an HBM bit-flip.  A cold resident cache (nothing uploaded
        yet) retries one virtual second later."""
        import numpy as np

        runner = self.runner
        kind = event.data["kind"]
        cols = runner.cache.columns

        def retry():
            runner.heap.push(ev.SimEvent(
                event.time + 1.0, ev.CORRUPT, dict(event.data)))

        import jax

        live = np.flatnonzero(np.asarray(cols.n_valid))
        # per-cycle corruptions must diverge device-from-MIRROR the way an
        # HBM flip does: the next swap's diff compares mirror vs host, so
        # the mirror row is pinned to the CURRENT host truth — the diff
        # stays silent and the corrupt device word survives into the solve
        # (a stale mirror row would make the swap scatter-heal it first)
        if kind == "pending":
            # flip a RUNNING row's device pending bit on; detection is the
            # action's HOST pending cross-check when the (full-matrix)
            # solve re-assigns the row
            rc = cols._per_cycle_dev.get(None)
            dev = rc._dev.get("task_pending") if rc is not None else None
            if dev is None:
                return retry()
            from kube_batch_tpu.api.types import TaskStatus

            rows = np.flatnonzero(
                np.asarray(cols.t_status) == int(TaskStatus.RUNNING)
            )
            if rows.size == 0:
                return retry()
            # the flip must OUTLIVE the next few dispatches: a task that
            # completes first frees its row (or drops out of the session),
            # dissolving the corruption into legitimate/inert state before
            # a solve can be condemned by it.  The heap KNOWS every
            # running pod's scheduled completion — pick the row whose
            # POD_SUCCEEDED is furthest out, and require ≥ 5 vt of life
            succeed_at = {
                e.item.data.get("key"): e.item.time
                for e in runner.heap._pq._heap
                if e.item.kind == ev.POD_SUCCEEDED
            }
            best, best_t = -1, event.time + 5.0
            for row in rows.tolist():
                task = cols.task_by_row[row]
                if task is None:
                    continue
                # a KNOWN future completion only: a pod missing from the
                # heap has its success event in THIS instant's due batch —
                # it is about to be deleted, the worst possible target
                t_done = succeed_at.get(task.pod.key())
                if t_done is not None and t_done > best_t:
                    best, best_t = row, t_done
            if best < 0:
                return retry()
            r = best
            host = np.array(jax.device_get(dev))
            host[r] = True
            rc._dev["task_pending"] = jax.device_put(host)
            rc._mirror["task_pending"][r] = False  # host truth: not pending
            field = "task_pending"
        elif kind == "score":
            # NaN a live node's releasing word — a fit/score input; the
            # sentinel's all-finite sweep condemns the next solve.  (Task-
            # axis feature columns re-upload on every arrival's version
            # bump, which would silently heal the corruption before a
            # solve ever saw it — node ledgers only scatter at moved rows)
            rc = cols._per_cycle_dev.get(None)
            dev = rc._dev.get("node_releasing") if rc is not None else None
            if dev is None or live.size == 0:
                return retry()
            r = int(live[0])
            host = np.array(jax.device_get(dev))
            host[r, 0] = np.nan
            rc._dev["node_releasing"] = jax.device_put(host)
            rc._mirror["node_releasing"][r] = np.asarray(cols.n_rel32)[r]
            field = "node_releasing"
        else:
            # static feature column (version-keyed cache): node features
            # only re-upload on a node-change version bump, so a zeroed
            # capacity word persists until the guard's trip-heal drops the
            # cache.  The row must be a LIVE node (the row allocator may
            # start live rows past 0 when the axis was pre-reserved)
            field = "node_alloc"
            feat = cols._dev_cache.get(None, {})
            entry = feat.get(field)
            if entry is None or live.size == 0:
                return retry()
            version, dev = entry
            host = np.array(jax.device_get(dev))
            host[int(live[0])] = 0.0
            feat[field] = (version, jax.device_put(host))
        self.corruptions_applied += 1
        runner.trace.record(ev.SimEvent(event.time, ev.CORRUPT, {
            "kind": kind, "field": field,
        }))

    def _watch_flap(self, event: ev.SimEvent) -> None:
        """Watch reconnect: the stream replays the whole store as MODIFIED
        (StubApiServer's list→watch gap closure) — every pod re-ingests
        through update_pod's upsert path."""
        runner = self.runner
        pods = list(runner.cache.pods.values())
        runner.trace.record(ev.SimEvent(event.time, ev.WATCH_FLAP,
                                        {"replayed": len(pods)}))
        for pod in pods:
            runner.cache.update_pod(dataclasses.replace(pod))
