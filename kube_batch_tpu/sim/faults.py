"""Fault injection for the simulator: node crash/re-add, binder failure
windows, watch-stream flaps, and eviction-termination delay.

Faults are ordinary `SimEvent`s on the heap; the runner hands the fault
kinds here. Each handler mutates the cluster through the same ingest
surface a real failure would use (delete_node / update_pod / binder
errors), so the scheduler sees faults exactly as it would in production —
then schedules the deterministic fallout (pod losses, node return).
"""

from __future__ import annotations

import dataclasses
from typing import List

from kube_batch_tpu.api.pod import Node
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.sim import events as ev

# resolve-at-apply-time crash target: the node carrying the most resident
# sim pods when the fault fires (ties break by name) — guarantees the crash
# actually displaces work regardless of where the solver placed it
BUSIEST = "@busiest"


def node_crash_script(t: float, node: str = BUSIEST, down_for: float = 10.0,
                      pod_fail_after: float = 1.0) -> List[ev.SimEvent]:
    """Crash `node` at t; its residents are lost pod_fail_after later (the
    node-lifecycle controller's pod GC analog); the node returns at
    t + down_for (re-add is scheduled at apply time, once the target
    resolves)."""
    return [ev.SimEvent(t, ev.NODE_CRASH, {
        "node": node, "down_for": down_for,
        "pod_fail_after": pod_fail_after,
    })]


def bind_fail_script(t: float, count: int) -> List[ev.SimEvent]:
    return [ev.SimEvent(t, ev.BIND_FAIL, {"count": count})]


def watch_flap_script(t: float) -> List[ev.SimEvent]:
    return [ev.SimEvent(t, ev.WATCH_FLAP, {})]


def brownout_script(t: float, duration: float = 8.0) -> List[ev.SimEvent]:
    """Apiserver brownout: every egress call (bind/evict) fails from t to
    t + duration — the circuit breaker opens, the degraded cycle parks
    decisions in the resync queue, and the loop must keep ticking."""
    return [ev.SimEvent(t, ev.BROWNOUT, {"duration": duration})]


def leader_failover_script(t: float) -> List[ev.SimEvent]:
    """Leadership loss mid-run: the warm standby takes over — the cache
    rebuilds from the pod store and revalidates (keeps) the resident
    device cache (cache.failover_recover)."""
    return [ev.SimEvent(t, ev.LEADER_FAILOVER, {})]


class FaultInjector:
    """Applies fault events against a running simulation. The runner owns
    the clock/heap/trace; this class owns what a fault *means*."""

    def __init__(self, runner):
        self.runner = runner
        self.crashed_nodes = {}   # name -> Node object to re-add
        self.displaced_jobs = set()  # job uids that lost pods to crashes

    def apply(self, event: ev.SimEvent) -> None:
        handler = {
            ev.NODE_CRASH: self._node_crash,
            ev.NODE_READD: self._node_readd,
            ev.BIND_FAIL: self._bind_fail,
            ev.WATCH_FLAP: self._watch_flap,
            ev.BROWNOUT: self._brownout,
            ev.BROWNOUT_END: self._brownout_end,
            ev.LEADER_FAILOVER: self._leader_failover,
        }[event.kind]
        handler(event)

    # ---- handlers --------------------------------------------------------
    def _resolve_node(self, name: str) -> str:
        if name != BUSIEST:
            return name
        counts = {}
        for pod in self.runner.cache.pods.values():
            if pod.node_name:
                counts[pod.node_name] = counts.get(pod.node_name, 0) + 1
        if not counts:  # nothing placed yet — crash the first node
            return next(iter(self.runner.cache.nodes), "")
        return max(counts, key=lambda n: (counts[n], n))

    def _node_crash(self, event: ev.SimEvent) -> None:
        runner = self.runner
        name = self._resolve_node(event.data["node"])
        node_info = runner.cache.nodes.get(name)
        if node_info is None or node_info.node is None:
            return
        # keep the Node spec for the re-add; record resolved target in trace
        self.crashed_nodes[name] = dataclasses.replace(node_info.node)
        residents = sorted(
            pod.key() for pod in runner.cache.pods.values()
            if pod.node_name == name and pod.phase in (PodPhase.PENDING,
                                                       PodPhase.RUNNING)
        )
        runner.trace.record(ev.SimEvent(event.time, ev.NODE_CRASH, {
            "node": name, "residents": residents,
        }))
        runner.cache.delete_node(name)
        t = event.time
        for key in residents:
            job = runner.job_of_pod(key)
            if job is not None:
                self.displaced_jobs.add(job)
            runner.heap.push(ev.SimEvent(
                t + event.data.get("pod_fail_after", 1.0), ev.POD_FAILED,
                {"key": key, "node": name},
            ))
        runner.heap.push(ev.SimEvent(
            t + event.data.get("down_for", 10.0), ev.NODE_READD, {"node": name}
        ))

    def _node_readd(self, event: ev.SimEvent) -> None:
        name = event.data["node"]
        node = self.crashed_nodes.pop(name, None)
        if node is None:
            return
        self.runner.trace.record(event)
        self.runner.cache.add_node(Node(
            name=node.name, allocatable=dict(node.allocatable),
            capacity=dict(node.capacity), labels=dict(node.labels),
            taints=list(node.taints),
        ))

    def _bind_fail(self, event: ev.SimEvent) -> None:
        self.runner.trace.record(event)
        self.runner.kubelet.fail_next_binds(event.data["count"])

    def _brownout(self, event: ev.SimEvent) -> None:
        runner = self.runner
        duration = float(event.data.get("duration", 8.0))
        runner.trace.record(ev.SimEvent(event.time, ev.BROWNOUT,
                                        {"duration": duration}))
        runner.kubelet.set_brownout(True)
        runner.heap.push(ev.SimEvent(event.time + duration,
                                     ev.BROWNOUT_END, {}))

    def _brownout_end(self, event: ev.SimEvent) -> None:
        self.runner.trace.record(event)
        self.runner.kubelet.set_brownout(False)

    def _leader_failover(self, event: ev.SimEvent) -> None:
        """Leadership loss: the warm standby takes over through the real
        recovery path (SchedulerCache.failover_recover — pod-store rebuild
        + resident-cache revalidation), exactly what cmd/server.py's
        run_warm_standby does on LostLeadership."""
        runner = self.runner
        report = runner.failover()
        runner.trace.record(ev.SimEvent(event.time, ev.LEADER_FAILOVER, {
            "mode": report["mode"],
        }))

    def _watch_flap(self, event: ev.SimEvent) -> None:
        """Watch reconnect: the stream replays the whole store as MODIFIED
        (StubApiServer's list→watch gap closure) — every pod re-ingests
        through update_pod's upsert path."""
        runner = self.runner
        pods = list(runner.cache.pods.values())
        runner.trace.record(ev.SimEvent(event.time, ev.WATCH_FLAP,
                                        {"replayed": len(pods)}))
        for pod in pods:
            runner.cache.update_pod(dataclasses.replace(pod))
