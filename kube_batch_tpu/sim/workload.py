"""Workload generators for the simulator, layered on testing/synthetic.py's
distributions (the BASELINE config matrix's request/gang shapes).

A workload is a list of JOB_ARRIVAL `SimEvent`s whose data fully describes
the job — name, queue, gang minMember, and per-pod requests/durations — so
the SAME event list drives a run whether it came from the Poisson generator
or from a previously recorded trace (`trace_arrivals`). All randomness is
drawn here, before the run starts, from one seeded numpy Generator: the
run itself contains no sampling, which is what makes `--seed` ⇒ identical
trace possible.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from kube_batch_tpu.sim import events as ev
from kube_batch_tpu.testing.synthetic import CPU_CHOICES, GiB

SIM_NS = "sim"

# memory follows the synthetic matrix but narrower, so small sim nodes
# contend on cpu (the interesting axis) rather than stranding on memory
MEM_CHOICES = np.array([1, 2, 4]) * GiB


def poisson_arrivals(
    seed: int,
    n_jobs: int,
    rate: float,
    queues: Sequence[str],
    gang_sizes: Sequence[int] = (1, 2, 4),
    cpu_choices: Sequence[float] = tuple(CPU_CHOICES[:4]),
    mem_choices: Sequence[float] = tuple(MEM_CHOICES),
    duration_range: Tuple[float, float] = (3.0, 12.0),
    start_latency: float = 0.5,
    start_at: float = 0.0,
) -> List[ev.SimEvent]:
    """Poisson job arrivals: exponential inter-arrival at `rate` jobs per
    virtual second; each job is a gang of a sampled size, queue round-robin
    (deterministic per index, like synthetic.py's job_queue), uniform pod
    durations."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate, size=n_jobs)
    times = start_at + np.cumsum(gaps)
    sizes = rng.choice(np.asarray(gang_sizes), size=n_jobs)
    out: List[ev.SimEvent] = []
    for i in range(n_jobs):
        g = int(sizes[i])
        name = f"j{i:04d}"
        tasks = []
        for k in range(g):
            tasks.append({
                "name": f"{name}-{k}",
                "cpu": float(rng.choice(np.asarray(cpu_choices))),
                "mem": float(rng.choice(np.asarray(mem_choices))),
                "duration": round(float(rng.uniform(*duration_range)), 6),
                "start_latency": round(float(start_latency), 6),
            })
        out.append(ev.SimEvent(round(float(times[i]), 6), ev.JOB_ARRIVAL, {
            "name": name,
            "namespace": SIM_NS,
            "queue": queues[i % len(queues)],
            "min_member": g,
            "tasks": tasks,
        }))
    return out


def fixed_gangs(
    t: float,
    n_gangs: int,
    gang_size: int,
    cpu: float,
    mem: float,
    duration: float,
    queues: Sequence[str],
    start_latency: float = 0.5,
    name_prefix: str = "g",
) -> List[ev.SimEvent]:
    """Deterministic homogeneous gangs arriving together — the fault
    presets use these so the displaced workload is exactly known."""
    out: List[ev.SimEvent] = []
    for i in range(n_gangs):
        name = f"{name_prefix}{i:03d}"
        out.append(ev.SimEvent(round(float(t), 6), ev.JOB_ARRIVAL, {
            "name": name,
            "namespace": SIM_NS,
            "queue": queues[i % len(queues)],
            "min_member": gang_size,
            "tasks": [{
                "name": f"{name}-{k}",
                "cpu": float(cpu), "mem": float(mem),
                "duration": round(float(duration), 6),
                "start_latency": round(float(start_latency), 6),
            } for k in range(gang_size)],
        }))
    return out


def trace_arrivals(path: str) -> List[ev.SimEvent]:
    """Trace-driven workload: re-inject the JOB_ARRIVAL events of a
    recorded run (everything else in the trace was derived state and is
    re-derived live)."""
    return [e for e in ev.read_trace(path) if e.kind == ev.JOB_ARRIVAL]
