"""The virtual-time cluster simulator: event-driven workload replay over
the REAL Scheduler/SchedulerCache.

Each virtual cycle: (1) all due events apply to the cache through the
ordinary ingest surface (add_pod/update_pod/add_node — the event-handler
path a live watch stream feeds), (2) the real L1 `Scheduler.run_once()`
executes the configured action pipeline, (3) binder/evictor acks drain
from the simulated kubelet and schedule lifecycle follow-ups on the event
heap, (4) longitudinal metrics sample the cache, (5) the virtual clock
advances one schedule period. No apiserver, no wall-clock waits, no
sampling during the run — same seed, byte-identical trace.

`python -m kube_batch_tpu.sim --seed 7 --preset smoke` is the CLI front.

`--pipelined` switches the loop to the event-driven pacing of the L1
pipeline (PR 9): cycles run at arrival events (floored by the config's
`min_period`, capped by the idle `period`) through the REAL staged cycle
body — run_once_pipelined + the writeback worker, joined per cycle so the
trace stays seed-deterministic.  The report's `pod_bind_latency_vt` is
then the arrival→decision latency the event trigger optimizes; compare
against the serial run of the same preset/seed.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

from kube_batch_tpu import metrics as prom_metrics
from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup, Queue
from kube_batch_tpu.api.types import PodPhase, TaskStatus, is_allocated
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.framework.conf import parse_scheduler_conf
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.sim import events as ev
from kube_batch_tpu.sim import kubelet as kl
from kube_batch_tpu.sim import workload
from kube_batch_tpu.sim.clock import EventHeap, VirtualClock
from kube_batch_tpu.sim.events import SimEvent, TraceRecorder
from kube_batch_tpu.k8s.transport import CircuitBreaker, GuardedBackend
from kube_batch_tpu.sim.faults import (
    BUSIEST,
    FaultInjector,
    bind_fail_script,
    brownout_script,
    corruption_script,
    leader_failover_script,
    node_crash_script,
    watch_flap_script,
)
from kube_batch_tpu.sim.metrics import LongitudinalMetrics
from kube_batch_tpu.testing.synthetic import GiB

SIM_NS = workload.SIM_NS


@dataclasses.dataclass
class SimConfig:
    """One simulation scenario. Everything that shapes the run is here (and
    is echoed into the report) so a config + seed IS the experiment."""

    seed: int = 0
    # cluster
    n_nodes: int = 6
    node_cpu: float = 16000.0
    node_mem: float = 64 * GiB
    node_pods: float = 110.0
    queues: Tuple[Tuple[str, int], ...] = (("q0", 1), ("q1", 2))
    # loop
    cycles: int = 60
    period: float = 1.0
    # None → the SHIPPED 5-action conf (enqueue, reclaim, allocate,
    # backfill, preempt), like the e2e driver — NOT the built-in 2-action
    # fallback: without the enqueue action a job that misses its first
    # cycle is written back PodGroupPending and the allocate gate then
    # skips it forever (allocate.go:50-52 / enqueue.go:66,115)
    conf_text: Optional[str] = None
    # workload (poisson unless `arrivals` is given explicitly)
    n_jobs: int = 16
    arrival_rate: float = 2.0
    gang_sizes: Tuple[int, ...] = (1, 2, 4)
    duration_range: Tuple[float, float] = (3.0, 12.0)
    start_latency: float = 0.5
    arrivals: Optional[List[SimEvent]] = None  # pre-built / trace-driven
    # event-driven pipelined pacing (the L1 loop's CycleTrigger under
    # virtual time): instead of ticking every `period`, the next cycle runs
    # at the earliest pending event, floored by `min_period` (burst
    # coalescing) and capped by `period` (the idle tick).  The cycle BODY is
    # the real pipelined one (staged close + writeback worker), joined per
    # cycle so the trace stays seed-deterministic; what virtual time
    # measures is the TRIGGER policy — arrival→decision latency — while the
    # wall-clock bench measures the overlap gain.
    pipelined: bool = False
    min_period: float = 0.05
    # column-capacity reservation (ColumnStore.reserve) — the corruption
    # preset reserves a task bucket big enough that the KB_TOPK compacted
    # path ENGAGES (capT ≥ 1024 → a 256-row pending bucket), so the
    # guard's demotion/re-promotion machinery has a real fast path to act
    # on at sim scale
    reserve_tasks: int = 0
    reserve_nodes: int = 0
    reserve_jobs: int = 0
    # faults
    faults: Tuple[SimEvent, ...] = ()
    evict_delay: float = 1.0
    # whether an evicted replica is recreated Pending by the job controller
    # (True models a Job/ReplicaSet owner; False mirrors the reference e2e's
    # bare pods, where eviction is deletion — and avoids the re-claim race
    # in which the recreated victim outranks the preemptor forever)
    evict_recreates: bool = False


def preset(name: str, seed: int = 0) -> SimConfig:
    """Named scenarios. `smoke` is the tier-1-sized run; `fault` crashes
    the busiest node under long-running gangs and must end with the
    displaced gangs re-placed; `churn` layers binder failures and a watch
    flap over the smoke workload (repair-path coverage).

    Chaos presets (fault-hardening evidence): `brownout` fails every
    egress call for a window — the breaker opens and the degraded cycle
    must keep ticking; `bind-storm` lands hundreds of gang pods while the
    binder flaps — zero lost/duplicate binds, bounded arrival→bind p99;
    `leader-failover` loses leadership mid-run — the warm standby must
    keep the resident device cache (no recompile/re-upload)."""
    if name == "smoke":
        return SimConfig(seed=seed)
    if name == "fault":
        # 3 gangs of 4×4000m on 4×16000m nodes: ≥3 nodes carry pods, every
        # pod runs for the whole horizon — the busiest node crashing at
        # t=8 displaces at least one full gang member set
        return SimConfig(
            seed=seed,
            n_nodes=4, node_cpu=16000.0,
            queues=(("q0", 1),),
            cycles=40, n_jobs=0,
            arrivals=workload.fixed_gangs(
                t=0.5, n_gangs=3, gang_size=4, cpu=4000.0, mem=2 * GiB,
                duration=200.0, queues=("q0",),
            ),
            faults=tuple(node_crash_script(
                t=8.0, node=BUSIEST, down_for=12.0, pod_fail_after=1.0
            )),
        )
    if name == "churn":
        cfg = SimConfig(seed=seed, cycles=80)
        cfg.faults = (
            *bind_fail_script(3.0, count=3),
            *watch_flap_script(9.0),
        )
        return cfg
    if name == "warm-churn":
        # the KB_WARM A/B scale (ISSUE 14): big enough that the compacted
        # allocate engages (task capacity past the smallest pending-bucket
        # rung, node capacity past the K width) with sustained gang churn
        # so the carried candidate table actually merges across cycles —
        # the --warm-ab leg runs this twice and bit-compares every bind
        cfg = SimConfig(seed=seed, cycles=60, n_nodes=40,
                        n_jobs=350, arrival_rate=10.0,
                        gang_sizes=(4, 6, 8),
                        duration_range=(6.0, 14.0))
        return cfg
    if name == "brownout":
        # apiserver brownout mid-workload: every egress call fails for a
        # window — the breaker must open, the degraded cycle must park
        # decisions and KEEP TICKING, and the workload must still drain
        # after the window (recovery through the resync backoff queue)
        cfg = SimConfig(seed=seed, cycles=90, n_jobs=12, arrival_rate=1.5)
        cfg.faults = tuple(brownout_script(6.0, duration=8.0))
        return cfg
    if name == "bind-storm":
        # hundreds of gang pods arrive in a tight burst while the binder
        # flaps (injected failures + a short brownout): the recovery
        # invariants are zero lost/duplicate binds and a bounded
        # pod-arrival→bind p99 despite the flapping
        # Job-controller semantics (evict_recreates): under storm pressure
        # preempt legitimately evicts singletons to start starving gangs —
        # with bare-pod semantics those victims would be DELETED and the
        # drain invariant (every submitted gang completes) could not hold
        arrivals = workload.poisson_arrivals(
            seed=seed, n_jobs=120, rate=30.0, queues=["q0"],
            gang_sizes=(1, 2, 4), duration_range=(2.0, 6.0),
            start_latency=0.25,
        )
        cfg = SimConfig(
            seed=seed, n_nodes=10, cycles=140, n_jobs=0, arrivals=arrivals,
            queues=(("q0", 1),), evict_recreates=True,
            faults=(
                *bind_fail_script(2.0, count=3),
                *brownout_script(4.0, duration=3.0),
                *bind_fail_script(12.0, count=2),
            ),
        )
        return cfg
    if name == "leader-failover":
        # leadership loss mid-run: the warm standby takes over through
        # cache.failover_recover — pod-store rebuild + resident-cache
        # revalidation — and must keep the device-resident buffers (no
        # full recompile/re-upload) while the workload drains normally
        cfg = SimConfig(seed=seed, cycles=70, n_jobs=14, arrival_rate=1.2)
        cfg.faults = tuple(leader_failover_script(9.0))
        return cfg
    if name == "corruption":
        # result-integrity chaos (the guard plane's acceptance preset):
        # three resident-DEVICE-column corruptions land mid-run — a zeroed
        # capacity word, a NaN score input, a flipped pending bit on a
        # RUNNING row — while the host truth stays intact.  Invariants the
        # CLI enforces: ZERO bad binds dispatched (no duplicate binds, no
        # accounting drift — every condemned solve failed closed),
        # demotion engages on trip, re-promotion recovers after the
        # cooldown, and a diagnostics bundle lands for --replay-bundle.
        # The reserved task bucket makes KB_TOPK engage at sim scale so
        # demotion has a real fast path to act on.
        cfg = SimConfig(
            seed=seed, n_nodes=4, node_cpu=8000.0, queues=(("q0", 1),),
            cycles=60, n_jobs=30, arrival_rate=0.75, gang_sizes=(1, 2),
            duration_range=(6.0, 18.0),
            reserve_tasks=1024, reserve_nodes=64,
        )
        cfg.faults = (
            *corruption_script(3.3, "ledger"),
            *corruption_script(16.3, "score"),
            # deliberately INSIDE the score trip's demotion window: with
            # KB_TOPK demoted the full-matrix program runs, which is the
            # path a flipped pending bit can actually steer into a
            # duplicate bind — the host pending cross-check must catch it
            *corruption_script(19.3, "pending"),
        )
        return cfg
    raise KeyError(
        f"unknown preset {name!r} (smoke | fault | churn | brownout | "
        "bind-storm | leader-failover | corruption)")


class SimRunner:
    def __init__(self, cfg: SimConfig):
        self.cfg = cfg
        self.clock = VirtualClock()
        self.heap = EventHeap()
        self.trace = TraceRecorder()
        self.metrics = LongitudinalMetrics()
        self.kubelet = kl.SimKubelet()
        # the kubelet rides the REAL transport circuit breaker (paced by the
        # virtual clock): a brownout opens it exactly like a production
        # apiserver outage would, and the cache's degraded path parks
        # decisions instead of hammering the failing egress
        self.breaker = CircuitBreaker(
            threshold=3, cooldown=2.5, clock=self.clock.monotonic,
            name="sim-apiserver",
        )
        guard = GuardedBackend(self.kubelet, self.breaker)
        self.cache = SchedulerCache(binder=guard, evictor=guard)
        if cfg.reserve_tasks or cfg.reserve_nodes or cfg.reserve_jobs:
            self.cache.columns.reserve(
                n_tasks=cfg.reserve_tasks, n_nodes=cfg.reserve_nodes,
                n_jobs=cfg.reserve_jobs,
            )
        if cfg.conf_text:
            conf = parse_scheduler_conf(cfg.conf_text)
        else:
            from kube_batch_tpu.framework.conf import (
                load_scheduler_conf, shipped_conf_path)

            conf = load_scheduler_conf(shipped_conf_path())
        # the sim drives run_once() itself, but the injected clock also
        # makes run_forever() pace in virtual time if a caller wants it
        self.scheduler = Scheduler(
            self.cache, conf=conf, schedule_period=cfg.period,
            clock=self.clock,
        )
        self.faults = FaultInjector(self)
        # per-pod lifecycle info: key → {job, duration, start_latency}
        self.pod_info: Dict[str, Dict] = {}
        self.job_tasks: Dict[str, set] = {}      # job uid → pod keys
        self.job_succeeded: Dict[str, set] = {}  # job uid → succeeded keys
        self._creation = itertools.count(1)
        self._reincarnation: Dict[str, int] = {}
        # bind-integrity bookkeeping: when each incarnation went Pending
        # (pod-arrival→bind latency) and which (key, uid) incarnations have
        # already been ack'd (a second ack = a duplicate bind — always a bug)
        self.pending_since: Dict[str, float] = {}
        self.bound_uids: set = set()
        self.duplicate_binds = 0
        self.failover_events: List[Dict] = []
        # KB_TOPK candidate-compaction longitudinal counters
        self.topk_cycles = 0
        self.topk_exhausted = 0
        self.topk_reentries = 0
        self.topk_k = 0
        # warm-carry accumulators (ISSUE 14)
        self.warm_cycles = 0
        self.warm_cold = 0
        self.warm_reranked = 0
        self.warm_changed = 0
        self.warm_live = 0
        # order-exact digest of every acked (pod, node) bind — the
        # KB_WARM A/B leg's decision-equality receipt (same seed + a
        # bit-exact fast path ⇒ identical digest)
        self._bind_hash = hashlib.sha256()

    # ---- shared lookups --------------------------------------------------
    def job_of_pod(self, key: str) -> Optional[str]:
        info = self.pod_info.get(key)
        return info["job"] if info else None

    # ---- leader failover (warm standby) ----------------------------------
    def failover(self) -> Dict:
        """The LEADER_FAILOVER fault's body: the warm standby takes over via
        the real recovery path. Resident counters are snapshotted before,
        so the report can prove the no-recompile/no-re-upload invariant
        (full_uploads flat on the warm path)."""
        before = {p: dict(c)
                  for p, c in self.cache.columns.resident_counters().items()}
        report = self.cache.failover_recover()
        report["t"] = self.clock.now()
        report["resident_before"] = before
        self.failover_events.append(report)
        return report

    # ---- setup -----------------------------------------------------------
    def _setup(self) -> None:
        cfg = self.cfg
        for qname, weight in cfg.queues:
            self.cache.add_queue(Queue(name=qname, uid=f"sim-q-{qname}",
                                       weight=weight))
            self.trace.record(SimEvent(0.0, "queue-add",
                                       {"name": qname, "weight": weight}))
        for i in range(cfg.n_nodes):
            name = f"sim-n{i}"
            self.cache.add_node(Node(
                name=name,
                allocatable={"cpu": cfg.node_cpu, "memory": cfg.node_mem,
                             "pods": cfg.node_pods},
            ))
            self.trace.record(SimEvent(0.0, "node-add", {"name": name}))
        arrivals = cfg.arrivals
        if arrivals is None:
            arrivals = workload.poisson_arrivals(
                seed=cfg.seed, n_jobs=cfg.n_jobs, rate=cfg.arrival_rate,
                queues=[q for q, _ in cfg.queues],
                gang_sizes=cfg.gang_sizes,
                duration_range=cfg.duration_range,
                start_latency=cfg.start_latency,
            )
        self.heap.push_all(arrivals)
        self.heap.push_all(SimEvent(e.time, e.kind, dict(e.data))
                           for e in cfg.faults)

    # ---- event application ----------------------------------------------
    def _apply(self, event: SimEvent) -> None:
        if event.kind in ev.FAULT_KINDS:
            self.faults.apply(event)  # records its own (resolved) trace
            return
        handler = {
            ev.JOB_ARRIVAL: self._on_job_arrival,
            ev.POD_RUNNING: self._on_pod_running,
            ev.POD_SUCCEEDED: self._on_pod_succeeded,
            ev.POD_FAILED: self._on_pod_failed,
            ev.EVICT_TERMINATED: self._on_evict_terminated,
        }[event.kind]
        handler(event)

    def _on_job_arrival(self, event: SimEvent) -> None:
        d = event.data
        job_uid = f"{d['namespace']}/{d['name']}"
        self.cache.add_pod_group(PodGroup(
            name=d["name"], namespace=d["namespace"],
            uid=f"sim-pg-{d['name']}",
            min_member=d["min_member"], queue=d["queue"],
            creation_index=next(self._creation),
        ))
        keys = set()
        for t in d["tasks"]:
            pod = Pod(
                name=t["name"], namespace=d["namespace"],
                uid=f"sim-pod-{t['name']}-r0",
                requests={"cpu": t["cpu"], "memory": t["mem"]},
                annotations={GROUP_NAME_ANNOTATION: d["name"]},
                phase=PodPhase.PENDING,
                priority=int(t.get("priority", 0)),
                creation_index=next(self._creation),
            )
            key = pod.key()
            keys.add(key)
            self.pod_info[key] = {
                "job": job_uid,
                "duration": t["duration"],
                "start_latency": t["start_latency"],
            }
            self.pending_since[key] = event.time
            self.cache.add_pod(pod)
        self.job_tasks[job_uid] = keys
        self.job_succeeded[job_uid] = set()
        self.metrics.note_arrival(job_uid, event.time)
        self.trace.record(event)

    def _stale(self, event: SimEvent) -> bool:
        """Lifecycle events are pinned to a pod INCARNATION by uid: a heap
        event queued for an incarnation that has since been crash-lost or
        evicted and recreated must not fire against its successor (the
        stale first-life POD_SUCCEEDED would complete the rerun early, and
        a stale POD_RUNNING would start the recreated pod on its old,
        possibly still-crashed node)."""
        stored = self.cache.pods.get(event.data["key"])
        return stored is None or stored.uid != event.data["uid"]

    def _on_pod_running(self, event: SimEvent) -> None:
        key = event.data["key"]
        if self._stale(event):
            return  # lost to a crash/eviction while starting
        if not kl.set_running(self.cache, key, event.data["node"]):
            return
        self.trace.record(event)
        info = self.pod_info[key]
        self.heap.push(SimEvent(event.time + info["duration"],
                                ev.POD_SUCCEEDED,
                                {"key": key, "uid": event.data["uid"]}))

    def _on_pod_succeeded(self, event: SimEvent) -> None:
        key = event.data["key"]
        if self._stale(event) or not kl.set_succeeded(self.cache, key):
            return
        self.trace.record(event)
        job = self.job_of_pod(key)
        if job is None:
            return
        done = self.job_succeeded.setdefault(job, set())
        done.add(key)
        if done >= self.job_tasks.get(job, set()):
            self._complete_job(job, event.time)

    def _complete_job(self, job_uid: str, t: float) -> None:
        self.metrics.note_completion(job_uid, t)
        for key in sorted(self.job_tasks.get(job_uid, ())):
            kl.delete_pod(self.cache, key)
        self.cache.delete_pod_group(job_uid)
        self.trace.record(SimEvent(t, ev.JOB_COMPLETE, {"job": job_uid}))

    def _reincarnate(self, key: str, t: float, kind: str, node: str = "") -> None:
        """Crash-lost / evicted replica → the job controller recreates it
        as a fresh Pending pod (deterministic reincarnated uid)."""
        n = self._reincarnation.get(key, 0) + 1
        self._reincarnation[key] = n
        name = key.split("/", 1)[1]
        data = {"key": key, "reincarnation": n}
        if node:
            data["node"] = node
        if kl.replace_pending(self.cache, key, f"sim-pod-{name}-r{n}",
                              next(self._creation)):
            job = self.job_of_pod(key)
            if job is not None:
                self.job_succeeded.get(job, set()).discard(key)
            self.pending_since[key] = t  # fresh incarnation awaits its bind
            self.trace.record(SimEvent(t, kind, data))

    def _on_pod_failed(self, event: SimEvent) -> None:
        self._reincarnate(event.data["key"], event.time, ev.POD_FAILED,
                          event.data.get("node", ""))

    def _on_evict_terminated(self, event: SimEvent) -> None:
        key = event.data["key"]
        if self._stale(event):
            return  # the evicted incarnation is already gone
        if self.cfg.evict_recreates:
            self._reincarnate(key, event.time, ev.EVICT_TERMINATED)
            return
        if not kl.delete_pod(self.cache, key):
            return
        self.trace.record(SimEvent(event.time, ev.EVICT_TERMINATED,
                                   {"key": key, "deleted": True}))
        job = self.job_of_pod(key)
        if job is None:
            return
        tasks = self.job_tasks.get(job)
        if tasks is None:
            return
        tasks.discard(key)
        done = self.job_succeeded.get(job, set())
        done.discard(key)
        if tasks and done >= tasks:
            self._complete_job(job, event.time)

    # ---- per-cycle observation ------------------------------------------
    def _drain_kubelet(self, now: float) -> None:
        binds, evicts = self.kubelet.drain()
        for key, node in binds:
            self._bind_hash.update(f"{key}->{node};".encode())
            self.trace.record(SimEvent(now, ev.BIND,
                                       {"key": key, "node": node}))
            info = self.pod_info.get(key)
            if info is None:
                continue
            self.metrics.note_bind(info["job"], now)
            since = self.pending_since.pop(key, None)
            if since is not None:
                self.metrics.note_pod_bind_latency(now - since)
            stored = self.cache.pods.get(key)
            if stored is not None:
                tag = (key, stored.uid)
                if tag in self.bound_uids:
                    self.duplicate_binds += 1
                    # a duplicate bind is ALWAYS a bug — capture the cycle
                    # traces around it for offline triage
                    flight = getattr(self.cache, "flight_recorder", None)
                    if flight is not None:
                        flight.trigger(
                            "duplicate_bind",
                            detail=f"pod {key} uid {stored.uid}",
                        )
                else:
                    self.bound_uids.add(tag)
            if stored is not None:
                # uid pins the follow-up to THIS incarnation (see _stale)
                self.heap.push(SimEvent(
                    now + info["start_latency"], ev.POD_RUNNING,
                    {"key": key, "node": node, "uid": stored.uid},
                ))
        for key in evicts:
            self.trace.record(SimEvent(now, ev.EVICT, {"key": key}))
            self.metrics.note_eviction()
            stored = self.cache.pods.get(key)
            if stored is not None:
                self.heap.push(SimEvent(
                    now + self.cfg.evict_delay, ev.EVICT_TERMINATED,
                    {"key": key, "uid": stored.uid},
                ))

    def _queue_shares(self) -> Dict[str, Dict]:
        total = np.zeros(self.cache.spec.n)
        for node in self.cache.nodes.values():
            total += node.allocatable.vec
        alloc: Dict[str, np.ndarray] = {
            q: np.zeros(self.cache.spec.n) for q, _ in self.cfg.queues
        }
        for job in self.cache.jobs.values():
            if job.queue in alloc:
                alloc[job.queue] += job.allocated.vec
        weights = dict(self.cfg.queues)
        wsum = sum(weights.values()) or 1
        nz = total > 0
        out = {}
        for q, _ in self.cfg.queues:
            share = float(np.max(alloc[q][nz] / total[nz])) if nz.any() else 0.0
            out[q] = {
                "share": round(share, 6),
                "entitlement": round(weights[q] / wsum, 6),
            }
        return out

    def _task_counts(self) -> Tuple[int, int]:
        pending = running = 0
        for job in self.cache.jobs.values():
            pending += len(job.task_status_index.get(TaskStatus.PENDING, {}))
            running += len(job.task_status_index.get(TaskStatus.RUNNING, {}))
        return pending, running

    # ---- the loop --------------------------------------------------------
    def _one_cycle(self) -> Tuple[int, int]:
        """Apply due events, run one scheduling cycle (serial or pipelined
        body per the config), drain the kubelet, sample the longitudinal
        metrics.  Returns (pending, running) task counts."""
        now = self.clock.now()
        for event in self.heap.pop_due(now):
            self._apply(event)
        if self.cfg.pipelined:
            # the real staged cycle — close stages the flush, the writeback
            # worker runs it — joined immediately so binder acks land before
            # the kubelet drain and the trace stays byte-deterministic
            self.scheduler.run_once_pipelined()
            self.scheduler.drain_pipeline()
        else:
            self.scheduler.run_once()  # flushes async binds at its end
        self._drain_kubelet(now)
        # candidate-compaction longitudinal counters (ISSUE 10): presets
        # prove K is sized right when the exhaustion/full-head-re-entry
        # totals stay near zero over the whole scenario
        from kube_batch_tpu.framework.interface import get_action

        topk = getattr(get_action("allocate"), "last_topk", None)
        if topk is not None:
            self.topk_cycles += 1
            self.topk_exhausted += topk.get("exhausted", 0)
            self.topk_reentries += topk.get("reentries", 0)
            self.topk_k = topk.get("k", self.topk_k)
        # warm-carry longitudinal counters (ISSUE 14): cycles the carried
        # table served, cold rebuilds, and the invalidation volume
        warm = getattr(get_action("allocate"), "last_warm", None)
        if warm is not None:
            self.warm_cycles += 1
            if warm.get("cold"):
                self.warm_cold += 1
            self.warm_reranked += warm.get("reranked", 0)
            self.warm_changed += warm.get("changed", 0)
            self.warm_live += warm.get("bucket_live", 0)
        pending, running = self._task_counts()
        shares = self._queue_shares()
        # surface the longitudinal fairness series live: the same
        # per-queue share/entitlement samples the report aggregates are
        # exported as volcano_queue_* gauges, so a /metrics scrape of a
        # sim-driven (or production) process sees the current window
        prom_metrics.set_queue_shares(shares)
        self.metrics.note_cycle(
            now, shares, pending, running,
            snapshot_path=(
                f"{self.cache.last_open_path}"
                f"/{self.cache.columns.last_snapshot_path}"
            ),
            churn=self.cache.last_churn,
        )
        return pending, running

    def _drained(self, pending: int) -> bool:
        submitted = len(self.metrics.arrivals)
        return (not self.heap and pending == 0 and submitted > 0
                and len(self.metrics.completions) == submitted)

    def run(self) -> Dict:
        self._setup()
        cfg = self.cfg
        cycles_run = 0
        if cfg.pipelined:
            # event-driven pacing over the SAME virtual horizon as the
            # serial run (cycles × period): wake at the earliest pending
            # event, floored by min_period, capped by the idle period — the
            # CycleTrigger's semantics computed from the event heap (a
            # virtual clock has no condition variable to block on).  The
            # iteration cap bounds a pathological event stream.
            horizon = cfg.cycles * cfg.period
            max_cycles = cfg.cycles * max(
                2, int(round(cfg.period / max(cfg.min_period, 1e-6)))
            )
            try:
                while cycles_run < max_cycles:
                    pending, _ = self._one_cycle()
                    cycles_run += 1
                    if self._drained(pending):
                        break
                    now = self.clock.now()
                    nxt = self.heap.next_time()
                    if nxt is None:
                        step = cfg.period       # idle: tick at the slow floor
                    else:
                        step = min(max(nxt - now, cfg.min_period), cfg.period)
                    if now + step > horizon:
                        break
                    self.clock.sleep(step)
            finally:
                # the per-cycle drain already joined every stage; retire the
                # writeback worker so runners don't leak executor threads
                if self.scheduler._wb_pool is not None:
                    self.scheduler._wb_pool.shutdown(wait=True)
                    self.scheduler._wb_pool = None
        else:
            for _ in range(cfg.cycles):
                pending, _ = self._one_cycle()
                cycles_run += 1
                if self._drained(pending):
                    break  # workload fully drained — nothing left to simulate
                self.clock.sleep(cfg.period)
        return self._finalize(cycles_run)

    # ---- end-of-run checks ----------------------------------------------
    def _invariant_errors(self) -> List[str]:
        errs = list(self.cache.columns.check_consistency(self.cache))
        for name, node in self.cache.nodes.items():
            if not np.allclose(node.idle.vec + node.used.vec,
                               node.allocatable.vec):
                errs.append(f"node {name} accounting drift: "
                            f"idle+used != allocatable")
            resident = np.zeros(self.cache.spec.n)
            for task in node.tasks.values():
                # RELEASING occupies `used` too (eviction in flight keeps
                # the capacity charged until the pod terminates,
                # node_info.py add_task); PIPELINED would not, but it is
                # session-only state reverted at close — never resident here
                if is_allocated(task.status) or (
                        task.status == TaskStatus.RELEASING):
                    resident += task.resreq.vec
            if not np.allclose(resident, node.used.vec):
                errs.append(f"node {name} used != Σ resident resreq")
        return errs

    def _fault_recovery(self) -> Optional[Dict]:
        displaced = sorted(self.faults.displaced_jobs)
        if not displaced and not self.faults.crashed_nodes:
            return None
        detail = {}
        all_ok = True
        for uid in displaced:
            job = self.cache.jobs.get(uid)
            if uid in self.metrics.completions:
                detail[uid] = "completed"
            elif job is not None and job.ready():
                detail[uid] = "re-placed"
            else:
                detail[uid] = "NOT re-placed"
                all_ok = False
        return {
            "displaced_jobs": detail,
            "recovered": all_ok,
            "nodes_still_down": sorted(self.faults.crashed_nodes),
        }

    def _finalize(self, cycles_run: int) -> Dict:
        report = self.metrics.report()
        cfg = self.cfg
        # per-cycle device-resident scatter counters (api/resident.py), per
        # solve path — the longitudinal twin of the bench's delta-vs-full
        # bytes-moved evidence
        from kube_batch_tpu.api.resident import scatter_summary

        scatter = scatter_summary(self.cache.columns.resident_counters())
        # sharded runs carry the traced per-round collective-bytes
        # inventory next to the scatter counters (the longitudinal twin of
        # the bench's collectives section); single-part sims skip it
        solve_collectives = None
        if "sharded" in scatter:
            try:
                from kube_batch_tpu.analysis.jaxpr_audit import (
                    abstract_snapshot,
                )
                from kube_batch_tpu.parallel.mesh import (
                    collective_stats,
                    default_mesh,
                    shard_map_enabled,
                )

                mesh = default_mesh()
                if mesh is not None and shard_map_enabled():
                    cols = self.cache.columns
                    solve_collectives = collective_stats(
                        mesh,
                        snap=abstract_snapshot(
                            T=cols.tasks.cap, N=cols.nodes.cap,
                            J=cols.jobs.cap, Q=cols.queues.cap,
                            R=cols.R,
                        ),
                    )
            except Exception:  # noqa: BLE001 — report must still land
                solve_collectives = {"error": "collective trace failed"}
        # cycle tracing plane: publish any still-armed flight dumps (a
        # trigger near the end of the horizon must not lose its capture)
        # and carry the SEED-STABLE stage-attribution summary — span
        # counts per stage + attributed retraces are functions of the
        # event stream, so they reproduce per seed like the trace hash
        tracer = getattr(self.cache, "tracer", None)
        flight = getattr(self.cache, "flight_recorder", None)
        if flight is not None:
            flight.flush()
        report.update({
            "unit": "virtual_seconds",
            "seed": cfg.seed,
            "cycle_mode": "pipelined" if cfg.pipelined else "serial",
            "cycles_run": cycles_run,
            "resident_scatter": scatter,
            **({"stage_attribution": tracer.stage_attribution()}
               if tracer is not None and tracer.enabled else {}),
            # candidate-compaction longitudinal evidence: how many cycles
            # ran compacted, and whether K was sized right (exhaustion /
            # re-entry totals near zero over the whole scenario)
            "topk": {
                "compacted_cycles": self.topk_cycles,
                "k": self.topk_k,
                "exhausted_total": self.topk_exhausted,
                "reentries_total": self.topk_reentries,
            },
            # warm-carry longitudinal evidence (ISSUE 14): how many cycles
            # the carried candidate table served, cold rebuilds, and the
            # invalidated-row fraction over the scenario — the KB_WARM A/B
            # leg (--warm-ab) additionally bit-compares bind_digest
            "warm": {
                "warm_cycles": self.warm_cycles,
                "cold_builds": self.warm_cold,
                "reranked_total": self.warm_reranked,
                "changed_total": self.warm_changed,
                "invalidated_row_fraction": (
                    round(self.warm_reranked / self.warm_live, 4)
                    if self.warm_live else None
                ),
            },
            **({"solve_collectives": solve_collectives}
               if solve_collectives is not None else {}),
            # fault-hardening evidence: bind integrity (no lost/duplicate
            # binds), the egress breaker's life, the repair queue's story
            "bind_integrity": {
                "acked_binds": self.kubelet.binds_total,
                "unique_pods_bound": len(self.bound_uids),
                "duplicate_binds": self.duplicate_binds,
            },
            "transport": {
                "breaker_state": self.breaker.state,
                "breaker_transitions": dict(self.breaker.transitions),
            },
            "resync": self.cache.resync.stats(),
            "config": {
                "n_nodes": cfg.n_nodes,
                "queues": list(map(list, cfg.queues)),
                "cycles": cfg.cycles,
                "period": cfg.period,
                "min_period": cfg.min_period if cfg.pipelined else None,
                "n_jobs_poisson": cfg.n_jobs if cfg.arrivals is None else 0,
                "faults": [e.kind for e in cfg.faults],
            },
            "invariants": {"errors": self._invariant_errors()},
            "bind_failures_injected": self.kubelet.bind_failures,
            "trace_events": len(self.trace),
            "trace_sha256": self.trace.sha256(),
            # decision receipt: the order-exact digest of every acked
            # bind — two runs that scheduled identically share it (the
            # --warm-ab leg's comparison point)
            "bind_digest": self._bind_hash.hexdigest(),
        })
        recovery = self._fault_recovery()
        if recovery is not None:
            report["fault_recovery"] = recovery
        failover = self._failover_report(scatter)
        if failover is not None:
            report["failover"] = failover
        guard = self._guard_report(report)
        if guard is not None:
            report["guard"] = guard
        return report

    def _guard_report(self, report) -> Optional[Dict]:
        """The result-integrity guard plane's longitudinal evidence.  On a
        corruption run, ``chaos_ok`` is the CLI's exit-code invariant:
        every injected corruption tripped the sentinel, every condemned
        solve failed closed (zero bad binds — no duplicate bind acks, no
        accounting drift), demotion engaged, re-promotion recovered after
        the cooldown, and a diagnostics bundle landed for
        ``--replay-bundle``."""
        gp = getattr(self.cache, "guard_plane", None)
        if gp is None:
            return None
        state = gp.state()
        state["corruptions_injected"] = self.faults.corruptions_applied
        state["trip_log"] = list(gp.trip_log)
        # trip-rate SLO alerting (obs/alerts) + the flight-recorder dumps
        # the trips triggered — both part of the corruption acceptance
        alert_ev = getattr(self.cache, "alert_evaluator", None)
        if alert_ev is not None:
            state["alerts"] = alert_ev.state()
        tracer = getattr(self.cache, "tracer", None)
        flight = getattr(self.cache, "flight_recorder", None)
        trace_on = tracer is not None and tracer.enabled
        if flight is not None:
            state["flight_dumps"] = list(flight.dumps)
        if self.faults.corruptions_applied:
            paths = state["paths"].values()
            alert_fired = bool(
                alert_ev is not None
                and alert_ev.state()["alerts"]
                .get("guard_trips", {}).get("fired_total", 0) >= 1
            )
            state["chaos_ok"] = bool(
                state["trips_total"] >= self.faults.corruptions_applied
                and state["failed_closed"] >= 1
                and any(p["trips"] > 0 for p in paths)       # demotion engaged
                and any(p["promotions"] > 0 for p in paths)  # re-promoted
                and state["bundles"]
                and alert_fired                              # SLO alert fired
                # every trip armed a flight dump (trace plane on)
                and (not trace_on or state.get("flight_dumps"))
                and self.duplicate_binds == 0
                and not report["invariants"]["errors"]
            )
        return state

    def _failover_report(self, scatter_now: Dict) -> Optional[List[Dict]]:
        """Per-failover recovery evidence: how many cycles until the
        pending backlog drained again, and whether the resident device
        cache survived (full_uploads flat ⇒ no re-upload, warm path)."""
        if not self.failover_events:
            return None
        out = []
        for evr in self.failover_events:
            recovery_cycles = None
            n = 0
            for rec in self.metrics.fairness:
                if rec["t"] < evr["t"]:
                    continue
                n += 1
                if rec["pending"] == 0:
                    recovery_cycles = n
                    break
            uploads_delta = {
                path: (scatter_now.get(path, {}).get("full_uploads", 0)
                       - evr["resident_before"].get(path, {})
                       .get("full_uploads", 0))
                for path in scatter_now
            }
            out.append({
                "t": evr["t"],
                "mode": evr["mode"],
                "resident_tokens": evr["resident_tokens"],
                "recovery_cycles": recovery_cycles,
                "resident_full_uploads_delta": uploads_delta,
            })
        return out


def run_preset(name: str, seed: int = 0, cycles: Optional[int] = None,
               trace_path: Optional[str] = None,
               pipelined: bool = False,
               chrome_trace_path: Optional[str] = None) -> Dict:
    """One-call entrypoint used by the CLI and the tests."""
    cfg = preset(name, seed=seed)
    if cycles is not None:
        cfg.cycles = cycles
    cfg.pipelined = pipelined
    runner = SimRunner(cfg)
    report = runner.run()
    report["metric"] = f"sim_{name}_makespan_vt"
    report["value"] = report.get("makespan_vt")
    report["preset"] = name
    if trace_path:
        runner.trace.write(trace_path)
        report["trace_path"] = trace_path
    if chrome_trace_path:
        # export the flight-recorder ring (the whole run at sim scale) as
        # Chrome trace-event JSON — chrome://tracing / Perfetto render it
        import json as _json

        from kube_batch_tpu.obs.trace import chrome_trace

        flight = getattr(runner.cache, "flight_recorder", None)
        records = flight.records() if flight is not None else []
        with open(chrome_trace_path, "w") as f:
            _json.dump(chrome_trace(records), f)
        report["chrome_trace_path"] = chrome_trace_path
    return report
