"""CLI for the virtual-time cluster simulator.

    python -m kube_batch_tpu.sim --seed 7 --preset smoke
    python -m kube_batch_tpu.sim --preset fault --trace /tmp/fault.jsonl
    python -m kube_batch_tpu.sim --preset bind-storm        # chaos: binder flaps under a gang burst
    python -m kube_batch_tpu.sim --preset brownout          # chaos: apiserver egress window outage
    python -m kube_batch_tpu.sim --preset leader-failover   # chaos: warm-standby takeover mid-run

Emits a single JSON report (BENCH_*.json style: `metric`/`value`/`unit`
plus the longitudinal detail) on stdout. Same seed ⇒ byte-identical trace
(`trace_sha256` in the report is the determinism receipt).
"""

from __future__ import annotations

import argparse
import json
import sys

from kube_batch_tpu.sim.runner import run_preset


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--preset", default="smoke",
                    help="scenario: smoke | fault | churn | brownout | "
                         "bind-storm | leader-failover | corruption "
                         "(default smoke)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cycles", type=int, default=None,
                    help="override the preset's virtual-cycle budget")
    ap.add_argument("--trace", default=None,
                    help="write the JSONL event trace to this path")
    ap.add_argument("--chrome-trace", default=None, metavar="PATH",
                    help="export the cycle span trees (the flight-recorder "
                         "ring) as Chrome trace-event JSON for "
                         "chrome://tracing / Perfetto")
    ap.add_argument("--report", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--no-fairness-series", action="store_true",
                    help="omit the per-cycle fairness series (compact)")
    ap.add_argument("--pipelined", action="store_true",
                    help="event-driven pipelined cycles: wake at arrivals "
                         "(floored by the preset's min_period) instead of "
                         "the fixed tick; staged close + writeback worker")
    ap.add_argument("--warm-ab", action="store_true",
                    help="KB_WARM A/B: run the preset twice — carried "
                         "candidate table (KB_WARM default) vs the cold "
                         "per-solve build (KB_WARM=0) — and exit nonzero "
                         "unless every acked bind is identical "
                         "(bind_digest equality; the warm leg must also "
                         "actually engage the carry)")
    ap.add_argument("--replay-bundle", default=None, metavar="DIR",
                    help="replay a guard-plane diagnostics bundle instead "
                         "of running a preset: re-run the condemned solve "
                         "and its oracle on the captured snapshot, "
                         "sentinel-fused both ways (exit 0 iff the "
                         "integrity failure reproduces)")
    args = ap.parse_args(argv)

    if args.replay_bundle:
        from kube_batch_tpu.guard.bundle import replay_bundle

        report = replay_bundle(args.replay_bundle)
        out = json.dumps(report, indent=2, sort_keys=True)
        if args.report:
            with open(args.report, "w") as f:
                f.write(out + "\n")
        print(out, flush=True)
        return 0 if report.get("reproduced") else 1

    if args.warm_ab:
        # the warm-carry decision-equality leg (ISSUE 14): same preset,
        # same seed, KB_WARM on vs off — bit-identical binds required.
        # Runs in-process back to back; the runner is seed-deterministic
        # and each run builds a fresh cache, so the only varying input is
        # the knob under test.
        import os

        saved = os.environ.get("KB_WARM")
        try:
            os.environ.pop("KB_WARM", None)
            warm = run_preset(args.preset, seed=args.seed,
                              cycles=args.cycles, pipelined=args.pipelined)
            os.environ["KB_WARM"] = "0"
            cold = run_preset(args.preset, seed=args.seed,
                              cycles=args.cycles, pipelined=args.pipelined)
        finally:
            if saved is None:
                os.environ.pop("KB_WARM", None)
            else:
                os.environ["KB_WARM"] = saved
        match = warm.get("bind_digest") == cold.get("bind_digest")
        # "engaged" = the CARRY actually served (merge cycles, not cold
        # rebuilds — a regression that escalates every plan to cold would
        # trivially match the oracle while the feature is dead)
        wrep = warm.get("warm", {})
        engaged = (
            wrep.get("warm_cycles", 0) - wrep.get("cold_builds", 0) > 0
        )
        out = json.dumps({
            "preset": args.preset, "seed": args.seed,
            "binds_match": match, "warm_engaged": engaged,
            "warm": warm.get("warm"),
            "acked_binds_warm": warm.get("bind_integrity", {}).get(
                "acked_binds"),
            "acked_binds_cold": cold.get("bind_integrity", {}).get(
                "acked_binds"),
        }, indent=2, sort_keys=True)
        if args.report:
            with open(args.report, "w") as f:
                f.write(out + "\n")
        print(out, flush=True)
        return 0 if match and engaged else 1

    report = run_preset(args.preset, seed=args.seed, cycles=args.cycles,
                        trace_path=args.trace, pipelined=args.pipelined,
                        chrome_trace_path=args.chrome_trace)
    if args.no_fairness_series:
        report.pop("fairness_series", None)
    out = json.dumps(report, indent=2, sort_keys=True)
    if args.report:
        with open(args.report, "w") as f:
            f.write(out + "\n")
    print(out, flush=True)
    errs = report.get("invariants", {}).get("errors", [])
    recovered = report.get("fault_recovery", {}).get("recovered", True)
    duplicates = report.get("bind_integrity", {}).get("duplicate_binds", 0)
    # corruption runs additionally gate on the guard-plane invariants
    # (zero bad binds, demotion engaged, re-promotion, bundle written)
    guard_ok = report.get("guard", {}).get("chaos_ok", True)
    return 0 if not errs and recovered and not duplicates and guard_ok else 1


if __name__ == "__main__":
    sys.exit(main())
