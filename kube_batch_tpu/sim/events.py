"""Typed simulator events + JSONL trace serialization.

Every state change the simulator makes — workload arrivals, kubelet
lifecycle transitions, fault injections — and every effect it observes from
the scheduler (bind/evict acks) is a `SimEvent`. Applied events append to a
`TraceRecorder` as canonical JSONL lines; the SHA-256 over those lines is
the run's trace hash, the determinism contract (`--seed N` twice ⇒ identical
hash, byte-identical trace files). A recorded trace replays: `read_trace` +
the workload module's trace-driven generator re-inject the same arrivals.

Event payloads are JSON primitives only (no object references) so a trace
line is self-contained and replayable across processes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, Iterator, List

# ---- event kinds ----------------------------------------------------------
# injected (scheduled on the heap, applied by the runner)
JOB_ARRIVAL = "job-arrival"          # podgroup + gang pods enter the cluster
POD_RUNNING = "pod-running"          # kubelet started a bound pod
POD_SUCCEEDED = "pod-succeeded"      # kubelet finished a running pod
POD_FAILED = "pod-failed"            # pod lost (node crash fallout)
EVICT_TERMINATED = "evict-terminated"  # eviction grace period elapsed
JOB_COMPLETE = "job-complete"        # all pods succeeded; objects collected
# faults (applied through sim/faults.py)
NODE_CRASH = "node-crash"
NODE_READD = "node-readd"
BIND_FAIL = "bind-fail"              # next N binder calls fail (resync path)
WATCH_FLAP = "watch-flap"            # watch reconnect: full MODIFIED replay
BROWNOUT = "apiserver-brownout"      # every egress call fails for a window
BROWNOUT_END = "brownout-end"
LEADER_FAILOVER = "leader-failover"  # leadership lost; warm standby takes over
CORRUPT = "resident-corrupt"         # flip a word in a resident DEVICE column
# observed (recorded from scheduler effects, never scheduled)
BIND = "bind"
EVICT = "evict"

FAULT_KINDS = frozenset({NODE_CRASH, NODE_READD, BIND_FAIL, WATCH_FLAP,
                         BROWNOUT, BROWNOUT_END, LEADER_FAILOVER, CORRUPT})


@dataclasses.dataclass
class SimEvent:
    """One simulator event: virtual timestamp, kind, JSON-primitive data."""

    time: float
    kind: str
    data: Dict = dataclasses.field(default_factory=dict)


def event_line(event: SimEvent, seq: int) -> str:
    """Canonical single-line JSON for the trace: sorted keys, compact
    separators, time rounded to microsecond-of-virtual-time — byte-stable
    across runs of the same seed."""
    rec = {"seq": seq, "t": round(event.time, 6), "kind": event.kind}
    rec.update(event.data)
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))


class TraceRecorder:
    """Append-only record of applied/observed events, hashable and
    writable as JSONL."""

    def __init__(self):
        self.lines: List[str] = []

    def record(self, event: SimEvent) -> None:
        self.lines.append(event_line(event, len(self.lines)))

    def sha256(self) -> str:
        h = hashlib.sha256()
        for line in self.lines:
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            for line in self.lines:
                f.write(line + "\n")

    def __len__(self) -> int:
        return len(self.lines)


def read_trace(path: str) -> Iterator[SimEvent]:
    """Parse a JSONL trace back into events (trace-driven replay)."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            t = rec.pop("t")
            kind = rec.pop("kind")
            rec.pop("seq", None)
            yield SimEvent(time=t, kind=kind, data=rec)
