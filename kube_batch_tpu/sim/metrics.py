"""Longitudinal scheduling metrics over a simulated run.

The in-process benchmark (testing/benchmark.py) measures one frozen cycle;
these measure what only a timeline can: per-job queueing delay (arrival →
first bind) and completion time (arrival → last pod success), per-queue
share-vs-entitlement over time, eviction/preemption churn, and makespan —
all in VIRTUAL seconds, so they are properties of the scheduling policy,
not of the host the simulation ran on.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def nearest_rank(values: List[float], p: float) -> float:
    """Nearest-rank percentile: ceil(p*n)-1.  `int(p*n)` sat one rank high
    (p50 of a 2-sample read the max), overstating small-n tails — the ONE
    shared definition (bench.py and testing/e2e.py call this too)."""
    import math

    xs = sorted(values)
    n = len(xs)
    return xs[min(n - 1, max(0, math.ceil(p * n) - 1))]


def percentile_summary(values: List[float]) -> Optional[Dict]:
    """p50/p90/p99 + mean over a sample (nearest-rank, like e2e's density
    percentiles); None for an empty sample."""
    if not values:
        return None
    xs = sorted(values)
    n = len(xs)
    return {
        "n": n,
        "mean": round(sum(xs) / n, 6),
        "p50": round(nearest_rank(xs, 0.50), 6),
        "p90": round(nearest_rank(xs, 0.90), 6),
        "p99": round(nearest_rank(xs, 0.99), 6),
        "max": round(xs[-1], 6),
    }


class LongitudinalMetrics:
    def __init__(self):
        self.arrivals: Dict[str, float] = {}      # job uid → arrival vt
        self.first_bind: Dict[str, float] = {}    # job uid → first bind vt
        self.completions: Dict[str, float] = {}   # job uid → all-succeeded vt
        self.evictions = 0
        self.binds = 0
        # per-POD arrival→bind latency (every incarnation), the bind-storm
        # preset's headline: p99 must stay bounded while the binder flaps
        self.pod_bind_latency: List[float] = []
        self.fairness: List[Dict] = []            # per-cycle queue shares
        self.cycles = 0
        # cross-cycle resident-snapshot bookkeeping: which open/snapshot
        # path each cycle took ("delta" vs "full") and its churn fraction —
        # the seed-deterministic evidence that the multi-cycle delta win
        # holds without the TPU tunnel
        self.snapshot_paths: Dict[str, int] = {}
        self.churn: List[float] = []

    # ---- job lifecycle ---------------------------------------------------
    def note_arrival(self, job_uid: str, t: float) -> None:
        self.arrivals.setdefault(job_uid, t)

    def note_bind(self, job_uid: str, t: float) -> None:
        self.binds += 1
        self.first_bind.setdefault(job_uid, t)

    def note_pod_bind_latency(self, dt: float) -> None:
        self.pod_bind_latency.append(dt)

    def note_eviction(self) -> None:
        self.evictions += 1

    def note_completion(self, job_uid: str, t: float) -> None:
        self.completions.setdefault(job_uid, t)

    # ---- per-cycle -------------------------------------------------------
    def note_cycle(self, t: float, queue_shares: Dict[str, Dict],
                   pending_tasks: int, running_tasks: int,
                   snapshot_path: Optional[str] = None,
                   churn: Optional[float] = None) -> None:
        self.cycles += 1
        rec = {
            "t": round(t, 6),
            "queues": queue_shares,
            "pending": pending_tasks,
            "running": running_tasks,
        }
        if snapshot_path is not None:
            rec["snapshot_path"] = snapshot_path
            self.snapshot_paths[snapshot_path] = (
                self.snapshot_paths.get(snapshot_path, 0) + 1
            )
        if churn is not None:
            rec["churn"] = round(churn, 6)
            self.churn.append(churn)
        self.fairness.append(rec)

    # ---- report ----------------------------------------------------------
    def report(self) -> Dict:
        jct = [self.completions[j] - self.arrivals[j]
               for j in self.completions if j in self.arrivals]
        wait = [self.first_bind[j] - self.arrivals[j]
                for j in self.first_bind if j in self.arrivals]
        completed_at = list(self.completions.values())
        arrived_at = list(self.arrivals.values())
        makespan = (round(max(completed_at) - min(arrived_at), 6)
                    if completed_at and arrived_at else None)
        # fairness summarized as each queue's mean |share − entitlement|
        # over cycles where anything was allocated, plus the raw series
        drift: Dict[str, List[float]] = {}
        for rec in self.fairness:
            for q, s in rec["queues"].items():
                drift.setdefault(q, []).append(
                    abs(s["share"] - s["entitlement"])
                )
        return {
            "jobs": {
                "submitted": len(self.arrivals),
                "started": len(self.first_bind),
                "completed": len(self.completions),
            },
            "jct_vt": percentile_summary(jct),
            "wait_vt": percentile_summary(wait),
            "pod_bind_latency_vt": percentile_summary(self.pod_bind_latency),
            "makespan_vt": makespan,
            "binds": self.binds,
            "evictions": self.evictions,
            "cycles": self.cycles,
            "fairness_mean_abs_drift": {
                q: round(sum(v) / len(v), 6) for q, v in drift.items() if v
            },
            # per-cycle open/snapshot path counts + churn-fraction summary
            # (the raw per-cycle values ride the fairness series records)
            "snapshot_paths": dict(self.snapshot_paths),
            "churn_fraction": percentile_summary(self.churn),
            "fairness_series": self.fairness,
        }
