"""Simulated kubelet: the Binder/Evictor seam plus the pod lifecycle
state machine, mirroring the stub apiserver's semantics
(testing/e2e.py StubApiServer): a Binding ack eventually transitions the
pod to Running on its node; an eviction terminates it after a grace delay.

Threading contract: the cache dispatches binder calls on its async
kb-dispatch worker (cache.go:478's goroutines), so `bind`/`bind_many`
only RECORD acks under a lock. The runner — single-threaded over the
virtual clock — drains the acks after each cycle's `flush_binds` and
schedules the lifecycle follow-ups on the event heap. Every cache
mutation therefore happens on the runner thread, in deterministic order.

The lifecycle transitions themselves are module functions over the
cache's own pod store: each builds a fresh `Pod` (informer-event style)
and feeds it through `cache.update_pod`, exactly the ingest path a live
watch stream uses.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List, Optional, Tuple

from kube_batch_tpu.api.pod import Pod
from kube_batch_tpu.api.types import PodPhase


class SimBindFailure(Exception):
    """Injected binder failure (the BIND_FAIL fault): exercises the
    cache's resync repair path (cache.go:559-581)."""


class SimKubelet:
    """Binder + Evictor backend recording acks for the runner to drain."""

    def __init__(self):
        self._lock = threading.Lock()
        self._bind_acks: List[Tuple[str, str]] = []   # (pod key, node)
        self._evict_acks: List[str] = []              # pod key
        self._fail_binds = 0  # pending injected per-pod bind failures
        self._brownout = False  # apiserver brownout: every call fails
        self.binds_total = 0
        self.bind_failures = 0

    # ---- fault injection -------------------------------------------------
    def fail_next_binds(self, n: int) -> None:
        with self._lock:
            self._fail_binds += int(n)

    def set_brownout(self, active: bool) -> None:
        """The APISERVER_BROWNOUT window: while active, EVERY egress call
        fails (the apiserver unreachable/overloaded) — upstream, the
        circuit breaker opens and the degraded cycle parks decisions."""
        with self._lock:
            self._brownout = active

    def _maybe_fail(self, what: str) -> None:
        # lock held by caller
        if self._brownout:
            self.bind_failures += 1
            raise SimBindFailure(f"apiserver brownout: {what}")

    # ---- Binder seam -----------------------------------------------------
    def bind(self, pod: Pod, hostname: str) -> None:
        with self._lock:
            self._maybe_fail(f"bind {pod.key()}")
            if self._fail_binds > 0:
                self._fail_binds -= 1
                self.bind_failures += 1
                raise SimBindFailure(f"injected bind failure for {pod.key()}")
            self._bind_acks.append((pod.key(), hostname))
            self.binds_total += 1

    def bind_many(self, pairs) -> None:
        """All-or-nothing batch (cache._dispatch_async retries per-task
        through bind() on failure). A failed batch consumes ONE unit of the
        injected budget — one failed API call — so the budget drains even
        when the circuit breaker blocks the per-task fallback and the next
        attempts are half-open bind_many probes."""
        with self._lock:
            self._maybe_fail("bind_many")
            if self._fail_binds > 0:
                self._fail_binds -= 1
                self.bind_failures += 1
                raise SimBindFailure("injected bind_many failure")
            for pod, hostname in pairs:
                self._bind_acks.append((pod.key(), hostname))
                self.binds_total += 1

    # ---- Evictor seam ----------------------------------------------------
    def evict(self, pod: Pod) -> None:
        with self._lock:
            self._maybe_fail(f"evict {pod.key()}")
            self._evict_acks.append(pod.key())

    # ---- runner drain ----------------------------------------------------
    def drain(self) -> Tuple[List[Tuple[str, str]], List[str]]:
        with self._lock:
            binds, self._bind_acks = self._bind_acks, []
            evicts, self._evict_acks = self._evict_acks, []
        return binds, evicts


# ---- lifecycle transitions over the cache's pod store ---------------------


def _stored(cache, key: str) -> Optional[Pod]:
    return cache.pods.get(key)


def set_running(cache, key: str, node: str) -> bool:
    """Binding ack matured: the kubelet runs the pod (StubApiServer.bind_pod
    sets spec.nodeName + status.phase=Running in one MODIFIED event)."""
    pod = _stored(cache, key)
    if pod is None or pod.phase != PodPhase.PENDING:
        return False  # deleted or superseded while the start latency elapsed
    cache.update_pod(dataclasses.replace(pod, phase=PodPhase.RUNNING,
                                         node_name=node))
    return True


def set_succeeded(cache, key: str) -> bool:
    pod = _stored(cache, key)
    if pod is None or pod.phase != PodPhase.RUNNING:
        return False
    cache.update_pod(dataclasses.replace(pod, phase=PodPhase.SUCCEEDED))
    return True


def delete_pod(cache, key: str) -> bool:
    pod = _stored(cache, key)
    if pod is None:
        return False
    cache.delete_pod(pod)
    return True


def replace_pending(cache, key: str, uid: str, creation_index: int) -> bool:
    """The job controller's part: a terminated (evicted / crash-lost)
    replica is deleted and recreated as a fresh Pending pod of the same
    name — what a Job/ReplicaSet controller does for kube-batch's gangs.
    `uid` must be deterministic (the runner derives it from a reincarnation
    counter), never the process-global auto-uid."""
    pod = _stored(cache, key)
    if pod is None:
        return False
    cache.delete_pod(pod)
    fresh = dataclasses.replace(
        pod, uid=uid, phase=PodPhase.PENDING, node_name=None,
        creation_index=creation_index,
    )
    cache.add_pod(fresh)
    return True
