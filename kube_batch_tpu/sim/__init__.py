"""Virtual-time cluster simulator: event-driven workload replay, fault
injection, and longitudinal scheduling metrics over the real
Scheduler/SchedulerCache. See sim/runner.py for the loop and
`python -m kube_batch_tpu.sim --help` for the CLI."""

from kube_batch_tpu.sim.runner import SimConfig, SimRunner, preset, run_preset

__all__ = ["SimConfig", "SimRunner", "preset", "run_preset"]
