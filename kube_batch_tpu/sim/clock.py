"""Virtual time for the cluster simulator.

Two pieces: a `VirtualClock` the runner (and the L1 `Scheduler`, via its
injected-clock seam) reads, and an `EventHeap` — a time-ordered heap of
pending `SimEvent`s built on `utils/priority_queue.PriorityQueue`, whose
stable insertion-order tie-break is exactly what trace determinism needs:
two events scheduled for the same instant always pop in scheduling order.

Virtual seconds have no relation to wall time: a 500-cycle day replays in
however long the 500 scheduling cycles take to compute.
"""

from __future__ import annotations

from typing import List, Optional

from kube_batch_tpu.sim.events import SimEvent
from kube_batch_tpu.utils.priority_queue import PriorityQueue


class VirtualClock:
    """Monotone virtual time. `monotonic()`/`sleep()` match the subset of
    the `time` module the Scheduler's clock seam uses, so a Scheduler
    constructed with this clock paces its loop in simulated seconds."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def monotonic(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            self._now += seconds

    def advance_to(self, t: float) -> None:
        if t > self._now:
            self._now = t


class EventHeap:
    """Pending simulator events ordered by (virtual time, insertion order)."""

    def __init__(self):
        self._pq = PriorityQueue(less=lambda a, b: a.time < b.time)

    def push(self, event: SimEvent) -> None:
        self._pq.push(event)

    def push_all(self, events) -> None:
        for ev in events:
            self.push(ev)

    def next_time(self) -> Optional[float]:
        return None if self._pq.empty() else self._pq.peek().time

    def pop_due(self, now: float) -> List[SimEvent]:
        """All events with time <= now, in deterministic order."""
        due: List[SimEvent] = []
        while not self._pq.empty() and self._pq.peek().time <= now:
            due.append(self._pq.pop())
        return due

    def __len__(self) -> int:
        return len(self._pq)

    def __bool__(self) -> bool:
        return bool(self._pq)
