"""Active/passive HA via a file-based lease lock.

The reference does leader election with a ConfigMap resource lock — 15s
lease, 10s renew deadline, 5s retry, and `glog.Fatalf` (crash → standby takes
over) on lost leadership (cmd/kube-batch/app/server.go:48-52,106-151). The
standalone analog uses an atomically-renamed lease file in the
lock-object-namespace directory with the same timing constants and the same
crash-on-loss contract.

Clock discipline: the lease RECORD carries wall-clock stamps (time.time())
because other processes compare against them — that half keeps the
reference's caveat (an NTP step larger than lease_duration can open a brief
dual-leader window; deploy with slewing, not stepping). The local
renew-DEADLINE bookkeeping, though, is process-private and now runs on
time.monotonic(): a wall-clock step can no longer fake a missed renewal and
spuriously crash a healthy leader."""

from __future__ import annotations

import json
import logging
import os
import socket
import tempfile
import threading
import time
import uuid
from typing import Callable, Optional

logger = logging.getLogger("kube_batch_tpu")

LEASE_DURATION = 15.0  # server.go:49
RENEW_DEADLINE = 10.0  # server.go:50
RETRY_PERIOD = 5.0     # server.go:51


class LostLeadership(RuntimeError):
    """Raised on the leader thread when renewal fails — the analog of
    `glog.Fatalf("leaderelection lost")` (server.go:145)."""


class LeaderElector:
    def __init__(
        self,
        lock_dir: str,
        identity: Optional[str] = None,
        lease_duration: float = LEASE_DURATION,
        renew_deadline: float = RENEW_DEADLINE,
        retry_period: float = RETRY_PERIOD,
    ):
        self.lock_path = os.path.join(lock_dir, "kube-batch-tpu-lock")
        self._init_common(identity, lease_duration, renew_deadline, retry_period)

    def _init_common(self, identity, lease_duration, renew_deadline,
                     retry_period) -> None:
        """Identity/timing/stop state shared by every lock flavor."""
        self.identity = identity or f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._stop = threading.Event()
        self._renew_thread: Optional[threading.Thread] = None

    def reset(self) -> None:
        """Re-arm a released elector so the warm-standby loop can contend
        again in the SAME process (release() set _stop to reap the renew
        thread; a fresh run() needs a clear event and no stale thread)."""
        self._stop = threading.Event()
        self._renew_thread = None

    # -- lease record ---------------------------------------------------
    def _read(self) -> Optional[dict]:
        try:
            with open(self.lock_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write(self) -> None:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self.lock_path))
        with os.fdopen(fd, "w") as f:
            json.dump({"holder": self.identity, "renew_time": time.time()}, f)
        os.replace(tmp, self.lock_path)  # atomic on POSIX

    def _try_acquire_or_renew(self) -> bool:
        """The read-check-write is serialized through a short-lived O_EXCL
        claim file so two standbys can't both grab an expired lease (the
        resourcelock's apiserver-side compare-and-swap analog).

        A claim collision is retried with a short backoff before reporting
        failure: a standby briefly holding the claim file is contention, not
        a lost lease — without the retry, two coincidental collisions one
        retry_period apart could kill a healthy leader."""
        claim = self.lock_path + ".claim"
        fd = None
        for attempt in range(4):
            try:
                fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                break
            except FileExistsError:
                try:  # break a claim orphaned by a crashed contender
                    if time.time() - os.path.getmtime(claim) > self.lease_duration:
                        os.unlink(claim)
                        continue
                except OSError:
                    pass
                # kbt: allow[KBT011] file-lock claim contention on local
                # disk, not an apiserver call — no transport policy applies
                time.sleep(0.05 * (attempt + 1))
        if fd is None:
            return False
        try:
            rec = self._read()
            now = time.time()
            if rec is not None and rec["holder"] != self.identity:
                if now - rec["renew_time"] < self.lease_duration:
                    return False  # current leader's lease still valid
            self._write()
            return True
        finally:
            os.close(fd)
            try:
                os.unlink(claim)
            except OSError:
                pass

    # -- run loop (leaderelection.RunOrDie analog) ----------------------
    def run(
        self,
        on_started_leading: Callable[[], None],
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        """Block until leadership is acquired, run the callback, and renew in
        the background. If renewal misses the deadline, `on_stopped_leading`
        is invoked (it must make the leading callback return — e.g.
        Scheduler.stop) and LostLeadership is raised, mirroring the
        reference's crash-on-loss (server.go:145)."""
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                break
            self._stop.wait(self.retry_period)
        if self._stop.is_set():
            return

        failure = []

        def renew_loop():
            # deadline bookkeeping is process-private → monotonic (module
            # docstring); only the lease record itself stays wall-clock
            last_renew = time.monotonic()
            while not self._stop.is_set():
                self._stop.wait(self.retry_period)
                if self._stop.is_set():
                    return
                if self._try_acquire_or_renew():
                    last_renew = time.monotonic()
                elif time.monotonic() - last_renew > self.renew_deadline:
                    failure.append(True)
                    if on_stopped_leading is not None:
                        on_stopped_leading()
                    return

        t = threading.Thread(target=renew_loop, daemon=True, name="lease-renew")
        self._renew_thread = t
        t.start()
        try:
            on_started_leading()
        finally:
            self.release()
        if failure:
            raise LostLeadership(f"{self.identity} lost the lease")

    # one K8s renew attempt is a GET + a PUT, EACH with a 10s HTTP timeout;
    # the join must outlast the pair or an in-flight renew PUT can land AFTER
    # release() vacates the lease and re-take it, delaying standby takeover
    # by a full lease_duration
    _RENEW_JOIN_TIMEOUT = 22.0

    def _join_renew(self) -> bool:
        """Stop and reap the renew thread BEFORE vacating the lock: a renew
        attempt in flight after the vacate would re-take the lease and delay
        standby takeover by a full lease_duration. Returns False when the
        thread could not be reaped within the transport timeout — callers
        must then re-check/re-vacate after it dies, or accept the risk."""
        self._stop.set()
        t = self._renew_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=self._RENEW_JOIN_TIMEOUT)
            if t.is_alive():
                logger.warning(
                    "renew thread still alive after %.0fs join; a late renew "
                    "may re-take the lease", self._RENEW_JOIN_TIMEOUT)
                return False
        return True

    def is_leader(self) -> bool:
        rec = self._read()
        return (
            rec is not None
            and rec["holder"] == self.identity
            and time.time() - rec["renew_time"] < self.lease_duration
        )

    def release(self) -> None:
        joined = self._join_renew()
        self._vacate()
        if not joined and self._renew_thread is not None:
            # a straggling renew may land after the vacate and re-take the
            # lease; wait for the thread to die and vacate once more
            self._renew_thread.join(timeout=self._RENEW_JOIN_TIMEOUT)
            self._vacate()

    def _vacate(self) -> None:
        rec = self._read()
        if rec is not None and rec["holder"] == self.identity:
            try:
                os.unlink(self.lock_path)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Kubernetes-native election (--master mode)
# ---------------------------------------------------------------------------

_LEASE_GROUP = "/apis/coordination.k8s.io/v1"


def _rfc3339_micro(ts: float) -> str:
    import datetime

    return (
        datetime.datetime.fromtimestamp(ts, datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%f")
        + "Z"
    )


def _parse_rfc3339(s: Optional[str]) -> float:
    import datetime

    if not s:
        return 0.0
    try:
        return datetime.datetime.fromisoformat(s.replace("Z", "+00:00")).timestamp()
    except ValueError:
        return 0.0


class K8sLeaseElector(LeaderElector):
    """Leader election through a coordination.k8s.io/v1 Lease — the
    cross-host resource lock the reference takes through the cluster API
    (server.go:106-151 uses the older ConfigMap resourcelock; the Lease
    object is its successor with first-class holder/renew fields). Same
    15s/10s/5s timings and crash-on-loss contract as the file elector; the
    apiserver's resourceVersion conflict (409) is the compare-and-swap the
    file elector approximates with its O_EXCL claim file.

    Like client-go, lease validity compares the apiserver-stored renewTime
    against the local clock — the file elector's NTP caveat (module
    docstring) applies unchanged."""

    def __init__(
        self,
        transport,
        namespace: str,
        name: str = "kube-batch-tpu",
        identity: Optional[str] = None,
        lease_duration: float = LEASE_DURATION,
        renew_deadline: float = RENEW_DEADLINE,
        retry_period: float = RETRY_PERIOD,
    ):
        # the Lease wire format carries whole seconds (leaseDurationSeconds);
        # a sub-second duration would serialize as 0 and every contender
        # would then judge validity by its own local config — dual leader
        if lease_duration < 1.0:
            raise ValueError("k8s lease_duration must be >= 1 second")
        self.transport = transport
        self.namespace = namespace
        self.name = name
        self._init_common(identity, lease_duration, renew_deadline, retry_period)

    @property
    def _path(self) -> str:
        return f"{_LEASE_GROUP}/namespaces/{self.namespace}/leases/{self.name}"

    def _get(self) -> Optional[dict]:
        import urllib.error

        try:
            # retry=False: the elector's retry_period loop IS the retry
            # policy — in-call retries would stretch a renew attempt past
            # the renew deadline (and the _RENEW_JOIN_TIMEOUT math)
            return self.transport.get_json(self._path, timeout=10,
                                           retry=False)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def _try_acquire_or_renew(self) -> bool:
        """One acquire/renew attempt (leaderelection.go tryAcquireOrRenew):
        create if absent, take over if expired, renew if held — every write
        CAS-guarded by resourceVersion (a 409 means another contender won
        the race; report failure and retry next period). Transport errors
        also report failure: an unreachable apiserver must run the renew
        deadline down, not crash the standby loop."""
        import urllib.error

        now = time.time()
        spec_new = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(round(self.lease_duration)),
            "renewTime": _rfc3339_micro(now),
        }
        try:
            obj = self._get()
            if obj is None:
                spec_new["acquireTime"] = spec_new["renewTime"]
                spec_new["leaseTransitions"] = 0
                self.transport.request(
                    "POST",
                    f"{_LEASE_GROUP}/namespaces/{self.namespace}/leases",
                    {
                        "apiVersion": "coordination.k8s.io/v1",
                        "kind": "Lease",
                        "metadata": {"name": self.name,
                                     "namespace": self.namespace},
                        "spec": spec_new,
                    },
                    timeout=10, retry=False,
                )
                return True
            spec = obj.get("spec") or {}
            holder = spec.get("holderIdentity")
            duration = float(spec.get("leaseDurationSeconds")
                             or self.lease_duration)
            if (
                holder
                and holder != self.identity
                and now - _parse_rfc3339(spec.get("renewTime")) < duration
            ):
                return False  # current leader's lease still valid
            if holder == self.identity:  # renewal
                spec_new["acquireTime"] = (
                    spec.get("acquireTime") or spec_new["renewTime"]
                )
                spec_new["leaseTransitions"] = int(
                    spec.get("leaseTransitions") or 0
                )
            else:  # takeover of an expired or vacated lease
                spec_new["acquireTime"] = spec_new["renewTime"]
                spec_new["leaseTransitions"] = int(
                    spec.get("leaseTransitions") or 0
                ) + 1
            obj["spec"] = spec_new
            self.transport.request("PUT", self._path, obj, timeout=10,
                                   retry=False)
            return True
        except urllib.error.HTTPError as e:
            if e.code == 409:
                return False  # lost the CAS race
            logger.warning("lease %s write failed: %s", self.name, e)
            return False
        except OSError as e:
            logger.warning("lease %s unreachable: %s", self.name, e)
            return False

    def is_leader(self) -> bool:
        try:
            obj = self._get()
        except OSError:
            return False
        if obj is None:
            return False
        spec = obj.get("spec") or {}
        duration = float(spec.get("leaseDurationSeconds") or self.lease_duration)
        return (
            spec.get("holderIdentity") == self.identity
            and time.time() - _parse_rfc3339(spec.get("renewTime")) < duration
        )

    def _vacate(self) -> None:
        """Vacate the lease on clean shutdown (client-go ReleaseOnCancel
        clears holderIdentity) so a standby can take over immediately.
        The renew thread is reaped FIRST (base-class release) — an in-flight
        renew landing after the vacate would re-take the lease; its CAS bump
        also explains the one 409 retry here."""
        import urllib.error

        for _ in range(2):
            try:
                obj = self._get()
                if obj is None:
                    return
                spec = obj.get("spec") or {}
                if spec.get("holderIdentity") != self.identity:
                    return
                spec["holderIdentity"] = ""
                obj["spec"] = spec
                self.transport.request("PUT", self._path, obj, timeout=10,
                                       retry=False)
                return
            except urllib.error.HTTPError as e:
                if e.code == 409:
                    continue  # lost a CAS race — re-GET and retry once
                return  # best-effort; the lease simply expires
            except OSError:
                return
