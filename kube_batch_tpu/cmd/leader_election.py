"""Active/passive HA via a file-based lease lock.

The reference does leader election with a ConfigMap resource lock — 15s
lease, 10s renew deadline, 5s retry, and `glog.Fatalf` (crash → standby takes
over) on lost leadership (cmd/kube-batch/app/server.go:48-52,106-151). The
standalone analog uses an atomically-renamed lease file in the
lock-object-namespace directory with the same timing constants and the same
crash-on-loss contract.

Wall-clock caveat: lease validity and renewal compare time.time() stamps
across processes (the reference similarly trusts apiserver timestamps). An
NTP step larger than renew_deadline can cause a spurious crash-on-loss or a
brief dual-leader window; deploy with slewing (chrony/ntpd -x), not stepping,
on the contending hosts."""

from __future__ import annotations

import json
import os
import socket
import tempfile
import threading
import time
import uuid
from typing import Callable, Optional

LEASE_DURATION = 15.0  # server.go:49
RENEW_DEADLINE = 10.0  # server.go:50
RETRY_PERIOD = 5.0     # server.go:51


class LostLeadership(RuntimeError):
    """Raised on the leader thread when renewal fails — the analog of
    `glog.Fatalf("leaderelection lost")` (server.go:145)."""


class LeaderElector:
    def __init__(
        self,
        lock_dir: str,
        identity: Optional[str] = None,
        lease_duration: float = LEASE_DURATION,
        renew_deadline: float = RENEW_DEADLINE,
        retry_period: float = RETRY_PERIOD,
    ):
        self.lock_path = os.path.join(lock_dir, "kube-batch-tpu-lock")
        self.identity = identity or f"{socket.gethostname()}-{uuid.uuid4().hex[:8]}"
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._stop = threading.Event()

    # -- lease record ---------------------------------------------------
    def _read(self) -> Optional[dict]:
        try:
            with open(self.lock_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write(self) -> None:
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(self.lock_path))
        with os.fdopen(fd, "w") as f:
            json.dump({"holder": self.identity, "renew_time": time.time()}, f)
        os.replace(tmp, self.lock_path)  # atomic on POSIX

    def _try_acquire_or_renew(self) -> bool:
        """The read-check-write is serialized through a short-lived O_EXCL
        claim file so two standbys can't both grab an expired lease (the
        resourcelock's apiserver-side compare-and-swap analog).

        A claim collision is retried with a short backoff before reporting
        failure: a standby briefly holding the claim file is contention, not
        a lost lease — without the retry, two coincidental collisions one
        retry_period apart could kill a healthy leader."""
        claim = self.lock_path + ".claim"
        fd = None
        for attempt in range(4):
            try:
                fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                break
            except FileExistsError:
                try:  # break a claim orphaned by a crashed contender
                    if time.time() - os.path.getmtime(claim) > self.lease_duration:
                        os.unlink(claim)
                        continue
                except OSError:
                    pass
                time.sleep(0.05 * (attempt + 1))
        if fd is None:
            return False
        try:
            rec = self._read()
            now = time.time()
            if rec is not None and rec["holder"] != self.identity:
                if now - rec["renew_time"] < self.lease_duration:
                    return False  # current leader's lease still valid
            self._write()
            return True
        finally:
            os.close(fd)
            try:
                os.unlink(claim)
            except OSError:
                pass

    # -- run loop (leaderelection.RunOrDie analog) ----------------------
    def run(
        self,
        on_started_leading: Callable[[], None],
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        """Block until leadership is acquired, run the callback, and renew in
        the background. If renewal misses the deadline, `on_stopped_leading`
        is invoked (it must make the leading callback return — e.g.
        Scheduler.stop) and LostLeadership is raised, mirroring the
        reference's crash-on-loss (server.go:145)."""
        while not self._stop.is_set():
            if self._try_acquire_or_renew():
                break
            self._stop.wait(self.retry_period)
        if self._stop.is_set():
            return

        failure = []

        def renew_loop():
            last_renew = time.time()
            while not self._stop.is_set():
                self._stop.wait(self.retry_period)
                if self._stop.is_set():
                    return
                if self._try_acquire_or_renew():
                    last_renew = time.time()
                elif time.time() - last_renew > self.renew_deadline:
                    failure.append(True)
                    if on_stopped_leading is not None:
                        on_stopped_leading()
                    return

        t = threading.Thread(target=renew_loop, daemon=True, name="lease-renew")
        t.start()
        try:
            on_started_leading()
        finally:
            self.release()
        if failure:
            raise LostLeadership(f"{self.identity} lost the lease")

    def is_leader(self) -> bool:
        rec = self._read()
        return (
            rec is not None
            and rec["holder"] == self.identity
            and time.time() - rec["renew_time"] < self.lease_duration
        )

    def release(self) -> None:
        self._stop.set()
        rec = self._read()
        if rec is not None and rec["holder"] == self.identity:
            try:
                os.unlink(self.lock_path)
            except OSError:
                pass
