"""Server options — all 13 CLI flags of the reference
(cmd/kube-batch/app/options/options.go:37-95), adapted to the standalone
host: `master`/`kubeconfig` become the listen address of an upstream ingest
feed (optional), QPS/burst throttle the egress writer."""

from __future__ import annotations

import argparse
import dataclasses
from typing import List, Optional


@dataclasses.dataclass
class ServerOption:
    """(options.go:37-51; defaults options.go:63-81)"""

    master: str = ""
    kubeconfig: str = ""
    scheduler_name: str = "volcano"
    scheduler_conf: str = ""
    schedule_period: float = 1.0  # seconds (`--schedule-period`, 1s default)
    default_queue: str = "default"
    enable_leader_election: bool = False
    lock_object_namespace: str = ""
    # warm-standby failover (BEYOND the reference's crash-on-loss): on a
    # lost lease the process demotes to standby IN PLACE — keeping the
    # compiled solve executables and device-resident buffers — and
    # re-contends; on re-acquire the cache rebuilds from the pod store and
    # revalidates the resident snapshot instead of cold-starting
    leader_warm_standby: bool = False
    listen_address: str = ":8080"
    enable_priority_class: bool = True
    kube_api_qps: float = 50.0
    kube_api_burst: int = 100
    print_version: bool = False
    # standalone-only: durable-state file (the etcd analog, SURVEY.md §5.4);
    # empty = in-memory only
    state_file: str = ""
    # standalone-only: how long the first cycle waits for the initial-sync
    # barrier (POST /v1/sync or a restored state file) — the WaitForCacheSync
    # analog; 0 = don't wait (clients that never signal lose nothing)
    cache_sync_timeout: float = 0.0
    # replicated read plane (replicate/): a leader URL turns this process
    # into a what-if follower — no scheduler, no ingest; the pull loop
    # applies the leader's cycle deltas and the serving stack answers
    # against the local replica
    follower: str = ""

    def check_option_or_die(self) -> None:
        """(options.go:84-90): leader election requires a lock namespace;
        the listen address must carry a numeric port."""
        if self.enable_leader_election and not self.lock_object_namespace:
            raise ValueError(
                "lock-object-namespace must not be nil when LeaderElection is enabled"
            )
        self.listen_host_port  # noqa: B018 — raises ValueError when malformed

    @property
    def listen_host_port(self) -> tuple:
        host, sep, port = self.listen_address.rpartition(":")
        if not sep or not port.isdigit():
            raise ValueError(
                f"listen-address {self.listen_address!r} must be host:port"
            )
        host = host.strip("[]")  # [::]:8080 → ::
        return host or "0.0.0.0", int(port)


# process-global options (options.go:54 `ServerOpts`)
server_opts: Optional[ServerOption] = None


def add_flags(parser: argparse.ArgumentParser) -> None:
    """(options.go:63-81)"""
    d = ServerOption()
    parser.add_argument("--master", default=d.master,
                        help="url of an upstream cluster feed (accepted for CLI "
                             "parity; standalone ingest is the HTTP admin API)")
    parser.add_argument("--kubeconfig", default=d.kubeconfig,
                        help="path to a cluster-connection config file (accepted "
                             "for CLI parity; unused standalone)")
    parser.add_argument("--scheduler-name", default=d.scheduler_name,
                        help="the scheduler name pods request in schedulerName")
    parser.add_argument("--scheduler-conf", default=d.scheduler_conf,
                        help="path to the YAML actions/tiers configuration")
    parser.add_argument("--schedule-period", default=d.schedule_period, type=float,
                        help="seconds between scheduling cycles")
    parser.add_argument("--default-queue", default=d.default_queue,
                        help="queue assigned to podgroups that name none")
    parser.add_argument("--leader-elect", action="store_true",
                        default=d.enable_leader_election,
                        help="enable active/passive HA via a lease lock")
    parser.add_argument("--lock-object-namespace", default=d.lock_object_namespace,
                        help="namespace (directory) holding the leader lease")
    parser.add_argument("--leader-warm-standby", action="store_true",
                        default=d.leader_warm_standby,
                        help="on lost leadership, demote to standby in-place "
                             "(keep compiled solves + device-resident "
                             "buffers) and re-contend instead of crashing")
    parser.add_argument("--listen-address", default=d.listen_address,
                        help="host:port for /metrics and the admin API")
    parser.add_argument("--priority-class", dest="priority_class", default=d.enable_priority_class,
                        action="store_true",
                        help="resolve pod/job priority from PriorityClasses")
    parser.add_argument("--no-priority-class", dest="priority_class", action="store_false")
    parser.add_argument("--kube-api-qps", default=d.kube_api_qps, type=float,
                        help="egress write QPS limit")
    parser.add_argument("--kube-api-burst", default=d.kube_api_burst, type=int,
                        help="egress write burst")
    parser.add_argument("--version", action="store_true", default=False,
                        help="print version and exit")
    parser.add_argument("--state-file", default=d.state_file,
                        help="durable cluster-state JSON (standalone etcd "
                             "analog); loaded at startup, saved each cycle")
    parser.add_argument("--cache-sync-timeout", default=d.cache_sync_timeout,
                        type=float,
                        help="seconds to wait for the initial-sync barrier "
                             "(POST /v1/sync) before the first cycle; 0 = "
                             "don't wait")
    parser.add_argument("--follower", default=d.follower, metavar="URL",
                        help="run as a what-if read replica of the leader at "
                             "URL (its /v1/replicate stream) instead of "
                             "scheduling")


def parse(argv: Optional[List[str]] = None) -> ServerOption:
    parser = argparse.ArgumentParser(prog="kube-batch-tpu")
    add_flags(parser)
    ns = parser.parse_args(argv)
    opt = ServerOption(
        master=ns.master,
        kubeconfig=ns.kubeconfig,
        scheduler_name=ns.scheduler_name,
        scheduler_conf=ns.scheduler_conf,
        schedule_period=ns.schedule_period,
        default_queue=ns.default_queue,
        enable_leader_election=ns.leader_elect,
        lock_object_namespace=ns.lock_object_namespace,
        leader_warm_standby=ns.leader_warm_standby,
        listen_address=ns.listen_address,
        enable_priority_class=ns.priority_class,
        kube_api_qps=ns.kube_api_qps,
        kube_api_burst=ns.kube_api_burst,
        print_version=ns.version,
        state_file=ns.state_file,
        cache_sync_timeout=ns.cache_sync_timeout,
        follower=ns.follower,
    )
    global server_opts
    server_opts = opt
    return opt
