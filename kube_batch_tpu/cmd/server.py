"""HTTP server + process run loop (cmd/kube-batch/app/server.go).

The reference serves Prometheus `/metrics` (+ pprof) on --listen-address
(server.go:96-99) and ingests cluster state through ten API-server informers
(cache.go:256-336). Standalone, the same listener carries both:

- GET  /metrics                — Prometheus text exposition (same metric names)
- GET  /healthz                — liveness
- GET  /version
- POST/DELETE /v1/pods         — informer-shaped ingest (JSON bodies per
- POST/DELETE /v1/nodes          api/serialize.py); POST is add-or-update,
- POST/DELETE /v1/podgroups      matching the informers' upsert handlers
- POST/DELETE /v1/queues         (event_handlers.go).  A LIST body batches:
- POST        /v1/priorityclasses  the whole batch applies under one cache
- POST/DELETE /v1/poddisruptionbudgets  lock acquisition + one dirty-version
- POST/DELETE /v1/persistentvolumes     advance ({"ok":true,"applied":N})
- GET  /v1/queues              — queue list w/ podgroup phase counts (the
                                 Queue CRD status the CLI renders, list.go:51)
- GET  /v1/jobs                — podgroup phases/conditions
- GET  /v1/bindings            — pod→node decisions made so far
- GET  /v1/guard               — result-integrity guard plane state (per-
                                 fast-path breaker, trips, audits, bundles)
- GET  /v1/trace               — cycle tracing plane: last cycle's span
                                 tree + flight-recorder ring stats
- GET  /v1/trace/dumps         — flight-recorder dump index; append
                                 /<name>/<trace.json|meta.json> to stream
                                 one dump's files (warm standbys and
                                 followers serve these too)
- GET  /v1/alerts              — guard trip-rate SLO alert state
- POST /v1/whatif              — batched what-if / admission probe against
                                 the resident snapshot (serve/; README
                                 "Query plane" for the schema)
- POST /v1/whatif/sweep        — server-side capacity sweep: binary-search
                                 the largest feasible replica count against
                                 ONE snapshot lease
- GET  /v1/replicate?since=N   — the replication stream (replicate/): the
                                 leader's KBR1 frame for record N+1, a
                                 synthesized full snapshot when N fell off
                                 the ring, or a heartbeat when caught up

`Run` mirrors app.Run (server.go:76-151): build cache + scheduler, start the
HTTP listener, then run the scheduling loop — optionally gated behind leader
election.  ``--follower http://leader:port`` boots the replicated read
plane instead (run_follower): no scheduler, no ingest — a pull loop applies
the leader's cycle deltas to a local device-resident replica and the SAME
serving stack answers /v1/whatif against it."""

from __future__ import annotations

import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kube_batch_tpu import metrics
from kube_batch_tpu.api import serialize
from kube_batch_tpu.api.pod import PersistentVolume, PodDisruptionBudget
from kube_batch_tpu.api.types import PodGroupPhase, queue_phase_counts
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.cmd.leader_election import LeaderElector, LostLeadership
from kube_batch_tpu.cmd.options import ServerOption
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.version import version_string

logger = logging.getLogger("kube_batch_tpu")


def _queue_status(cache: SchedulerCache) -> list:
    """Queue list with the CRD's status counts (types.go:211-223)."""
    with cache._lock:
        counts = {
            name: queue_phase_counts()
            for name in cache.queues
        }
        for job in cache.jobs.values():
            c = counts.get(job.queue)
            if c is None or job.pod_group is None:
                continue
            phase = job.pod_group.phase or PodGroupPhase.PENDING
            c[phase.value.lower()] = c.get(phase.value.lower(), 0) + 1
        return [
            {"name": name, "weight": q.weight, **counts[name]}
            for name, q in sorted(cache.queues.items())
        ]


def _job_status(cache: SchedulerCache) -> list:
    with cache._lock:
        rows = []
        for uid, job in sorted(cache.jobs.items()):
            pg = job.pod_group
            rows.append(
                {
                    "uid": uid,
                    "queue": job.queue,
                    "min_member": job.min_available,
                    "phase": (pg.phase.value if pg and pg.phase else "Pending"),
                    "running": pg.running if pg else 0,
                    "conditions": [
                        {"type": c.type, "status": c.status, "reason": c.reason,
                         "message": c.message}
                        for c in (pg.conditions if pg else [])
                    ],
                }
            )
        return rows


def _bindings(cache: SchedulerCache) -> list:
    with cache._lock:
        out = []
        for job in cache.jobs.values():
            for task in job.tasks.values():
                if task.node_name is not None:
                    out.append({"pod": task.key(), "node": task.node_name,
                                "status": task.status.name})
        return sorted(out, key=lambda r: r["pod"])


def make_handler(cache: SchedulerCache, query_plane=None):
    ingest = {
        # POST is add-or-update: update_pod is delete+add (event_handlers.go:116-130)
        "pods": (serialize.pod_from_dict, cache.update_pod, cache.delete_pod),
        "nodes": (serialize.node_from_dict, cache.add_node,
                  lambda n: cache.delete_node(n.name)),
        "podgroups": (serialize.pod_group_from_dict, cache.add_pod_group,
                      lambda pg: cache.delete_pod_group(pg.key())),
        "queues": (serialize.queue_from_dict, cache.add_queue,
                   lambda q: cache.delete_queue(q.name)),
        "priorityclasses": (serialize.priority_class_from_dict,
                            cache.add_priority_class,
                            lambda pc: cache.delete_priority_class(pc.name)),
        # legacy gang source (event_handlers.go:484-594)
        "poddisruptionbudgets": (
            lambda d: PodDisruptionBudget(**d), cache.add_pdb, cache.delete_pdb),
        # PV ledger ingest (the pv informer analog, cache.go:189-209); no-op
        # deletes/adds when the volume binder is the fake
        "persistentvolumes": (
            lambda d: PersistentVolume(**d),
            lambda pv: getattr(cache.volume_binder, "add_pv", lambda _: None)(pv),
            lambda pv: getattr(cache.volume_binder, "delete_pv", lambda _: None)(pv.name),
        ),
    }

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route to glog-analog logger
            logger.debug("http: " + fmt, *args)

        def _send(self, code: int, body: str, ctype="application/json"):
            self._send_bytes(code, body.encode(), ctype)

        def _send_bytes(self, code: int, data: bytes,
                        ctype="application/octet-stream"):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/metrics":
                self._send(200, metrics.render_prometheus(), "text/plain; version=0.0.4")
            elif self.path == "/healthz":
                self._send(200, "ok", "text/plain")
            elif self.path == "/version":
                self._send(200, version_string(), "text/plain")
            elif self.path == "/debug/stacks":
                # pprof goroutine-dump analog (main.go:25 net/http/pprof)
                import sys
                import traceback

                frames = sys._current_frames()
                out = []
                for tid, frame in frames.items():
                    out.append(f"--- thread {tid} ---")
                    out.extend(l.rstrip() for l in traceback.format_stack(frame))
                self._send(200, "\n".join(out), "text/plain")
            elif self.path.startswith("/debug/pprof"):
                # CPU-profile analog (?seconds=N): a SAMPLING profiler over
                # every thread via sys._current_frames — cProfile in this
                # handler would profile only the handler's own (sleeping)
                # thread.  Output: sample counts per stack, hottest first,
                # pprof-text-shaped.
                import math
                import sys as _sys
                import time as _time
                from collections import Counter
                from urllib.parse import parse_qs, urlparse

                q = parse_qs(urlparse(self.path).query)
                try:
                    seconds = float(q.get("seconds", ["5"])[0])
                except ValueError:
                    self._send(400, "seconds must be a number", "text/plain")
                    return
                if not math.isfinite(seconds) or seconds <= 0:
                    self._send(400, "seconds must be a positive finite number",
                               "text/plain")
                    return
                seconds = min(seconds, 60.0)
                interval = 0.01
                me = threading.get_ident()
                stacks: Counter = Counter()
                deadline = _time.monotonic() + seconds
                n_samples = 0
                while _time.monotonic() < deadline:
                    for tid, frame in _sys._current_frames().items():
                        if tid == me:
                            continue
                        # raw (code, lineno) tuples per frame: no linecache /
                        # FrameSummary work inside the sampling loop — stacks
                        # are formatted once at output time
                        key = []
                        f = frame
                        while f is not None and len(key) < 12:
                            key.append((f.f_code, f.f_lineno))
                            f = f.f_back
                        stacks[tuple(key)] += 1
                    n_samples += 1
                    # kbt: allow[KBT011] profiler sampling cadence — a
                    # fixed-interval sampler, not a retry/backoff loop
                    _time.sleep(interval)
                out = [
                    f"samples: {n_samples} over {seconds:.1f}s "
                    f"({len(stacks)} distinct stacks)",
                    "NOTE: wall-clock sampler — blocked/sleeping stacks count "
                    "the same as busy ones (a mostly-idle scheduler tops out "
                    "in its sleep/select frames); read busy stacks relative "
                    "to each other for the CPU picture",
                ]
                for key, count in stacks.most_common(40):
                    out.append(f"\n{count} samples ({100.0 * count / max(1, n_samples):.0f}%):")
                    out.extend(
                        f"  {code.co_filename.rsplit('/', 1)[-1]}:{lineno} "
                        f"{code.co_name}"
                        for code, lineno in reversed(key)
                    )
                self._send(200, "\n".join(out), "text/plain")
            elif self.path == "/v1/queues":
                self._send(200, json.dumps(_queue_status(cache)))
            elif self.path == "/v1/jobs":
                self._send(200, json.dumps(_job_status(cache)))
            elif self.path == "/v1/bindings":
                self._send(200, json.dumps(_bindings(cache)))
            elif self.path == "/v1/guard":
                # result-integrity guard plane state: per-fast-path breaker
                # (healthy|demoted|probing), trips, audits, bundle paths —
                # the operator's first stop when a trip alert fires
                from kube_batch_tpu.guard import guard_of

                self._send(200, json.dumps(guard_of(cache).state()))
            elif self.path == "/v1/trace":
                # cycle tracing plane: the last completed cycle's span tree
                # + the flight-recorder ring stats (obs/trace, obs/recorder)
                from kube_batch_tpu.obs.trace import tracer_of

                self._send(200, json.dumps(tracer_of(cache).state()))
            elif self.path == "/v1/trace/dumps" or self.path.startswith(
                "/v1/trace/dumps/"
            ):
                self._trace_dumps()
            elif self.path == "/v1/replicate" or self.path.startswith(
                "/v1/replicate?"
            ):
                self._replicate()
            elif self.path == "/v1/alerts":
                # guard trip-rate SLO alerts (obs/alerts): firing state,
                # windowed trip counts, thresholds
                from kube_batch_tpu.obs.alerts import alerts_of

                self._send(200, json.dumps(alerts_of(cache).state()))
            else:
                self._send(404, json.dumps({"error": "not found"}))

        def _body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        def _replicate(self):
            """The leader's replication publish endpoint: one KBR1 frame
            per pull, chosen by the follower's applied cursor (heartbeat
            when caught up, a synthesized full snapshot when the cursor
            fell off the ring — the delta-gap escalation)."""
            from urllib.parse import parse_qs, urlparse

            pub = getattr(cache, "replication", None)
            if pub is None:
                self._send(503, json.dumps(
                    {"error": "replication not enabled"}))
                return
            q = parse_qs(urlparse(self.path).query)
            try:
                since = int(q.get("since", ["-1"])[0])
            except ValueError:
                self._send(400, json.dumps(
                    {"error": "since must be an integer"}))
                return
            self._send_bytes(200, pub.record_for(since))

        def _trace_dumps(self):
            """Flight-recorder dump streaming: the index lists every dump
            this process published; /<name>/<trace.json|meta.json> streams
            one file.  Only names the recorder itself registered resolve —
            the dump list is the allow-list, so no path escapes it."""
            from kube_batch_tpu.obs.trace import tracer_of

            recorder = tracer_of(cache).recorder
            dumps = recorder.stats()["dumps"] if recorder is not None else []
            by_name = {os.path.basename(p): p for p in dumps}
            rest = self.path[len("/v1/trace/dumps"):].strip("/")
            if not rest:
                self._send(200, json.dumps({
                    "dumps": sorted(by_name),
                    "directory": recorder.directory if recorder else None,
                }))
                return
            parts = rest.split("/")
            root = by_name.get(parts[0])
            if root is None or len(parts) != 2 or parts[1] not in (
                "trace.json", "meta.json"
            ):
                self._send(404, json.dumps({"error": "no such dump file"}))
                return
            try:
                with open(os.path.join(root, parts[1]), "rb") as f:
                    self._send_bytes(200, f.read(), "application/json")
            except OSError as e:
                self._send(404, json.dumps({"error": str(e)}))

        def _ingest(self, delete: bool):
            kind = self.path.rsplit("/", 1)[-1]
            entry = ingest.get(kind)
            if entry is None:
                self._send(404, json.dumps({"error": f"unknown kind {kind}"}))
                return
            parse, add, remove = entry
            apply_fn = remove if delete else add
            try:
                body = self._body()
                if isinstance(body, list):
                    # batched ingest: a list body applies under ONE cache
                    # lock acquisition and ONE dirty-version advance
                    # (cache.ingest_batch) — high-QPS clients stop paying a
                    # lock round-trip (and a lease/delta token move) per
                    # pod.  The whole batch parses BEFORE any element
                    # applies: a malformed element rejects the batch, never
                    # half-applies it.
                    ops = [(apply_fn, parse(d)) for d in body]
                    applied = cache.ingest_batch(ops)
                    if applied < len(ops):
                        # an element that parsed but whose HANDLER raised:
                        # mirror the single-object path's 400, with the
                        # partial count so the client knows what landed
                        self._send(400, json.dumps({
                            "ok": False, "applied": applied,
                            "failed": len(ops) - applied}))
                        return
                    self._send(200, json.dumps(
                        {"ok": True, "applied": applied}))
                    return
                apply_fn(parse(body))
            except (TypeError, ValueError, KeyError) as e:
                self._send(400, json.dumps({"error": str(e)}))
                return
            self._send(200, json.dumps({"ok": True}))

        def do_POST(self):
            if self.path == "/v1/sync":
                # initial-sync barrier: a client that finished its re-list
                # signals the scheduler to start (WaitForCacheSync analog)
                cache.mark_synced()
                self._send(200, "{}")
                return
            if self.path == "/v1/whatif":
                self._whatif(lambda body: query_plane.submit(body))
                return
            if self.path == "/v1/whatif/sweep":
                # server-side capacity sweep: binary-search max replicas
                # against ONE lease (the autoscaler's "how many fit" ask)
                self._whatif(lambda body: query_plane.submit_sweep(body))
                return
            self._ingest(delete=False)

        def _whatif(self, submit):
            """The query plane's serving endpoint: validate, enqueue into
            the micro-batcher, block this handler thread on the per-request
            future (ThreadingHTTPServer gives every request its own thread,
            so concurrent handlers pile into ONE probe dispatch)."""
            from concurrent.futures import TimeoutError as FutureTimeout

            from kube_batch_tpu.serve.batcher import QueueFull
            from kube_batch_tpu.serve.plane import WhatifError

            if query_plane is None:
                self._send(503, json.dumps(
                    {"error": "query plane not enabled"}))
                return
            try:
                body = self._body()
            except (ValueError, json.JSONDecodeError) as e:
                self._send(400, json.dumps({"error": str(e)}))
                return
            try:
                fut = submit(body)
                resp = fut.result(timeout=query_plane.dispatch_timeout + 8)
            except WhatifError as e:
                self._send(e.status, json.dumps({"error": str(e)}))
                return
            except QueueFull as e:
                self._send(503, json.dumps({"error": str(e)}))
                return
            except (FutureTimeout, TimeoutError):
                # abandon the queued probe: a cancelled future is skipped
                # at flush (no device time, no verdict counters for an
                # answer nobody receives); cancel() failing means the
                # flush is resolving it right now — the answer is simply
                # discarded
                fut.cancel()
                self._send(503, json.dumps(
                    {"error": "whatif probe timed out"}))
                return
            self._send(200, json.dumps(resp))

        def do_DELETE(self):
            self._ingest(delete=True)

    return Handler


class AdminServer:
    """The --listen-address listener (server.go:96-99).  With a
    ``query_plane`` the same listener serves ``POST /v1/whatif`` (the
    serve/ read path) beside the admin/ingest API."""

    def __init__(self, cache: SchedulerCache, host: str = "127.0.0.1",
                 port: int = 0, query_plane=None):
        self.query_plane = query_plane
        self.httpd = ThreadingHTTPServer(
            (host, port), make_handler(cache, query_plane=query_plane)
        )
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="admin-http"
        )
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        # bounded join: serve_forever returns once shutdown() lands, so the
        # acceptor thread exits promptly — but don't hang stop() on a
        # wedged in-flight handler (the thread is daemon either way)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class TokenBucket:
    """The client-side 50 QPS / 100-burst throttle of the reference
    (options.go:32-33, server.go:69-70). The reference has ONE rest.Config —
    binder, evictor, and status updater all ride the same rate limiter — so
    one bucket instance must be shared across every egress wrapper."""

    def __init__(self, qps: float, burst: int):
        import time as _time

        self._qps = qps
        self._burst = float(burst)
        self._tokens = float(burst)
        self._last = _time.monotonic()
        self._lock = threading.Lock()
        self._time = _time

    def take(self) -> None:
        """Reserve a token under the lock, sleep OUTSIDE it. The balance may
        go negative: each waiter's debt position is its reservation, and its
        wait is the time until its own token mints — so concurrent waiters
        (the 16-worker status pool, the binder, the pv-writes thread) sleep
        in parallel instead of serializing behind whoever holds the lock
        (ADVICE.md #3). Aggregate rate is unchanged: tokens still mint at
        qps with a burst cap, and reservations are FIFO by lock order."""
        with self._lock:
            now = self._time.monotonic()
            self._tokens = min(self._burst, self._tokens + (now - self._last) * self._qps)
            self._last = now
            self._tokens -= 1.0
            wait = -self._tokens / self._qps if self._tokens < 0.0 else 0.0
        if wait > 0.0:
            self._time.sleep(wait)


class RateLimitedBackend:
    """Token-bucket throttle applied to the Binder/Evictor seam. Pass a shared
    TokenBucket via `bucket` so multiple seams drain one budget; qps/burst
    kwargs build a private bucket (single-seam deployments and tests)."""

    def __init__(self, backend, qps: float = 0.0, burst: int = 0,
                 bucket: Optional[TokenBucket] = None):
        if bucket is None and qps <= 0.0:
            raise ValueError("RateLimitedBackend needs a shared bucket or qps > 0")
        self._backend = backend
        self._bucket = bucket if bucket is not None else TokenBucket(qps, burst)

    def _take(self) -> None:
        self._bucket.take()

    def bind(self, pod, hostname):
        self._take()
        return self._backend.bind(pod, hostname)

    def evict(self, pod):
        self._take()
        return self._backend.evict(pod)


class RateLimitedStatusUpdater(RateLimitedBackend):
    """The same token bucket on the StatusUpdater seam (the reference's
    status writes ride the identical throttled rest.Config client,
    server.go:69-70).  parallel_safe passes through: the bucket is
    thread-safe, so the close-time jobUpdater pool may call concurrently."""

    @property
    def parallel_safe(self):
        return getattr(self._backend, "parallel_safe", False)

    def degraded(self):
        """Forward the writeback-breaker probe: without this passthrough
        the cache's degraded-cycle shedding would never see the wrapped
        K8sBackend's open breaker."""
        probe = getattr(self._backend, "degraded", None)
        return bool(probe()) if probe is not None else False

    def update_pod_group(self, pg):
        self._take()
        return self._backend.update_pod_group(pg)

    def update_pod_condition(self, pod, cond):
        self._take()
        return self._backend.update_pod_condition(pod, cond)

    def update_queue_status(self, name, counts):
        self._take()
        return self._backend.update_queue_status(name, counts)


def run_warm_standby(elector, sched: Scheduler, cache: SchedulerCache,
                     max_takeovers: Optional[int] = None) -> None:
    """Leadership loop with in-place warm standby (BEYOND the reference's
    crash-on-loss): a lost lease stops the scheduling loop but NOT the
    process — the jit-compiled solve executables and the device-resident
    snapshot stay alive — and the elector re-contends. On every
    (re-)acquire the cache recovers through ``failover_recover``: rebuild
    from the pod store (the watch keeps feeding it while standby), then
    revalidate-or-drop the resident device cache, so a failover normally
    pays NO recompile and NO full re-upload.

    ``max_takeovers`` bounds the loop for tests; production runs forever
    (a supervisor can still kill the process for a hard restart)."""
    takeovers = 0

    def lead():
        # recovery runs AFTER the lease is won (elector.run invokes this
        # only as leader) and before the first cycle of the new reign
        if takeovers > 1:
            cache.failover_recover()
        sched.run_forever()

    while max_takeovers is None or takeovers < max_takeovers:
        takeovers += 1
        try:
            elector.run(lead, on_stopped_leading=sched.stop)
            return  # clean stop (sched.stop() by other means)
        except LostLeadership:
            logger.warning(
                "leadership lost; demoting to warm standby (resident cache "
                "kept) and re-contending")
            elector.reset()


def run_follower(opt: ServerOption) -> None:
    """The replicated read plane's process loop (--follower URL): no
    scheduler, no ingest — a pull thread subscribes to the leader's
    /v1/replicate stream, applies cycle deltas to a local device-resident
    ColumnStore replica, and the admin listener serves the SAME /v1/whatif
    stack (plus sweep/trace/metrics) against it.  Horizontal read scale:
    each follower owns its own devices and probe executables, so serving
    QPS adds up across follower processes while the leader pays one encode
    per cycle regardless of fan-out."""
    from kube_batch_tpu.envutil import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()

    from kube_batch_tpu.replicate.follower import (
        FollowerCache,
        ReplicationFollower,
    )
    from kube_batch_tpu.serve.plane import QueryPlane

    cache = FollowerCache()
    query_plane = QueryPlane(cache, prewarm=True)
    follower = ReplicationFollower(opt.follower, cache=cache,
                                   query_plane=query_plane)
    host, port = opt.listen_host_port
    admin = AdminServer(cache, host, port, query_plane=query_plane)
    admin.start()
    logger.info("follower serving on %s:%d, replicating from %s", host,
                admin.port, opt.follower)
    follower.start()
    try:
        follower.join()
    finally:
        follower.stop()
        query_plane.close()
        admin.stop()


def run(opt: ServerOption) -> None:
    """app.Run (server.go:76-151): metrics/admin listener up front, then the
    scheduling loop — behind leader election when enabled. Option validation
    and --version live in cmd/main.py."""
    if opt.follower:
        return run_follower(opt)
    from kube_batch_tpu.envutil import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()  # restart re-pays no solve compiles

    from kube_batch_tpu.cache.fake import FakeBinder, FakeEvictor

    from kube_batch_tpu.cache.volume import StandalonePVBinder

    # with a k8s front end (--master), binds/evictions write back to the
    # apiserver (pods/binding POST, pod DELETE); standalone deployments keep
    # the recording fakes behind the ingest API
    k8s_mode = opt.master.startswith("http")
    # one bucket for ALL egress (binds + evictions + status writes): the
    # reference's writes share a single throttled rest.Config (server.go:69-70)
    bucket = TokenBucket(opt.kube_api_qps, opt.kube_api_burst)
    if k8s_mode:
        from kube_batch_tpu.cache.volume import K8sPVLedger
        from kube_batch_tpu.k8s.bind import K8sBackend
        from kube_batch_tpu.k8s.transport import ApiTransport, in_cluster_auth

        auth = in_cluster_auth()
        backend = K8sBackend(opt.master, **auth)
        binder, evictor = backend, backend
        status_updater = RateLimitedStatusUpdater(backend, bucket=bucket)
        # pv/pvc/storageclass watches feed this ledger; its claimRef /
        # selected-node PATCHes ride the backend's own transport AND the
        # same shared token bucket as every other egress write
        volume_binder = K8sPVLedger(
            transport=getattr(backend, "transport", None)
            or ApiTransport(opt.master, role="pv", **auth),
            bucket=bucket,
        )
    else:
        binder, evictor = FakeBinder(), FakeEvictor()
        status_updater = None  # cache default: recording fake
        # real PV ledger behind /v1/persistentvolumes
        volume_binder = StandalonePVBinder()
    cache = SchedulerCache(
        scheduler_name=opt.scheduler_name,
        default_queue=opt.default_queue,
        binder=RateLimitedBackend(binder, bucket=bucket),
        evictor=RateLimitedBackend(evictor, bucket=bucket),
        status_updater=status_updater,
        volume_binder=volume_binder,
        resolve_priority=opt.enable_priority_class,
    )
    on_cycle_end = None
    if opt.state_file:
        from kube_batch_tpu.cache.persistence import load_state, save_state

        if load_state(cache, opt.state_file):
            logger.info("restored cluster state from %s", opt.state_file)
            cache.mark_synced()  # the state file IS the initial listing
        on_cycle_end = lambda: save_state(cache, opt.state_file)  # noqa: E731
    sched = Scheduler(
        cache,
        conf_path=opt.scheduler_conf or None,
        schedule_period=opt.schedule_period,
        on_cycle_end=on_cycle_end,
    )
    # the read-side query plane (serve/): /v1/whatif rides the same
    # listener; KB_WHATIF=0 opts out (e.g. a memory-constrained part where
    # the probe's compiled specializations are unwelcome)
    query_plane = None
    if os.environ.get("KB_WHATIF", "").strip().lower() not in (
        "0", "false", "off", "no"
    ):
        from kube_batch_tpu.serve.plane import QueryPlane

        query_plane = QueryPlane(cache, prewarm=True)
        # the replication publisher (replicate/): each cycle's resident
        # swap goes out as a wire delta on GET /v1/replicate for follower
        # read replicas; KB_REPLICATE=0 opts out.  Publisher encode runs
        # overlapped like the writeback stage (scheduler.drain_pipeline
        # joins it), so the leader's cycle pays ~one host diff.
        if os.environ.get("KB_REPLICATE", "").strip().lower() not in (
            "0", "false", "off", "no"
        ):
            from kube_batch_tpu.obs.trace import tracer_of
            from kube_batch_tpu.replicate.publisher import ReplicationPublisher

            cache.replication = ReplicationPublisher(tracer=tracer_of(cache))
    host, port = opt.listen_host_port
    admin = AdminServer(cache, host, port, query_plane=query_plane)
    admin.start()
    logger.info("admin/metrics listening on %s:%d (whatif %s)", host,
                admin.port, "on" if query_plane is not None else "off")
    # Kubernetes front end (cache.go:256-339 informers): --master pointing
    # at an apiserver URL starts the list+watch adapter.  start() BLOCKS
    # until every resource finished its initial LIST and then marks the
    # cache synced — the reference's unconditional WaitForCacheSync gate
    # before the first cycle (scheduler.go:64); scheduling against a
    # half-seeded cache would overstate node idle capacity.
    watcher = None
    if k8s_mode:
        from kube_batch_tpu.k8s.watch import WatchAdapter

        watcher = WatchAdapter(cache, api_server=opt.master, **auth)
        logger.info("seeding from kubernetes apiserver %s ...", opt.master)
        watcher.start()
        logger.info("kubernetes watch adapter synced against %s", opt.master)
    # WaitForCacheSync (scheduler.go:64 / cache.go:363-384): give clients a
    # bounded window to land their initial listing (or POST /v1/sync) before
    # the first cycle; on timeout schedule whatever arrived. Off by default —
    # only deployments whose clients signal the barrier opt in.
    if opt.cache_sync_timeout > 0:
        cache.wait_for_cache_sync(timeout=opt.cache_sync_timeout)
    try:
        if opt.enable_leader_election:
            if k8s_mode:
                # cross-host HA rides the cluster API: a coordination.k8s.io
                # Lease in --lock-object-namespace (the reference's ConfigMap
                # resourcelock, server.go:106-151) — works across nodes with
                # no shared filesystem
                from kube_batch_tpu.cmd.leader_election import K8sLeaseElector
                from kube_batch_tpu.k8s.transport import ApiTransport

                elector = K8sLeaseElector(
                    ApiTransport(opt.master, role="lease", **auth),
                    namespace=opt.lock_object_namespace,
                )
            else:
                elector = LeaderElector(opt.lock_object_namespace)
            if opt.leader_warm_standby:
                run_warm_standby(elector, sched, cache)
            else:
                # on lease loss the elector stops the loop so run() can
                # raise — the crash-on-loss contract (server.go:145); a
                # supervisor restarts the process as a standby
                elector.run(sched.run_forever, on_stopped_leading=sched.stop)
        else:
            sched.run_forever()
    finally:
        if watcher is not None:
            watcher.stop()
        if query_plane is not None:
            query_plane.close()
        pub = getattr(cache, "replication", None)
        if pub is not None:
            pub.close()
        admin.stop()
