"""Process entry layer (cmd/kube-batch in the reference)."""
