"""Process entry (cmd/kube-batch/main.go): `python -m kube_batch_tpu.cmd.main`."""

from __future__ import annotations

import logging
import sys

from kube_batch_tpu.cmd import options, server
from kube_batch_tpu.version import version_string


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(levelname).1s%(asctime)s %(name)s] %(message)s",
    )
    opt = options.parse(argv)
    if opt.print_version:
        print(version_string())
        return 0
    try:
        opt.check_option_or_die()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    server.run(opt)  # validated: run() itself doesn't re-check
    return 0


if __name__ == "__main__":
    sys.exit(main())
