"""Guard diagnostics bundles — self-contained trip captures for offline
fast-vs-oracle triage.

On every guard trip the dispatching action dumps the EXACT solve problem
it condemned: the device snapshot's columns (the post-resident-swap arrays
the solve consumed — a corrupted resident word is captured corrupted),
the solve configuration, the compaction plan, the knob environment, and
the violation report.  The write uses cache/persistence.py's atomic idiom
(write into a temp sibling, ``os.replace`` into place) so a crash mid-dump
never leaves a half bundle that replays differently.

``python -m kube_batch_tpu.sim --replay-bundle <dir>`` reloads a bundle
and re-runs the condemned program AND its oracle (KB_TOPK=0 / full-matrix
/ use_pallas off) on the captured snapshot, sentinel-fused both ways —
deterministic reproduction of the trip without the cluster, the workload,
or the timing that produced it.

Bundle layout: ``<dir>/meta.json`` (config, knobs, violation report,
invariant names) + ``<dir>/arrays.npz`` (every DeviceSnapshot field, plus
``pend_rows`` when the compacted path was engaged).  ScoreWeights
``extra_rows`` (registered score functions) are not serializable — the
replay notes their names and runs without them.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Dict, Optional

import numpy as np

logger = logging.getLogger("kube_batch_tpu")

_KNOBS = (
    "KB_TOPK", "KB_SHARD_MAP", "KB_SHARD", "KB_TASK_SHARDS", "KB_PALLAS",
    "KB_GUARD", "KB_AUDIT_EVERY", "KB_GUARD_COOLDOWN", "KB_DEVICE_CACHE",
    "KB_SNAPSHOT_DELTA", "KB_PIPELINE", "JAX_PLATFORMS",
)


def bundle_dir() -> str:
    return os.environ.get("KB_GUARD_DIR", "").strip() or "guard-bundles"


def _weights_dict(weights) -> Dict:
    d = weights._asdict()
    extra = d.pop("extra_rows", ()) or ()
    d["extra_row_names"] = [name for (name, _fn, _w) in extra]
    return d


def _config_dict(config) -> Dict:
    d = config._asdict()
    w = d.pop("weights", None)
    if w is not None:
        d["weights"] = _weights_dict(w)
    return d


def dump_bundle(action: str, snap, config, report: Dict,
                pend_rows: Optional[np.ndarray] = None,
                directory: Optional[str] = None) -> str:
    """Write one diagnostics bundle; returns its path.  ``snap`` is the
    DeviceSnapshot the condemned solve consumed (device or host-backed —
    read back here, once, on the rare trip path)."""
    import jax

    from kube_batch_tpu.ops.invariants import INVARIANT_NAMES

    root = directory or bundle_dir()
    os.makedirs(root, exist_ok=True)
    # kbt: allow[KBT010] trip-path readback — the bundle must capture the
    # exact (possibly corrupted) device bytes the solve consumed
    host = jax.device_get(snap)
    arrays = {f: np.asarray(getattr(host, f)) for f in snap._fields}
    if pend_rows is not None:
        arrays["pend_rows"] = np.asarray(pend_rows)
    meta = {
        "schema": 1,
        "action": action,
        "config": _config_dict(config),
        "config_kind": type(config).__name__,
        "report": report,
        "invariant_names": list(INVARIANT_NAMES),
        "knobs": {k: os.environ.get(k, "") for k in _KNOBS},
        "has_pend_rows": pend_rows is not None,
    }
    # atomic publish: build the whole bundle in a temp sibling dir, then
    # one rename — the persistence.py idiom, directory-shaped
    tmp = tempfile.mkdtemp(dir=root, prefix=".tmp-bundle-")
    try:
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)
        np.savez(os.path.join(tmp, "arrays.npz"), **arrays)
        serial = 0
        while True:
            final = os.path.join(root, f"trip-{action}-{serial:04d}")
            if not os.path.exists(final):
                try:
                    os.replace(tmp, final)
                    break
                except OSError:
                    pass  # lost the race to a concurrent dump — next serial
            serial += 1
            if serial > 9999:
                raise OSError("guard bundle directory full")
    except BaseException:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise
    logger.warning("guard diagnostics bundle written: %s", final)
    return final


def load_bundle(path: str):
    """(DeviceSnapshot of host arrays, meta dict, pend_rows|None)."""
    from kube_batch_tpu.api.snapshot import DeviceSnapshot

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    with np.load(os.path.join(path, "arrays.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    pend_rows = arrays.pop("pend_rows", None)
    snap = DeviceSnapshot(**{f: arrays[f] for f in DeviceSnapshot._fields})
    return snap, meta, pend_rows


def _rebuild_config(meta: Dict):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.ops.eviction import EvictConfig
    from kube_batch_tpu.ops.scoring import ScoreWeights

    d = dict(meta["config"])
    w = d.pop("weights", None)
    dropped = []
    if w is not None:
        w = dict(w)
        dropped = w.pop("extra_row_names", [])
        d["weights"] = ScoreWeights(**w)
    cls = AllocateConfig if meta["config_kind"] == "AllocateConfig" else EvictConfig
    return cls(**d), dropped


def replay_bundle(path: str) -> Dict:
    """Re-run a bundle's condemned program and its oracle, sentinel-fused
    both ways, on the captured snapshot — the deterministic offline
    fast-vs-oracle triage.  Returns a JSON-shaped report; ``reproduced``
    is True when the replay re-derives an integrity failure (a nonzero
    sentinel verdict on the fast program, or a fast-vs-oracle mismatch)."""
    import jax

    from kube_batch_tpu.ops.invariants import (
        INVARIANT_NAMES,
        allocate_sentinel_solve,
        allocate_topk_sentinel_solve,
        evict_sentinel_solve,
    )

    snap_host, meta, pend_rows = load_bundle(path)
    config, dropped_rows = _rebuild_config(meta)
    snap = jax.tree_util.tree_map(jax.numpy.asarray, snap_host)
    out: Dict = {
        "bundle": path,
        "action": meta["action"],
        "original_report": meta["report"],
        "weights_extra_rows_dropped": dropped_rows,
    }
    # device-vs-host divergence (the eligibility cross-check): the bundle
    # records the HOST's checksum at trip time; the captured snapshot is
    # the DEVICE's — a mismatch reproduces a flipped status/pending word
    # that the device-side invariants alone cannot see
    host_ck = meta["report"].get("host_checksum")
    ck_mismatch = False
    if host_ck is not None:
        from kube_batch_tpu.ops.invariants import eligibility_checksum

        dev_ck = int(jax.device_get(eligibility_checksum(snap))) & 0xFFFFFFFF
        ck_mismatch = dev_ck != (int(host_ck) & 0xFFFFFFFF)
        out["host_checksum_mismatch"] = ck_mismatch

    def _hist(h):
        h = np.asarray(h)
        return {n: int(c) for n, c in zip(INVARIANT_NAMES, h) if c}

    if meta["config_kind"] == "EvictConfig":
        res, v, h, _e = evict_sentinel_solve(snap, config)
        claim, evicted, verdict = jax.device_get(
            (res.claim_node, res.evicted, v)
        )
        out.update(
            fast_verdict=int(verdict), fast_violations=_hist(jax.device_get(h)),
            claims=int((np.asarray(claim) >= 0).sum()),
            victims=int(np.asarray(evicted).sum()),
            reproduced=bool(int(verdict) != 0 or ck_mismatch),
        )
        return out

    # allocate-shaped: fast (as captured) vs oracle (every knob off)
    if pend_rows is not None and config.topk > 0:
        fast_res, fv, fh, _e = allocate_topk_sentinel_solve(
            snap, jax.numpy.asarray(pend_rows), config
        )
        fast_name = f"topk[K={config.topk}]"
    else:
        fast_res, fv, fh, _e = allocate_sentinel_solve(snap, config)
        fast_name = "full"
    oracle_cfg = config._replace(topk=0, use_pallas=False)
    orc_res, ov, oh, _oe = allocate_sentinel_solve(snap, oracle_cfg)
    (f_assigned, f_pipe, fv, fh, o_assigned, o_pipe, ov, oh) = jax.device_get(
        (fast_res.assigned, fast_res.pipelined, fv, fh,
         orc_res.assigned, orc_res.pipelined, ov, oh)
    )
    mismatch_rows = np.flatnonzero(
        (np.asarray(f_assigned) != np.asarray(o_assigned))
        | (np.asarray(f_pipe) != np.asarray(o_pipe))
    )
    out.update(
        fast_program=fast_name,
        fast_verdict=int(fv), fast_violations=_hist(fh),
        oracle_verdict=int(ov), oracle_violations=_hist(oh),
        fast_vs_oracle_mismatch_rows=mismatch_rows[:64].tolist(),
        fast_vs_oracle_mismatches=int(mismatch_rows.size),
        reproduced=bool(int(fv) != 0 or mismatch_rows.size or ck_mismatch),
    )
    return out
