"""GuardPlane — the per-fast-path health breaker (guard plane tier 3).

Generalizes the transport layer's :class:`k8s.transport.CircuitBreaker`
discipline from "is this apiserver reachable" to "is this solve fast path
producing lawful results": each demotable fast path (KB_TOPK compaction,
the shard_map collective bodies, the Pallas round head) carries a health
state —

    healthy ──trip──▶ demoted ──KB_GUARD_COOLDOWN clean cycles──▶ probing
       ▲                 ▲                                           │
       └── clean probe ──┘◀──────────── trip during probe ───────────┘

A demoted path's dispatches run the ORACLE program (KB_TOPK=0 / pjit /
use_pallas=False — the same knobs the tests pin bit-exactness against);
``probing`` is the half-open state: the next dispatch runs the fast path
again under the sentinel, and one clean engaged cycle re-promotes.  Time
is counted in SCHEDULING CYCLES (the Scheduler's loop calls
:meth:`end_cycle`), not wall seconds, so the breaker is deterministic
under the simulator's virtual clock — the same reasoning that put the
resync queue's backoff in repair ticks.

Every trip additionally invokes the registered heal hook (the actions pass
``ColumnStore.drop_resident``): an HBM bit-flip in a resident column is
cured by the cold full re-upload the next dispatch pays, so the system
self-heals the data while demotion guards the code paths.  A trip also
dumps a diagnostics bundle (guard/bundle.py) when the caller supplies a
``dump`` thunk — lazily, so the snapshot serialization cost is only paid
on the (rare) trip path.

Thread-safety: every state transition happens under one leaf lock;
nothing blocks under it (bundle dumps and heals run outside).  A trip
racing an in-flight audit, or a mid-cycle conf reload swapping the
session's config, cannot wedge the state machine — tests/test_guard.py
pins both races.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence

from kube_batch_tpu import metrics
from kube_batch_tpu.envutil import env_int

logger = logging.getLogger("kube_batch_tpu")

#: the demotable fast paths — each has a per-dispatch oracle knob the
#: demotion flips (actions/allocate.py dispatch + parallel/mesh.py impl
#: selection + the session's use_pallas flag).  "warm" is the carried
#: candidate-table path (KB_WARM): demotion pins the compacted solve to
#: its cold per-solve build, and the trip heal drops the carried table
#: with the resident caches (ColumnStore.drop_resident)
FAST_PATHS = ("topk", "shard_map", "pallas", "warm")

HEALTHY, DEMOTED, PROBING = "healthy", "demoted", "probing"


class PathHealth:
    """One fast path's breaker state (mutated under the plane's lock)."""

    def __init__(self, name: str):
        self.name = name
        self.state = HEALTHY
        self.clean_cycles = 0   # clean cycles since demotion
        self.trips = 0
        self.promotions = 0

    def snapshot(self) -> Dict:
        return {
            "state": self.state,
            "clean_cycles": self.clean_cycles,
            "trips": self.trips,
            "promotions": self.promotions,
        }


class GuardPlane:
    def __init__(self, enabled: Optional[bool] = None,
                 audit_every: Optional[int] = None,
                 cooldown: Optional[int] = None,
                 bundle_dir: Optional[str] = None):
        if enabled is None:
            enabled = os.environ.get("KB_GUARD", "").strip().lower() not in (
                "0", "false", "off", "no"
            )
        self.enabled = enabled
        self.audit_every = (
            audit_every if audit_every is not None
            else env_int("KB_AUDIT_EVERY", 64)
        )
        self.cooldown = (
            cooldown if cooldown is not None
            else max(1, env_int("KB_GUARD_COOLDOWN", 8))
        )
        self.bundle_dir = bundle_dir  # None → guard/bundle.py's env default
        self._lock = threading.Lock()
        self.paths: Dict[str, PathHealth] = {
            name: PathHealth(name) for name in FAST_PATHS
        }
        # per-action dispatch counters (the audit cadence) — dispatches,
        # not cycles, so direct action invocation (bench, tests) still
        # audits on schedule
        self._dispatches: Dict[str, int] = {}
        # engagement/trip bookkeeping for the current cycle
        self._cycle_engaged: set = set()
        self._cycle_tripped: set = set()
        self._ever_engaged: set = set()  # fast paths seen in this process
        self.cycle = 0
        # lifetime diagnostics (the sim report + tests read these)
        self.trips_total = 0
        self.failed_closed = 0      # condemned solves discarded
        self.audits_run = 0
        self.audits_mismatched = 0
        self.bundles: List[str] = []
        self.trip_log: List[Dict] = []
        self.cycle_of_last_trip = -1

    @classmethod
    def from_env(cls) -> "GuardPlane":
        return cls()

    # ------------------------------------------------------------------
    # dispatch-side queries
    # ------------------------------------------------------------------
    def allow(self, path: str) -> bool:
        """May this fast path run?  Demoted paths answer False (the
        dispatch selects the oracle); probing paths answer True — the
        half-open probe runs under the sentinel."""
        if not self.enabled:
            return True
        with self._lock:
            ph = self.paths.get(path)
            return ph is None or ph.state != DEMOTED

    def audit_due(self, action: str) -> bool:
        """True on every KB_AUDIT_EVERY-th dispatch of ``action`` — the
        shadow-oracle cadence.  Counted per dispatch (not per cycle) so
        direct action invocation still audits."""
        if not self.enabled or self.audit_every <= 0:
            return False
        with self._lock:
            n = self._dispatches.get(action, 0) + 1
            self._dispatches[action] = n
            return n % self.audit_every == 0

    # ------------------------------------------------------------------
    # verdict / audit consumption (the actions' choke points)
    # ------------------------------------------------------------------
    def consume_verdict(self, action: str, engaged: Sequence[str],
                        verdict: int, hist=None, detail: str = "",
                        dump: Optional[Callable[[], str]] = None,
                        heal: Optional[Callable[[], None]] = None) -> bool:
        """Record one sentinel verdict.  Returns True when the action may
        apply the result; False = the solve is condemned and the action
        must FAIL CLOSED (discard, dispatch nothing)."""
        if not self.enabled:
            return True
        with self._lock:
            self._ever_engaged.update(engaged)
        if int(verdict) == 0:
            with self._lock:
                self._cycle_engaged.update(engaged)
            return True
        with self._lock:
            self.failed_closed += 1
        self.trip(action, engaged, reason="invariant",
                  detail=detail or f"verdict={int(verdict)}",
                  hist=hist, dump=dump, heal=heal)
        return False

    def note_audit(self, action: str, engaged: Sequence[str], matched: bool,
                   detail: str = "",
                   dump: Optional[Callable[[], str]] = None,
                   heal: Optional[Callable[[], None]] = None) -> None:
        """Record one shadow-oracle comparison (tier 2)."""
        if not self.enabled:
            return
        with self._lock:
            self.audits_run += 1
        metrics.register_guard_audit("match" if matched else "mismatch")
        if matched:
            with self._lock:
                self._cycle_engaged.update(engaged)
            return
        with self._lock:
            self.audits_mismatched += 1
        self.trip(action, engaged, reason="audit", detail=detail,
                  dump=dump, heal=heal)

    def trip(self, action: str, engaged: Sequence[str], reason: str,
             detail: str = "", hist=None,
             dump: Optional[Callable[[], str]] = None,
             heal: Optional[Callable[[], None]] = None) -> None:
        """One integrity trip: demote the engaged fast paths, self-heal the
        resident data, dump the diagnostics bundle.  Idempotent per path —
        a second trip in the same cycle (the audit racing the sentinel)
        just re-confirms the demotion."""
        with self._lock:
            self.trips_total += 1
            self.cycle_of_last_trip = self.cycle
            targets = [n for n in engaged if n in self.paths]
            if not targets:
                # unattributable trip (e.g. a corrupted resident column
                # caught by a full-matrix solve's sentinel): conservatively
                # demote every non-demoted fast path that has engaged in
                # this process — a PROBING path's half-open window failed
                # too — the oracles run until clean cycles prove health,
                # and the heal hook cures the data either way
                targets = sorted(
                    p for p in self._ever_engaged
                    if self.paths[p].state != DEMOTED
                )
            record = {
                "cycle": self.cycle, "action": action, "reason": reason,
                "engaged": list(engaged), "demoted": list(targets),
                "detail": detail,
                "hist": list(map(int, hist)) if hist is not None else None,
            }
            self.trip_log.append(record)
            for name in targets:
                ph = self.paths[name]
                ph.trips += 1
                ph.state = DEMOTED
                ph.clean_cycles = 0
                self._cycle_tripped.add(name)
                metrics.set_guard_path_demoted(name, 1)
        metrics.register_guard_trip(action, reason)
        logger.error(
            "guard plane trip (%s/%s): %s — failing closed; demoted %s",
            action, reason, detail, targets or "no fast path",
        )
        # outside the lock: the heal touches the column store, the dump
        # serializes the snapshot and writes files.  A trip is also the
        # flight recorder's primary trigger — the cycle trace trees around
        # the condemned solve dump beside the guard bundle (obs/recorder).
        flight = getattr(getattr(self, "host_cache", None),
                         "flight_recorder", None)
        if flight is not None:
            try:
                flight.trigger(
                    "guard_trip", detail=f"{action}/{reason}: {detail}"
                )
            except Exception:  # noqa: BLE001 — diagnostics only
                logger.exception("flight-recorder trigger failed")
        if heal is not None:
            try:
                heal()
            except Exception:  # noqa: BLE001 — healing must not kill the cycle
                logger.exception("guard resident heal failed")
        if dump is not None:
            try:
                path = dump()
                if path:
                    with self._lock:
                        record["bundle"] = path
                        self.bundles.append(path)
            except Exception:  # noqa: BLE001 — diagnostics only
                logger.exception("guard bundle dump failed")

    # ------------------------------------------------------------------
    # cycle clock (Scheduler._cycle calls this once per cycle)
    # ------------------------------------------------------------------
    def end_cycle(self) -> None:
        """Advance the breaker clock: demoted paths accrue clean cycles
        toward their half-open probe; a probing path that ran engaged and
        clean this cycle re-promotes."""
        if not self.enabled:
            return
        with self._lock:
            self.cycle += 1
            for name, ph in self.paths.items():
                if name in self._cycle_tripped:
                    continue  # trip() already reset this path
                if ph.state == DEMOTED:
                    ph.clean_cycles += 1
                    if ph.clean_cycles >= self.cooldown:
                        ph.state = PROBING
                        logger.info(
                            "guard path %s half-open after %d clean cycles",
                            name, ph.clean_cycles,
                        )
                elif ph.state == PROBING and name in self._cycle_engaged:
                    ph.state = HEALTHY
                    ph.promotions += 1
                    metrics.set_guard_path_demoted(name, 0)
                    logger.info("guard path %s re-promoted (clean probe)",
                                name)
            self._cycle_engaged.clear()
            self._cycle_tripped.clear()

    def trip_series(self, since: int):
        """(cycle, trip_log[since:], new_len) under the plane's lock — the
        alert evaluator's incremental read (obs/alerts.py)."""
        with self._lock:
            return self.cycle, list(self.trip_log[since:]), len(self.trip_log)

    # ------------------------------------------------------------------
    def state(self) -> Dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "cycle": self.cycle,
                "cooldown": self.cooldown,
                "audit_every": self.audit_every,
                "trips_total": self.trips_total,
                "failed_closed": self.failed_closed,
                "audits_run": self.audits_run,
                "audits_mismatched": self.audits_mismatched,
                "bundles": list(self.bundles),
                "paths": {n: p.snapshot() for n, p in self.paths.items()},
            }


#: serializes the lazy attach below — GET /v1/guard (HTTP handler threads)
#: and the cycle's first dispatch can race it, and an unsynchronized
#: check-then-act could overwrite a plane that already holds breaker state
_ATTACH_LOCK = threading.Lock()


def guard_of(cache) -> GuardPlane:
    """THE per-cache guard plane accessor: every dispatch site goes through
    here, so the plane attaches lazily on first use and the whole pipeline
    (allocate, reclaim, preempt, backfill, enqueue) shares one breaker
    state per scheduler cache."""
    gp = getattr(cache, "guard_plane", None)
    if gp is None:
        with _ATTACH_LOCK:
            gp = getattr(cache, "guard_plane", None)
            if gp is None:
                gp = GuardPlane.from_env()
                # back-pointer for the flight-recorder trigger (trip());
                # the plane's own state machine never reads through it
                gp.host_cache = cache
                cache.guard_plane = gp
    return gp


# --------------------------------------------------------------------------
# the shared sentinel consumer — ONE copy of the readback-side plumbing
# (checksum cross-check, histogram folding, detail rendering, bundle thunk,
# heal) so the three dispatching actions cannot drift apart in what a trip
# records or how it self-heals.
# --------------------------------------------------------------------------


def make_heal(ssn):
    """The standard trip heal: drop the resident device caches (a
    corrupted column is cured by the next dispatch's full re-upload) AND
    retire the published what-if lease — a condemned solve's snapshot must
    not keep serving probes; serving waits for the next clean publish."""
    cols = ssn.columns
    qp = getattr(ssn.cache, "query_plane", None)

    def heal():
        if cols is not None:
            cols.drop_resident()
        if qp is not None:
            qp.broker.retire()

    return heal


def sentinel_bundle_thunk(gp: GuardPlane, action: str, dev_snap, config,
                          report, pend_rows=None):
    """Lazy diagnostics-bundle dump for a trip (shared by the sentinel
    consumer and the audit comparator) — captures the exact
    post-resident-swap snapshot the condemned solve consumed."""
    def dump():
        from kube_batch_tpu.guard.bundle import dump_bundle

        return dump_bundle(action, dev_snap, config, report,
                           pend_rows=pend_rows, directory=gp.bundle_dir)

    return dump


def consume_sentinel(gp: GuardPlane, action: str, ssn, snap, dev_snap,
                     config, verdict: int, vhist, echeck: int,
                     engaged, host_bad: int = 0, pend_rows=None,
                     extra_report=None) -> bool:
    """Consume one solve's fused sentinel outputs plus the host
    cross-checks: ``host_bad`` carries the action-specific count (e.g.
    assignments targeting rows the HOST doesn't believe pending); the
    device-vs-host eligibility checksum compare happens here, once.
    Host-side violations fold into slot 0 of the histogram so the trip
    log and the bundle tell one story regardless of which action fired.
    Returns True = lawful, apply the result; False = FAIL CLOSED."""
    import numpy as np

    from kube_batch_tpu.ops.invariants import (
        INVARIANT_NAMES,
        host_eligibility_checksum,
    )

    host_ck = host_eligibility_checksum(snap)
    if (int(echeck) & 0xFFFFFFFF) != host_ck:
        host_bad += 1
    total = int(verdict) + host_bad
    vhist = (
        np.zeros(len(INVARIANT_NAMES), np.int64) if vhist is None
        else np.asarray(vhist).astype(np.int64).copy()
    )
    vhist[0] += host_bad
    detail = ", ".join(
        f"{name}={int(c)}" for name, c in zip(INVARIANT_NAMES, vhist) if c
    )
    if host_bad:
        detail += f" (host eligibility cross-check: {host_bad})"
    report = {
        "verdict": int(total), "detail": detail, "engaged": list(engaged),
        "host_cross_check": host_bad, "host_checksum": host_ck,
    }
    if extra_report:
        report.update(extra_report)
    return gp.consume_verdict(
        action, engaged, total, hist=vhist, detail=detail,
        dump=sentinel_bundle_thunk(gp, action, dev_snap, config, report,
                                   pend_rows=pend_rows),
        heal=make_heal(ssn),
    )


def consume_assignment_sentinel(gp: GuardPlane, action: str, ssn, snap,
                                meta, ginfo, verdict: int, vhist,
                                echeck: int, assigned,
                                extra_report=None) -> bool:
    """The assignment-shaped consumer shared by allocate and backfill's
    real-request pass: ONE copy of the host cross-check (an assignment
    must target a row the HOST also believes pending — the device-resident
    pending column could be the corrupted thing) feeding
    :func:`consume_sentinel`, so the two actions cannot condemn different
    things for the same corruption."""
    import numpy as np

    host_bad = int(np.sum(
        (np.asarray(assigned) >= 0)
        & ~np.asarray(snap.task_pending)[: meta.n_tasks]
    ))
    return consume_sentinel(
        gp, action, ssn, snap, ginfo["dev"], ginfo["config"],
        int(verdict), vhist, int(echeck), ginfo["engaged"],
        host_bad=host_bad, pend_rows=ginfo.get("pend_rows"),
        extra_report=extra_report,
    )
