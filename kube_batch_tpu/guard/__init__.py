"""Result-integrity guard plane — runtime verification of the fast paths.

Three tiers, wired through every dispatching action:

1. **Cycle invariant sentinel** (ops/invariants.py): a fused device-side
   check appended to each solve program; a nonzero verdict makes the
   action FAIL CLOSED — no binds/evictions from a condemned solve.
2. **Sampled shadow-oracle audit**: every KB_AUDIT_EVERY-th dispatch the
   committed solve re-runs through its oracle path (KB_TOPK=0 / pjit /
   full-matrix) against the same snapshot, bit-compared off the critical
   path (overlapped with the host replay).
3. **Self-healing demotion** (:class:`GuardPlane`): a per-fast-path health
   breaker — a trip demotes the engaged fast paths to their oracles,
   drops the resident device cache (an HBM corruption heals on the next
   full upload), and dumps a self-contained diagnostics bundle
   (guard/bundle.py) that ``python -m kube_batch_tpu.sim --replay-bundle``
   reloads for deterministic offline triage; half-open probes re-promote
   after KB_GUARD_COOLDOWN clean cycles.

Knobs: ``KB_GUARD=0`` (escape hatch — no sentinel, no audits, no
demotion), ``KB_AUDIT_EVERY`` (default 64; 0 = audits off),
``KB_GUARD_COOLDOWN`` (clean cycles before a half-open probe; default 8),
``KB_GUARD_DIR`` (diagnostics bundle directory).
"""

from kube_batch_tpu.guard.plane import (
    FAST_PATHS,
    GuardPlane,
    consume_assignment_sentinel,
    consume_sentinel,
    guard_of,
    make_heal,
    sentinel_bundle_thunk,
)

__all__ = [
    "FAST_PATHS", "GuardPlane", "consume_assignment_sentinel",
    "consume_sentinel", "guard_of", "make_heal", "sentinel_bundle_thunk",
]
