"""Session — the per-cycle runtime (framework/session.go, session_plugins.go,
statement.go, framework.go).

A Session owns one immutable-ish snapshot of the cluster (deep-cloned by the
cache), the tier-configured plugin callbacks, and the mutation verbs
(Allocate/Pipeline/Evict) whose committed effects flow back to the cache as
bind/evict calls. The TPU divergence: the hot allocate path doesn't use the
per-task verbs — it runs the device solve (ops/assignment.py) over the
snapshot tensors and then *replays* the resulting assignment through the same
verbs so host state, event handlers, and the binder see exactly the
sequential semantics.
"""

from __future__ import annotations

import itertools
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.api.job_info import JobInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.pod import PodGroupCondition
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.types import (
    PodGroupPhase,
    TaskStatus,
    queue_phase_counts,
)
from kube_batch_tpu.framework.conf import Tier
from kube_batch_tpu import metrics

# fn-kind names used in the per-plugin registries
JOB_ORDER, QUEUE_ORDER, TASK_ORDER = "job_order", "queue_order", "task_order"
JOB_READY, JOB_PIPELINED, JOB_VALID = "job_ready", "job_pipelined", "job_valid"
JOB_ENQUEUEABLE, OVERUSED = "job_enqueueable", "overused"
PREEMPTABLE, RECLAIMABLE = "preemptable", "reclaimable"
PREDICATE, NODE_ORDER = "predicate", "node_order"

_ENABLE_FIELD = {
    JOB_ORDER: "enabled_job_order",
    QUEUE_ORDER: "enabled_queue_order",
    TASK_ORDER: "enabled_task_order",
    JOB_READY: "enabled_job_ready",
    JOB_PIPELINED: "enabled_job_pipelined",
    JOB_VALID: None,  # JobValid has no enable switch (session_plugins.go:244)
    JOB_ENQUEUEABLE: None,
    OVERUSED: None,
    PREEMPTABLE: "enabled_preemptable",
    RECLAIMABLE: "enabled_reclaimable",
    PREDICATE: "enabled_predicate",
    NODE_ORDER: "enabled_node_order",
}


class Event:
    """Allocate/Deallocate event (framework/event.go:24-32)."""

    def __init__(self, task: TaskInfo):
        self.task = task


class EventHandler:
    """Allocate/Deallocate hooks (framework/event.go:24-32).

    `batch_allocate_func(job, tasks, total_resreq)` is an optional
    TPU-rebuild extension: a handler whose per-task effect is linear in
    task.resreq (drf's job share, proportion's queue allocation) can expose
    one call per job with the presummed resreq, letting the vectorized
    allocate replay skip the per-task event loop. Handlers without it are
    fired per task even on the bulk path — semantics never depend on it.

    `columnar_allocate_func(cols, job_sums)` is the fully-vectorized form:
    one call per replay with the [capJ, R] per-job-row resreq sums (zeros for
    untouched jobs).  The columnar allocate replay requires every handler
    with allocate-side effects to provide it (actions/allocate.py gates on
    that), so no handler can silently miss events."""

    def __init__(self, allocate_func=None, deallocate_func=None,
                 batch_allocate_func=None, columnar_allocate_func=None):
        self.allocate_func = allocate_func
        self.deallocate_func = deallocate_func
        self.batch_allocate_func = batch_allocate_func
        self.columnar_allocate_func = columnar_allocate_func


class FitFailure(Exception):
    """A predicate rejection with a reason (api.FitError analog)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class Session:
    def __init__(self, cache, cluster: ClusterInfo, tiers: List[Tier],
                 exclusive: bool = False, open_reuse=None,
                 dirty_jobs=frozenset()):
        self.uid = str(uuid.uuid4())
        self.cache = cache
        self.spec = cluster.spec
        self.jobs: Dict[str, JobInfo] = cluster.jobs
        self.nodes: Dict[str, NodeInfo] = cluster.nodes
        self.queues: Dict[str, QueueInfo] = cluster.queues
        # exclusive (no-clone) session: jobs/nodes ARE the cache's objects;
        # the cache defers ingest until close and close_session unwinds
        # session-only state (pipelined placements)
        self.exclusive = exclusive
        # the cache's persistent ColumnStore, exposed to plugins for
        # vectorized session-open state (None for isolated sessions, whose
        # cloned objects are not column-bound)
        self.columns = getattr(cache, "columns", None) if exclusive else None
        # every task Pipelined this session (Statement.pipeline /
        # Session.pipeline / the bulk replay) — session-only state the
        # exclusive close must revert (a cloned session just dies)
        self.pipelined_tasks: List[TaskInfo] = []
        # every task set ALLOCATED this session (Session.allocate /
        # Statement.allocate).  ALLOCATED only becomes durable via dispatch
        # (→ BINDING); residue whose job never turned ready is session-only
        # state too — exclusive close reverts whatever is still ALLOCATED
        # (the reference's clone takes it to the grave, session.go:286-294)
        self.allocated_tasks: List[TaskInfo] = []
        self.tiers = tiers
        self.plugins: List = []
        # plugin-fn registries: kind → {plugin_name: fn}
        self._fns: Dict[str, Dict[str, Callable]] = {}
        self.event_handlers: List[EventHandler] = []
        # device-solve knobs populated by plugins at session open
        from kube_batch_tpu.ops.scoring import ScoreWeights

        self.score_weights = ScoreWeights()
        # set by plugins whose predicates the device mask can't encode;
        # forces per-placement host re-validation for every job
        self.host_only_predicates = False
        # node names a plugin excludes for this whole session (task-
        # independent vetoes like the pressure gates) — both snapshot
        # builders fold these into node_sched, so the device mask stays
        # exact and the replay stays on the fast path
        self.session_excluded_nodes: set = set()
        # PodGroup statuses as they stood at open (session.go:102-105), used
        # by the job updater to detect condition-only updates (rate-limited)
        # — essential in exclusive mode, where the session mutates the
        # authoritative PodGroup in place and a post-hoc compare is vacuous.
        # Exclusive sessions also clear per-session diagnostic state on the
        # live objects in the same pass — a cloned session starts clean
        # because clone() does this (job_info.go:295-329); the no-clone path
        # must, or stale fit errors replay forever (and grow unboundedly).
        #
        # `open_reuse` (cache/dirty.py OpenCache) is the delta form of this
        # pass: the cache maintained the at-open statuses across cycles
        # (session_view_delta refreshed the dirty jobs), and only jobs known
        # to carry fit diagnostics — cache.fit_state_jobs, populated by
        # note_fit_state at every write site — pay the clearing visit.
        self.pod_group_status_at_open: Dict[str, tuple] = {}
        if exclusive and open_reuse is not None:
            fit_jobs = cache.fit_state_jobs
            for uid in (fit_jobs | set(dirty_jobs)) if fit_jobs or dirty_jobs else ():
                job = self.jobs.get(uid)
                if job is None:
                    continue
                if job.nodes_fit_delta:
                    job.nodes_fit_delta = {}
                if job.nodes_fit_errors:
                    job.nodes_fit_errors = {}
                if job.job_fit_errors:
                    job.job_fit_errors = ""
            fit_jobs.clear()
            self.pod_group_status_at_open = dict(open_reuse.pg_status)
        else:
            at_open = self.pod_group_status_at_open
            for job in self.jobs.values():
                if exclusive:
                    if job.nodes_fit_delta:
                        job.nodes_fit_delta = {}
                    if job.nodes_fit_errors:
                        job.nodes_fit_errors = {}
                    if job.job_fit_errors:
                        job.job_fit_errors = ""
                pg = job.pod_group
                if pg is not None:
                    at_open[job.uid] = (pg.phase, pg.running, pg.failed,
                                        pg.succeeded)
        # set by open_session once the ColumnStore's session job-row arrays
        # (j_sess & friends) are synced for this cycle — the gate, the close
        # status pass, and the device snapshot all read them
        self.rows_synced = False
        self._total_alloc_cache = None
        # job uids given an Unschedulable=True condition THIS session —
        # saves the close pass a per-job scan over conditions lists
        self.unschedulable_marked: set = set()
        # jobs the open gate dropped (gang-invalid, session.go:107-124) —
        # their podgroups still count toward QueueStatus phase counts
        self.gate_dropped_jobs: List[JobInfo] = []
        # jobs whose placements the allocate replay DISCARDED host-side this
        # cycle (JobReady failures after host predicate rejections, volume
        # demotion dead-ends) — the backfill action's real-request pass keys
        # off this. Carried on the session, NOT the process-global action
        # registry singleton: multiple Scheduler/cache instances in one
        # process (tests, the simulator) must not cross wires (ADVICE.md #5)
        self.host_discards = 0
        # the staged StatusFlush, stashed here by close_session as soon as
        # staging succeeds: if the close's own finally raises afterwards,
        # the pipelined caller recovers the flush from the session instead
        # of dropping writes whose stage-time bookkeeping already committed
        self.staged_flush = None

    def drop_job(self, uid: str) -> None:
        """Remove a job from the session (open-gate drops).  The caller is
        responsible for clearing the job's j_sess row when the session rows
        are already synced (open_session's gate does)."""
        del self.jobs[uid]

    def session_rows(self):
        """(rows[int], jobs_list) of the CURRENT session job set, straight
        off the synced j_sess column — the shared basis for the vectorized
        gate, the gang close sweep, and the columnar close status pass.
        Columnar sessions only; requires rows_synced."""
        import numpy as np

        cols = self.columns
        rows = np.flatnonzero(cols.j_sess)
        job_by_row = cols.job_by_row
        return rows, [job_by_row[r] for r in rows.tolist()]

    def note_fit_state(self, job: JobInfo) -> None:
        """Record that `job` now carries per-session fit diagnostics
        (nodes_fit_delta / nodes_fit_errors / job_fit_errors) — the delta
        session open clears exactly these jobs instead of probing all of
        them.  Every write site of those fields must call this."""
        fit_jobs = getattr(self.cache, "fit_state_jobs", None)
        if fit_jobs is not None:
            fit_jobs.add(job.uid)

    def total_allocatable(self):
        """Σ allocatable over the session's nodes (the drf/proportion
        cluster total, drf.go:57-62 / proportion.go:67-74), computed once
        per session — vectorized over the node columns when bound, else the
        object loop."""
        if self._total_alloc_cache is not None:
            return self._total_alloc_cache
        cols = self.columns
        total = self.spec.empty()
        # session nodes are exactly the Ready rows (session_view filters on
        # node.ready, which n_valid mirrors) — checked cheaply; any mismatch
        # falls back to the authoritative object loop
        if (
            cols is not None
            and len(self.nodes) > 64
            and int(cols.n_valid.sum()) == len(self.nodes)
        ):
            total.vec = cols.n_alloc[cols.n_valid].sum(axis=0)
        else:
            for node in self.nodes.values():
                total.add_(node.allocatable)
        self._total_alloc_cache = total
        return total

    # ---- registration (session_plugins.go:25-97) ------------------------
    def add_fn(self, kind: str, plugin_name: str, fn: Callable) -> None:
        self._fns.setdefault(kind, {})[plugin_name] = fn

    def add_score_row(self, name: str, fn: Callable, weight: float = 1.0) -> None:
        """Register a DEVICE score row: fn(snap: DeviceSnapshot) -> [T, N]
        f32, summed into the compiled solve's score matrix with `weight` —
        the NodeOrder/BatchNodeOrder extension surface
        (session_plugins.go:392-492) at the tensor level.  A plugin whose
        scoring policy also matters on the host replay paths should
        additionally register a host scorer via add_fn(NODE_ORDER, ...).
        Use a module-level fn: the row set is part of the jit cache key, so
        a fresh lambda per session forces a recompile every cycle."""
        self.score_weights = self.score_weights._replace(
            extra_rows=self.score_weights.extra_rows + ((name, fn, weight),)
        )

    def add_event_handler(self, handler: EventHandler) -> None:
        self.event_handlers.append(handler)

    def _enabled(self, kind: str, opt) -> bool:
        field = _ENABLE_FIELD[kind]
        return True if field is None else getattr(opt, field)

    def _iter_fns(self, kind: str):
        """Yield (tier_index, fn) for enabled plugins, in tier order."""
        fns = self._fns.get(kind, {})
        for ti, tier in enumerate(self.tiers):
            for opt in tier.plugins:
                fn = fns.get(opt.name)
                if fn is not None and self._enabled(kind, opt):
                    yield ti, fn

    def plugin_enabled(self, name: str) -> bool:
        return any(opt.name == name for tier in self.tiers for opt in tier.plugins)

    def conf_flag(self, key: str, default: bool = False) -> bool:
        """A free-form boolean argument searched across every tier's plugin
        Arguments (arguments.go:26-66) — the conf surface for action-level
        toggles: `allocate.pallas`, and the sanctioned-divergence escape
        hatches `preempt.referenceExact` / `reclaim.referenceExact`
        (PARITY.md "known divergences")."""
        for tier in self.tiers:
            for opt in tier.plugins:
                v = opt.arguments.get(key)
                if v is not None:
                    return str(v).strip().lower() in ("1", "true", "yes")
        return default

    def enabled_plugin_names(self, kind: str) -> set:
        """Names of plugins with an enabled fn of `kind` registered — lets the
        vectorized allocate replay prove the gang arithmetic gate is the only
        JobReady veto before taking the fast path."""
        fns = self._fns.get(kind, {})
        return {
            opt.name
            for tier in self.tiers
            for opt in tier.plugins
            if opt.name in fns and self._enabled(kind, opt)
        }

    def ordered_enabled_plugins(self, kind: str) -> List[str]:
        """Enabled voter names of `kind` in tiered dispatch order (the
        _iter_fns iteration order) — the enqueue column gate derives its
        vectorized ordering keys in exactly this significance order."""
        fns = self._fns.get(kind, {})
        return [
            opt.name
            for tier in self.tiers
            for opt in tier.plugins
            if opt.name in fns and self._enabled(kind, opt)
        ]

    # ---- tiered dispatch ------------------------------------------------
    def _order(self, kind: str, l, r, l_info: Tuple, r_info: Tuple) -> bool:
        """First non-zero verdict wins; fallback CreationTimestamp-then-UID
        (session_plugins.go:281-305)."""
        for _, fn in self._iter_fns(kind):
            v = fn(l, r)
            if v != 0:
                return v < 0
        return l_info < r_info

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        return self._order(JOB_ORDER, l, r, (l.creation_index, l.uid), (r.creation_index, r.uid))

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        return self._order(QUEUE_ORDER, l, r, (l.name,), (r.name,))

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        return self._order(
            TASK_ORDER, l, r, (l.pod.creation_index, l.uid), (r.pod.creation_index, r.uid)
        )

    def task_order_plugin_verdict(self, l: TaskInfo, r: TaskInfo) -> int:
        """The tiered plugin verdict alone (<0 l first, 0 no plugin voted),
        WITHOUT the creation-timestamp fallback — for callers that must
        distinguish 'a plugin prefers l' from 'mere tie-break order', e.g.
        preempt's phase-2 worth-it gate."""
        for _, fn in self._iter_fns(TASK_ORDER):
            v = fn(l, r)
            if v != 0:
                return v
        return 0

    def _veto(self, kind: str, obj) -> bool:
        """All enabled plugins must pass (JobReady session_plugins.go:202-220)."""
        for _, fn in self._iter_fns(kind):
            if not fn(obj):
                return False
        return True

    def job_ready(self, job: JobInfo) -> bool:
        return self._veto(JOB_READY, job)

    def job_pipelined(self, job: JobInfo) -> bool:
        return self._veto(JOB_PIPELINED, job)

    def job_enqueueable(self, job: JobInfo) -> bool:
        return self._veto(JOB_ENQUEUEABLE, job)

    def job_valid(self, job: JobInfo) -> Optional[str]:
        """First failing plugin's reason, None = valid
        (session_plugins.go:244-260)."""
        for _, fn in self._iter_fns(JOB_VALID):
            reason = fn(job)
            if reason is not None:
                return reason
        return None

    def overused(self, queue: QueueInfo) -> bool:
        """Any plugin saying overused wins (session_plugins.go:185-199)."""
        return any(fn(queue) for _, fn in self._iter_fns(OVERUSED))

    def _victims(self, kind: str, actor: TaskInfo, candidates: List[TaskInfo]):
        """Per-tier intersection; first tier with a non-None verdict wins
        (session_plugins.go:100-182). None = no plugin in the tier voted;
        [] = plugins voted and vetoed everything."""
        for ti, tier in enumerate(self.tiers):
            victims: Optional[List[TaskInfo]] = None
            init = False
            for opt in tier.plugins:
                fn = self._fns.get(kind, {}).get(opt.name)
                if fn is None or not self._enabled(kind, opt):
                    continue
                cand = fn(actor, candidates)
                if not init:
                    victims, init = cand, True
                elif victims is not None:
                    cand_uids = {c.uid for c in (cand or [])}
                    victims = [v for v in victims if v.uid in cand_uids]
            if victims is not None:
                return victims
        return None

    def preemptable(self, preemptor: TaskInfo, preemptees: List[TaskInfo]):
        return self._victims(PREEMPTABLE, preemptor, preemptees)

    def reclaimable(self, reclaimer: TaskInfo, reclaimees: List[TaskInfo]):
        return self._victims(RECLAIMABLE, reclaimer, reclaimees)

    def predicate(self, task: TaskInfo, node: NodeInfo) -> None:
        """All enabled predicates must pass; raises FitFailure
        (session_plugins.go:372-389)."""
        for _, fn in self._iter_fns(PREDICATE):
            fn(task, node)  # raises FitFailure

    def node_order(self, task: TaskInfo, node: NodeInfo) -> float:
        """Additive score (session_plugins.go:392-412)."""
        return sum(fn(task, node) for _, fn in self._iter_fns(NODE_ORDER))

    # ---- verbs (session.go:199-363) -------------------------------------
    def _fire(self, allocate: bool, task: TaskInfo) -> None:
        for eh in self.event_handlers:
            fn = eh.allocate_func if allocate else eh.deallocate_func
            if fn is not None:
                fn(Event(task))

    def fire_batch_allocations(self, job: JobInfo, tasks, total_resreq) -> None:
        """Fire allocate events for `tasks` (all of one job) — one call per
        handler that supports batching (with `total_resreq` presummed over the
        tasks), the per-task loop for handlers that don't."""
        for eh in self.event_handlers:
            if eh.batch_allocate_func is not None:
                eh.batch_allocate_func(job, tasks, total_resreq)
            elif eh.allocate_func is not None:
                for t in tasks:
                    eh.allocate_func(Event(t))

    def fire_columnar_allocations(self, cols, job_sums) -> None:
        """One vectorized allocate-event pass for the whole replay
        (job_sums: [capJ, R] per-job-row resreq sums)."""
        for eh in self.event_handlers:
            if eh.columnar_allocate_func is not None:
                eh.columnar_allocate_func(cols, job_sums)

    def all_handlers_columnar(self) -> bool:
        """True when every handler with allocate-side effects supports the
        columnar form — the allocate replay's gate for the vectorized path."""
        return all(
            eh.columnar_allocate_func is not None
            or (eh.allocate_func is None and eh.batch_allocate_func is None)
            for eh in self.event_handlers
        )

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        job = self.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        self.pipelined_tasks.append(task)
        self._fire(True, task)

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        """Allocate + (when the job turns ready) dispatch every Allocated
        task to the binder (session.go:252-296)."""
        self.cache.allocate_volumes(task, hostname)
        job = self.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        self.allocated_tasks.append(task)
        self._fire(True, task)
        if job is not None and self.job_ready(job):
            for t in list(job.task_status_index.get(TaskStatus.ALLOCATED, {}).values()):
                self.dispatch(t)

    def dispatch(self, task: TaskInfo) -> None:
        self.cache.bind_volumes(task)
        self.cache.bind(task, task.node_name)
        job = self.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.BINDING)

    def evict(self, task: TaskInfo, reason: str) -> None:
        self.cache.evict(task, reason)
        job = self.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.RELEASING)
        node = self.nodes.get(task.node_name)
        if node is not None:
            node.update_task(task)
        self._fire(False, task)

    def statement(self) -> "Statement":
        return Statement(self)

    def update_job_condition(self, job: JobInfo, condition: PodGroupCondition) -> None:
        """Upsert by type (session.go:366-388)."""
        if job.pod_group is None:
            return
        if (
            condition.type == "Unschedulable"
            and condition.status == "True"
            and condition.transition_id == self.uid
        ):
            self.unschedulable_marked.add(job.uid)
        cols = self.columns
        if cols is not None and job._cols is cols and job._row >= 0:
            # conditions feed the close pass's need-record set and its
            # touched-row visit — the delta close must see mid-cycle writes
            cols.j_has_conds[job._row] = True
            cols.j_touched[job._row] = True
        for i, c in enumerate(job.pod_group.conditions):
            if c.type == condition.type:
                job.pod_group.conditions[i] = condition
                return
        job.pod_group.conditions.append(condition)


class Statement:
    """All-or-nothing op log (statement.go:29-337): verbs mutate session
    state immediately and append ops; Commit replays against the cache,
    Discard undoes in reverse."""

    def __init__(self, ssn: Session):
        self.ssn = ssn
        self.operations: List[Tuple[str, tuple]] = []

    # -- session-visible verbs -------------------------------------------
    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self.ssn._fire(False, reclaimee)
        self.operations.append(("evict", (reclaimee, reason)))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        self.ssn.pipelined_tasks.append(task)
        self.ssn._fire(True, task)
        self.operations.append(("pipeline", (task, hostname)))

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        self.ssn.cache.allocate_volumes(task, hostname)
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = hostname
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        self.ssn.allocated_tasks.append(task)
        self.ssn._fire(True, task)
        self.operations.append(("allocate", (task, hostname)))

    # -- terminal ---------------------------------------------------------
    def commit(self) -> None:
        # eviction-free statements (the allocate action's gang commits) batch
        # every bind under one cache lock; mixed statements replay in order
        if not any(name == "evict" for name, _ in self.operations):
            allocs = [args for name, args in self.operations if name == "allocate"]
            if allocs:
                for task, _ in allocs:
                    self.ssn.cache.bind_volumes(task)
                self.ssn.cache.bulk_bind(
                    [(task, task.node_name) for task, _ in allocs]
                )
                for task, _ in allocs:
                    job = self.ssn.jobs.get(task.job)
                    if job is not None:
                        job.update_task_status(task, TaskStatus.BINDING)
            self.operations = []
            return
        for name, args in self.operations:
            if name == "evict":
                task, reason = args
                self.ssn.cache.evict(task, reason)
            elif name == "pipeline":
                pass  # session-only state (statement.go pipeline no-ops on commit)
            elif name == "allocate":
                task, _ = args
                self.ssn.cache.bind_volumes(task)
                self.ssn.cache.bind(task, task.node_name)
                job = self.ssn.jobs.get(task.job)
                if job is not None:
                    job.update_task_status(task, TaskStatus.BINDING)
        self.operations = []

    def discard(self) -> None:
        for name, args in reversed(self.operations):
            if name == "evict":
                task, _ = args
                self._unevict(task)
            elif name == "pipeline":
                task, _ = args
                self._unpipeline(task)
            elif name == "allocate":
                task, _ = args
                self._unallocate(task)
        self.operations = []

    # -- inverses (statement.go unevict/unpipeline/unallocate) ------------
    def _unevict(self, task: TaskInfo) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.RUNNING)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.update_task(task)
        self.ssn._fire(True, task)

    def _unpipeline(self, task: TaskInfo) -> None:
        _undo_placement(self.ssn, task, release_volumes=False)
        self.ssn._fire(False, task)

    def _unallocate(self, task: TaskInfo) -> None:
        # release_volumes frees the PV reservation the allocate took — a
        # discarded gang must not hold volumes across cycles and starve
        # other claimants
        _undo_placement(self.ssn, task, release_volumes=True)
        self.ssn._fire(False, task)


# ---- session lifecycle (framework/framework.go:30-62) -------------------

def open_session(cache, tiers: List[Tier], plugin_options=None,
                 isolated: bool = False) -> Session:
    """Open a scheduling session: drop gang-invalid jobs (marking them
    unschedulable, session.go:107-124) and run every configured plugin's
    OnSessionOpen.

    Default is the EXCLUSIVE (no-clone) mode: the session takes ownership of
    the cache's own objects for the cycle — ingest and repair mutations are
    deferred by the cache until close, exactly the once-per-cycle staleness
    the reference's deep-cloned snapshot has, without paying the 50k-task
    clone or the commit-time double bookkeeping (the reference clones
    because informer goroutines race the session, cache.go:584-654; here
    the gate provides the same isolation). Session-only state (Pipelined
    placements) is unwound at close. `isolated=True` forces the reference's
    deep-clone behavior — callers that want to inspect a what-if session
    without touching the cache.

    Exclusive opens are INCREMENTAL when churn allows (cache/dirty.py):
    the per-job open structures — membership view, resolved priorities,
    at-open PodGroup statuses, fit-state clears, the column job-row arrays —
    are deltas against the previous cycle keyed on the cache's dirty sets,
    with the full rebuild as the bit-exact fallback for high churn,
    queue/priority-class row-space changes, or a cold cache."""
    from kube_batch_tpu.framework.interface import get_plugin_builder

    use_delta = False
    delta = None
    oc = None
    if isolated:
        cluster = cache.snapshot()
        ssn = Session(cache, cluster, tiers)
    else:
        cache.begin_exclusive_session()
        try:
            oc = getattr(cache, "open_cache", None)
            take = getattr(cache, "take_dirty", None)
            delta = take() if take is not None else None
            use_delta = (
                delta is not None
                and oc is not None
                and getattr(cache, "columns", None) is not None
                and getattr(cache, "delta_enabled", False)
                and oc.valid
                and not (delta.full or delta.queues_changed
                         or delta.priority_classes_changed)
                and len(delta.jobs) <= max(
                    32, cache.delta_churn_threshold * len(oc.jobs)
                )
            )
            if use_delta:
                cache.last_open_path = "delta"
                cache.last_churn = delta.churn_fraction(len(oc.jobs))
                cluster = cache.session_view_delta(delta)
            else:
                if delta is not None:
                    cache.last_open_path = "full"
                    cache.last_churn = delta.churn_fraction(len(cache.jobs))
                cluster = cache.session_view()
        except BaseException:
            cache.end_exclusive_session()
            raise
        try:
            ssn = Session(cache, cluster, tiers, exclusive=True,
                          open_reuse=oc if use_delta else None,
                          dirty_jobs=delta.jobs if use_delta else frozenset())
            if not use_delta and oc is not None:
                # reseed the cross-cycle open cache from this full rebuild —
                # BEFORE the gate mutates ssn.jobs (same dict as cluster.jobs)
                cache.rebuild_open_cache(cluster,
                                         ssn.pod_group_status_at_open)
        except BaseException:
            # same contract as the guards around it: never leave the gate
            # stuck, and never trust half-updated cross-cycle open state
            if oc is not None:
                oc.invalidate()
                cache.dirty.mark_full()
            cache.end_exclusive_session()
            raise
    try:
        cols = ssn.columns
        if cols is not None:
            # sync the column job-row arrays (j_sess membership, j_min,
            # j_queue, j_prio, j_creation, j_sched) before plugin opens —
            # proportion's vectorized open, the gang gate, the device
            # snapshot, and the close status pass all read them
            if use_delta:
                cols.sync_session_rows(ssn, dirty_uids=delta.jobs,
                                       restore_rows=oc.gate_dropped_rows)
            else:
                cols.sync_session_rows(ssn)
            ssn.rows_synced = True
        from kube_batch_tpu.obs.trace import tracer_of

        tracer = tracer_of(cache)
        for tier in tiers:
            for opt in tier.plugins:
                plugin = get_plugin_builder(opt.name)(opt.arguments)
                ssn.plugins.append(plugin)
                # the span IS the measurement (rule KBT014): the plugin
                # latency histogram feeds from its stamps
                with tracer.span("plugin:" + opt.name + ".open") as sp:
                    plugin.on_session_open(ssn)
                metrics.observe_plugin_latency(
                    opt.name, "OnSessionOpen", sp.dur_us
                )
        # gang-validity gate after plugins registered their JobValid fns.
        # Columnar sessions prefilter with one counts-matrix expression when
        # gang is the only JobValid voter (its verdict IS the count compare,
        # gang.go:48-69) — only the normally-sparse invalid set walks the
        # full dispatch for its reason string.
        valid_voters = set(ssn._fns.get(JOB_VALID, {}).keys())
        if cols is not None and ssn.rows_synced and valid_voters <= {"gang"} \
                and ssn.jobs:
            if not valid_voters:
                gate_jobs = []
            else:
                import numpy as np

                from kube_batch_tpu.api.columns import VALID_STATUSES

                rows, jobs_list = ssn.session_rows()
                valid_num = cols.j_counts[rows][:, VALID_STATUSES].sum(axis=1)
                gate_jobs = [
                    (jobs_list[i].uid, jobs_list[i])
                    for i in np.flatnonzero(valid_num < cols.j_min[rows])
                ]
        else:
            gate_jobs = list(ssn.jobs.items())
        dropped_rows = set()
        for uid, job in gate_jobs:
            reason = ssn.job_valid(job)
            if reason is not None:
                ssn.update_job_condition(
                    job,
                    PodGroupCondition(
                        type="Unschedulable",
                        status="True",
                        transition_id=ssn.uid,
                        reason="NotEnoughPods",
                        message=reason,
                    ),
                )
                cache.record_job_status_event(job)
                ssn.gate_dropped_jobs.append(job)
                ssn.drop_job(uid)
                if cols is not None and job._cols is cols and job._row >= 0:
                    # the dropped job leaves the device snapshot too; its
                    # row is remembered so the next delta open re-admits it
                    # for the gate's re-vote
                    cols.j_sess[job._row] = False
                    dropped_rows.add(job._row)
        if oc is not None:
            oc.gate_dropped_rows = dropped_rows
    except BaseException:
        if ssn.exclusive:
            # never leave the gate stuck; and a half-opened session may have
            # consumed dirty marks without refreshing the open cache — force
            # the next open to rebuild from scratch
            invalidate = getattr(cache, "open_cache", None)
            if invalidate is not None:
                invalidate.invalidate()
                cache.dirty.mark_full()
            cache.end_exclusive_session()
        raise
    return ssn


def job_status(ssn: Session, job: JobInfo) -> None:
    """Derive and set the PodGroup phase/counts (session.go:151-189).

    Shadow PodGroups (synthesized for plain pods, cache/util.go:42-60) carry
    NO durable phase: in the reference the jobUpdater's CRD write fails for
    them and the informer-fed mirror keeps the phase empty, so an
    unschedulable plain pod is retried every cycle even without the enqueue
    action.  The no-clone session must reproduce that by not writing the
    phase onto the synthesized object."""
    pg = job.pod_group
    if pg is None:
        return
    if pg.shadow:
        pg.running = len(job.task_status_index.get(TaskStatus.RUNNING, {}))
        pg.failed = len(job.task_status_index.get(TaskStatus.FAILED, {}))
        pg.succeeded = len(job.task_status_index.get(TaskStatus.SUCCEEDED, {}))
        return
    unschedulable = any(
        c.type == "Unschedulable" and c.status == "True" and c.transition_id == ssn.uid
        for c in pg.conditions
    )
    running = len(job.task_status_index.get(TaskStatus.RUNNING, {}))
    if running and unschedulable:
        pg.phase = PodGroupPhase.UNKNOWN
    else:
        allocated = job.task_num(
            TaskStatus.BOUND, TaskStatus.BINDING, TaskStatus.RUNNING, TaskStatus.ALLOCATED
        )
        if allocated >= pg.min_member:
            pg.phase = PodGroupPhase.RUNNING
        elif pg.phase != PodGroupPhase.INQUEUE:
            pg.phase = PodGroupPhase.PENDING
    pg.running = running
    pg.failed = len(job.task_status_index.get(TaskStatus.FAILED, {}))
    pg.succeeded = len(job.task_status_index.get(TaskStatus.SUCCEEDED, {}))


def _undo_placement(ssn: Session, task: TaskInfo, release_volumes: bool) -> None:
    """The shared placement-undo core: status→PENDING, node removal,
    node_name cleared, and (for allocates) volume reservation release.
    Used by Statement discard inverses (which additionally fire deallocate
    events) and the exclusive-close residue revert (which doesn't — plugin
    session state dies with the session anyway)."""
    job = ssn.jobs.get(task.job)
    if job is not None and task.key() in job.tasks:
        job.update_task_status(task, TaskStatus.PENDING)
    node = ssn.nodes.get(task.node_name) if task.node_name else None
    if node is not None and task.key() in node.tasks:
        node.remove_task(task)
    task.node_name = None
    if release_volumes:
        task.volume_ready = False
        release = getattr(ssn.cache.volume_binder, "release_task", None)
        if release is not None:
            release(task.uid)


def _revert_residue(ssn: Session, tasks: List[TaskInfo], expected: TaskStatus,
                    release_volumes: bool) -> None:
    """Revert session-only placements still in `expected` status back to
    PENDING on the live objects (exclusive close; the reference's clone takes
    such state to the grave). The status guard makes this idempotent —
    dispatched / discarded / transitioned tasks are skipped."""
    for task in tasks:
        if task.status != expected:
            continue
        _undo_placement(ssn, task, release_volumes)


def _close_status_columnar(ssn: Session) -> None:
    """The close-session status pass driven by the counts matrix: phase
    derivation (job_status) becomes vectorized arithmetic; per-job work is
    paid only by jobs whose status changed or that have something to report.
    End state equals the per-job loop's.

    DELTA form (this PR): the j_counts choke points already know which jobs
    moved — every count write (JobInfo's index choke points, the columnar
    replay's vectorized update), every session row re-sync (dirty jobs at
    open; ALL rows on a full-rebuild open), and every mid-cycle phase/
    condition write stamps ``cols.j_touched``.  A row NOT stamped since the
    last close provably has identical derivation inputs (counts, phase,
    min_member, unschedulable marks), so its phase/count writes would be
    no-ops and its at-open compare would read unchanged — the per-job visit
    therefore covers only touched rows plus the standing need-record set
    (stuck tasks, Pending/Unknown phases, condition-bearing jobs, PDB jobs
    with Pending tasks), and the per-queue phase counts come off the
    j_phase column as one bincount over every session row.
    ``KB_DELTA_CLOSE=0`` forces the full visit (the bit-exact oracle the
    equivalence tests compare against).

    The count columns are pulled into plain Python lists once (numpy scalar
    indexing inside the visit loop costs more than the loop body) and the
    per-job conditions scan is replaced by the session's unschedulable-mark
    set (update_job_condition records the uids as it writes the conditions —
    transition_id == ssn.uid is exactly 'marked this session')."""
    import os

    import numpy as np

    from kube_batch_tpu.api.columns import CODE_PHASE, N_PHASES, PHASE_CODE

    cols = ssn.columns
    rows_all = np.flatnonzero(cols.j_sess)
    jc = cols.j_counts
    PEND_I, ALLOC_I = int(TaskStatus.PENDING), int(TaskStatus.ALLOCATED)
    pend_code = PHASE_CODE[PodGroupPhase.PENDING]
    unk_code = PHASE_CODE[PodGroupPhase.UNKNOWN]
    delta_close = os.environ.get("KB_DELTA_CLOSE", "").strip().lower() not in (
        "0", "false", "off", "no"
    )
    if delta_close and rows_all.size:
        phase_codes = cols.j_phase[rows_all]
        stuck_rows = (jc[rows_all, PEND_I] + jc[rows_all, ALLOC_I]) > 0
        visit = (
            cols.j_touched[rows_all]
            | stuck_rows
            | (phase_codes == pend_code)
            | (phase_codes == unk_code)
            | cols.j_has_conds[rows_all]
            | (~cols.j_has_pg[rows_all] & cols.j_pdb[rows_all]
               & (jc[rows_all, PEND_I] > 0))
        )
        rows = rows_all[visit]
    else:
        rows = rows_all
    jobs_list = [cols.job_by_row[r] for r in rows.tolist()]
    counts = cols.j_counts[rows]
    running_l = counts[:, int(TaskStatus.RUNNING)].tolist()
    failed_l = counts[:, int(TaskStatus.FAILED)].tolist()
    succ_l = counts[:, int(TaskStatus.SUCCEEDED)].tolist()
    pending_l = counts[:, int(TaskStatus.PENDING)].tolist()
    # phase derives from pg.min_member, NOT job.min_available (minav): a job
    # carrying both a PodGroup and a PDB has min_available overwritten by
    # the PDB while job_status (session.go:151-189) still compares against
    # the PodGroup's MinMember
    alloc_l = (
        counts[:, int(TaskStatus.BOUND)]
        + counts[:, int(TaskStatus.BINDING)]
        + counts[:, int(TaskStatus.RUNNING)]
        + counts[:, int(TaskStatus.ALLOCATED)]
    ).tolist()
    # tasks stuck Pending/Allocated → fit-error conditions must be written
    # (record_job_status_event's has_stuck gate, cache.go:704-719)
    stuck_l = (
        counts[:, int(TaskStatus.PENDING)] + counts[:, int(TaskStatus.ALLOCATED)]
    ).tolist()
    prev_map = ssn.pod_group_status_at_open
    prev_get = prev_map.get
    unsched_marked = ssn.unschedulable_marked
    RUNNING, PENDING, UNKNOWN, INQUEUE = (
        PodGroupPhase.RUNNING, PodGroupPhase.PENDING,
        PodGroupPhase.UNKNOWN, PodGroupPhase.INQUEUE,
    )
    record_event = ssn.cache.record_job_status_event
    updates = []
    append = updates.append
    rows_l = rows.tolist()
    j_phase = cols.j_phase
    for i, job in enumerate(jobs_list):
        pg = job.pod_group
        if pg is None:
            if job.pdb is not None and pending_l[i]:
                record_event(job)
            continue
        r, f, s = running_l[i], failed_l[i], succ_l[i]
        if pg.shadow:
            # no durable phase for synthesized groups (see job_status) —
            # but changed counts still write, like the per-job path
            pg.running, pg.failed, pg.succeeded = r, f, s
            changed = prev_get(job.uid) != (pg.phase, r, f, s)
            if changed or stuck_l[i]:
                append((job, changed, bool(stuck_l[i])))
            continue
        if r and job.uid in unsched_marked:
            phase = UNKNOWN
        elif alloc_l[i] >= pg.min_member:
            phase = RUNNING
        elif pg.phase != INQUEUE:
            phase = PENDING
        else:
            phase = pg.phase
        pg.phase, pg.running, pg.failed, pg.succeeded = phase, r, f, s
        j_phase[rows_l[i]] = PHASE_CODE[phase]
        changed = prev_get(job.uid) != (phase, r, f, s)
        need_record = bool(stuck_l[i]) or phase is PENDING or phase is UNKNOWN
        if changed or need_record or pg.conditions:
            append((job, changed, need_record))
    # per-queue podgroup-phase counts (QueueStatus writeback): one bincount
    # over EVERY session row's j_phase — visited rows were just rewritten,
    # unvisited rows' phases provably could not move this cycle
    qcounts: Dict[str, dict] = {}
    if rows_all.size:
        qmask = cols.j_has_pg[rows_all] & ~cols.j_shadow[rows_all]
        sel = rows_all[qmask]
        pcodes = cols.j_phase[sel]
        ok = pcodes >= 0
        sel, pcodes = sel[ok], pcodes[ok]
        if sel.size:
            pairs = cols.j_queue[sel].astype(np.int64) * N_PHASES + pcodes
            bc = np.bincount(
                pairs, minlength=cols.queues.cap * N_PHASES
            ).reshape(cols.queues.cap, N_PHASES)
            for qi in np.flatnonzero(bc.any(axis=1)).tolist():
                qc = queue_phase_counts()
                for code in range(N_PHASES):
                    qc[CODE_PHASE[code].value.lower()] = int(bc[qi, code])
                qcounts[cols.queue_names[qi]] = qc
    _count_gate_dropped(ssn, qcounts)
    # consumed: ingest that lands after this point (deferred mutations,
    # residue reverts) re-stamps rows for the next cycle's visit
    cols.j_touched[:] = False
    return updates, qcounts


def _count_gate_dropped(ssn: Session, qcounts: Dict[str, dict]) -> None:
    """Fold the podgroups of gang-invalid jobs (deleted from ssn.jobs by the
    open gate, session.go:107-124) into the queue phase counts — QueueStatus
    counts podgroups by phase, not by session membership; without this a
    queue whose only podgroups are gang-invalid would zero out while the
    cluster still holds its Pending groups."""
    for job in ssn.gate_dropped_jobs:
        pg = job.pod_group
        if pg is None or pg.shadow or job.queue not in ssn.queues:
            continue
        qc = qcounts.get(job.queue)
        if qc is None:
            qc = qcounts[job.queue] = queue_phase_counts()
        qc[(pg.phase or PodGroupPhase.PENDING).value.lower()] += 1


def close_session(ssn: Session, stage_flush: bool = False):
    """Plugin close hooks then the job updater (framework.go:55-62 +
    job_updater.go:33-122, sans the 16-worker pool — the host loop is cold).
    Exclusive sessions additionally unwind Pipelined placements (session-only
    state, gone with a cloned session) and release the cache gate.

    ``stage_flush=True`` is the pipelined cycle's close: the status pass
    still DERIVES everything synchronously (phase writes, dirty stamps,
    rate-limit bookkeeping, queue-delta decisions — all the state the next
    session open depends on), but the egress half is returned as a
    value-snapshotted ``StatusFlush`` for the writeback stage to run
    overlapped with the next cycle, and the async binder drain is left to
    that same stage (``_inflight_bind_hosts`` protects deferred ingest
    against the unacked window).  Serial callers get ``None`` and identical
    behavior to before the split — stage + run back-to-back."""
    from kube_batch_tpu.obs.trace import tracer_of

    tracer = tracer_of(ssn.cache)
    flush = None
    try:
        for plugin in ssn.plugins:
            with tracer.span("plugin:" + plugin.name + ".close") as sp:
                plugin.on_session_close(ssn)
            metrics.observe_plugin_latency(
                plugin.name, "OnSessionClose", sp.dur_us
            )
        if ssn.columns is not None and ssn.rows_synced and ssn.jobs:
            updates, qcounts = _close_status_columnar(ssn)
            flush = ssn.staged_flush = ssn.cache.stage_status_flush(
                updates, qcounts)
            if not stage_flush:
                ssn.cache.run_status_flush(flush)
                flush = ssn.staged_flush = None
        else:
            qcounts: Dict[str, dict] = {}
            for job in ssn.jobs.values():
                if job.pod_group is None:
                    # PDB-defined jobs get events only, no status writeback
                    # (job_updater.go:108-111; unschedulable iff tasks stay
                    # Pending, cache.go:699)
                    if job.pdb is not None and job.task_status_index.get(
                        TaskStatus.PENDING
                    ):
                        ssn.cache.record_job_status_event(job)
                    continue
                job_status(ssn, job)
                pg = job.pod_group
                if not pg.shadow and pg.phase is not None:
                    qc = qcounts.setdefault(job.queue, queue_phase_counts())
                    qc[pg.phase.value.lower()] += 1
                ssn.cache.update_job_status(
                    job, prev_status=ssn.pod_group_status_at_open.get(job.uid)
                )
            _count_gate_dropped(ssn, qcounts)
            if stage_flush:
                # the pipelined loop reaches this branch only for EMPTY
                # sessions (exclusive sessions always carry columns): the
                # per-job loop above did nothing, and the queue zero-outs
                # must go through the same staged handoff — an inline write
                # here would race the previous cycle's writeback worker,
                # breaking the single-status-writer design
                flush = ssn.staged_flush = ssn.cache.stage_status_flush(
                    (), qcounts)
            else:
                ssn.cache.update_queue_statuses(qcounts)
    finally:
        if ssn.exclusive:
            # revert surviving Pipelined placements: they exist only inside
            # a session (the reference's clone takes them to the grave;
            # statement.go pipeline no-ops on commit) — next cycle re-derives
            # them from fresh Releasing capacity
            _revert_residue(ssn, ssn.pipelined_tasks, TaskStatus.PIPELINED,
                            release_volumes=False)
            # likewise ALLOCATED residue: allocate only becomes durable via
            # dispatch (ALLOCATED→BINDING when the job turns ready); a task
            # still ALLOCATED here belongs to a job that never became ready
            # this cycle (e.g. backfill into an unready gang) and must not
            # leak node/volume accounting onto the authoritative cache
            _revert_residue(ssn, ssn.allocated_tasks, TaskStatus.ALLOCATED,
                            release_volumes=True)
            if not stage_flush:
                # drain binder acks BEFORE applying deferred ingest: a
                # deferred pod update must observe the durable bindings
                # (pod.node_name) this cycle produced, or it would clobber
                # them.  The pipelined close leaves the drain to the
                # writeback stage — deferred ingest racing the unacked
                # window is protected by the cache's in-flight bind map.
                drain = getattr(ssn.cache, "flush_binds", None)
                if drain is not None:
                    drain()
            ssn.cache.end_exclusive_session()
        ssn.jobs = {}
        ssn.nodes = {}
        ssn.queues = {}
        ssn.plugins = []
        ssn.pipelined_tasks = []
        ssn.allocated_tasks = []
    return flush
