from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.conf import (
    PluginOption,
    SchedulerConfiguration,
    Tier,
    default_configuration,
    load_scheduler_conf,
)
from kube_batch_tpu.framework.interface import (
    Action,
    Plugin,
    get_action,
    get_plugin_builder,
    list_actions,
    register_action,
    register_plugin_builder,
)
from kube_batch_tpu.framework.session import Session, Statement, open_session, close_session

__all__ = [
    "Arguments",
    "PluginOption",
    "SchedulerConfiguration",
    "Tier",
    "default_configuration",
    "load_scheduler_conf",
    "Action",
    "Plugin",
    "get_action",
    "get_plugin_builder",
    "list_actions",
    "register_action",
    "register_plugin_builder",
    "Session",
    "Statement",
    "open_session",
    "close_session",
]
