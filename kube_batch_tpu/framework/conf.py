"""Scheduler behavior configuration (conf/scheduler_conf.go:20-56 +
pkg/scheduler/util.go:31-70 loadSchedulerConf + plugins/defaults.go:22-52).

YAML shape, compatible with the reference's scheduler-conf files:

    actions: "enqueue, reclaim, allocate, backfill, preempt"
    tiers:
    - plugins:
      - name: priority
      - name: gang
      - name: conformance
    - plugins:
      - name: drf
      - name: predicates
      - name: proportion
      - name: nodeorder
        arguments:
          leastrequested.weight: 2

Each plugin option carries nine enable switches (all default true) and an
Arguments string map.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import yaml

from kube_batch_tpu.framework.arguments import Arguments

ENABLE_FIELDS = (
    "enabledJobOrder",
    "enabledJobReady",
    "enabledJobPipelined",
    "enabledTaskOrder",
    "enabledPreemptable",
    "enabledReclaimable",
    "enabledQueueOrder",
    "enabledPredicate",
    "enabledNodeOrder",
)


@dataclasses.dataclass
class PluginOption:
    name: str
    enabled_job_order: bool = True
    enabled_job_ready: bool = True
    enabled_job_pipelined: bool = True
    enabled_task_order: bool = True
    enabled_preemptable: bool = True
    enabled_reclaimable: bool = True
    enabled_queue_order: bool = True
    enabled_predicate: bool = True
    enabled_node_order: bool = True
    arguments: Arguments = dataclasses.field(default_factory=Arguments)


@dataclasses.dataclass
class Tier:
    plugins: List[PluginOption] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class SchedulerConfiguration:
    actions: List[str] = dataclasses.field(default_factory=list)
    tiers: List[Tier] = dataclasses.field(default_factory=list)

    def plugin_option(self, name: str) -> Optional[PluginOption]:
        for tier in self.tiers:
            for p in tier.plugins:
                if p.name == name:
                    return p
        return None

    def plugin_enabled(self, name: str) -> bool:
        return self.plugin_option(name) is not None


def _snake(field: str) -> str:
    # enabledJobOrder → enabled_job_order
    out = []
    for ch in field:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def parse_scheduler_conf(text: str) -> SchedulerConfiguration:
    """Parse the YAML conf; unknown action names raise at load like
    util.go:63-70."""
    data = yaml.safe_load(text) or {}
    actions = [a.strip() for a in str(data.get("actions", "")).split(",") if a.strip()]
    tiers: List[Tier] = []
    for tier_data in data.get("tiers") or []:
        plugins = []
        for p in tier_data.get("plugins") or []:
            opt = PluginOption(name=p["name"])
            for field in ENABLE_FIELDS:
                if field in p:
                    setattr(opt, _snake(field), bool(p[field]))
            if p.get("arguments"):
                opt.arguments = Arguments(
                    {str(k): str(v) for k, v in p["arguments"].items()}
                )
            plugins.append(opt)
        tiers.append(Tier(plugins=plugins))
    return SchedulerConfiguration(actions=actions, tiers=tiers)


DEFAULT_CONF = """
actions: "allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def default_configuration() -> SchedulerConfiguration:
    """The built-in fallback conf (pkg/scheduler/util.go:31-42)."""
    return parse_scheduler_conf(DEFAULT_CONF)


def shipped_conf_path() -> str:
    """Absolute path of the shipped 5-action conf
    (config/kube-batch-tpu-conf.yaml) — the one deployment ships and the
    e2e/bench/sim drivers load; resolved relative to the repo root."""
    import os

    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "config", "kube-batch-tpu-conf.yaml")


def load_scheduler_conf(path: Optional[str]) -> SchedulerConfiguration:
    """Load conf from a file path, or the built-in default when None
    (pkg/scheduler/util.go:44-61). Unknown actions raise KeyError at
    Scheduler construction when resolved against the action registry."""
    if not path:
        return default_configuration()
    with open(path) as f:
        return parse_scheduler_conf(f.read())
