"""Plugin argument map with typed getters (framework/arguments.go:26-66)."""

from __future__ import annotations

from typing import Dict, Optional


class Arguments(dict):
    """map[string]string with GetInt/GetBool/GetFloat semantics: missing or
    unparsable values leave the caller's default untouched."""

    def get_int(self, key: str, default: int) -> int:
        v = self.get(key)
        if v is None:
            return default
        try:
            return int(str(v).strip())
        except ValueError:
            return default

    def get_float(self, key: str, default: float) -> float:
        v = self.get(key)
        if v is None:
            return default
        try:
            return float(str(v).strip())
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool) -> bool:
        v = self.get(key)
        if v is None:
            return default
        s = str(v).strip().lower()
        if s in ("true", "1", "yes"):
            return True
        if s in ("false", "0", "no"):
            return False
        return default
