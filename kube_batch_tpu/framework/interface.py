"""Action/Plugin interfaces and registries (framework/interface.go:20-41,
framework/plugins.go:24-72)."""

from __future__ import annotations

import threading
from typing import Callable, Dict, List

from kube_batch_tpu.framework.arguments import Arguments


class Plugin:
    """A scheduling policy: registers callbacks into the Session on open
    (interface.go:35-41)."""

    name: str = "plugin"

    def __init__(self, arguments: Arguments | None = None):
        self.arguments = arguments or Arguments()

    def on_session_open(self, session) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def on_session_close(self, session) -> None:
        pass


class Action:
    """A scheduling pass over the session (interface.go:20-32)."""

    name: str = "action"

    def initialize(self) -> None:
        pass

    def execute(self, session) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def uninitialize(self) -> None:
        pass


_lock = threading.Lock()
# kbt: allow[KBT003] import-time registry: filled once by module import
# (plugins/__init__, actions/__init__), read-only at scheduling time
_plugin_builders: Dict[str, Callable[[Arguments], Plugin]] = {}
# kbt: allow[KBT003] import-time registry, same contract as _plugin_builders
_actions: Dict[str, Action] = {}


def register_plugin_builder(name: str, builder: Callable[[Arguments], Plugin]) -> None:
    with _lock:
        _plugin_builders[name] = builder


def get_plugin_builder(name: str) -> Callable[[Arguments], Plugin]:
    with _lock:
        if name not in _plugin_builders:
            raise KeyError(f"unknown plugin {name!r}")
        return _plugin_builders[name]


def register_action(action: Action) -> None:
    with _lock:
        _actions[action.name] = action


def get_action(name: str) -> Action:
    with _lock:
        if name not in _actions:
            raise KeyError(f"unknown action {name!r} (util.go:63-70)")
        return _actions[name]


def list_actions() -> List[str]:
    with _lock:
        return sorted(_actions)
