"""`kb-ctl queue create|list` — the reference's cobra CLI
(cmd/cli/queue.go:26-52; pkg/cli/queue/create.go, list.go), speaking the
scheduler's HTTP admin API instead of the Kubernetes API server.

    python -m kube_batch_tpu.cli.queue create --name q1 --weight 2 \
        --server http://127.0.0.1:8080
    python -m kube_batch_tpu.cli.queue list --server http://127.0.0.1:8080
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def _request(server: str, method: str, path: str, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        server.rstrip("/") + path,
        data=data,
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read() or b"null")


def create(args) -> int:
    """(pkg/cli/queue/create.go:38-68)"""
    _request(args.server, "POST", "/v1/queues",
             {"name": args.name, "weight": args.weight})
    print(f"queue/{args.name} created")
    return 0


def list_(args) -> int:
    """(pkg/cli/queue/list.go:51-87): Name, Weight, then the Queue status
    podgroup-phase counts."""
    rows = _request(args.server, "GET", "/v1/queues")
    fmt = "%-25s%-8s%-8s%-8s%-8s%-8s"
    print(fmt % ("Name", "Weight", "Pending", "Running", "Unknown", "Inqueue"))
    for r in rows:
        print(fmt % (r["name"], r["weight"], r["pending"], r["running"],
                     r["unknown"], r["inqueue"]))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kb-ctl queue")
    parser.add_argument("--server", default="http://127.0.0.1:8080",
                        help="scheduler admin API address")
    sub = parser.add_subparsers(dest="cmd", required=True)
    pc = sub.add_parser("create", help="create a queue")
    pc.add_argument("--name", required=True)
    pc.add_argument("--weight", type=int, default=1)
    pc.set_defaults(fn=create)
    pl = sub.add_parser("list", help="list queues")
    pl.set_defaults(fn=list_)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
