"""`kb-ctl queue create|list` — the reference's cobra CLI
(cmd/cli/queue.go:26-52; pkg/cli/queue/create.go, list.go).

Two backends, matching the scheduler's own deployment modes:

  --master http://...   the Kubernetes API server: create/list Queue CRDs
                        (cluster-scoped, scheduling.incubator.k8s.io/v1alpha1)
                        — the reference CLI's clientset path
                        (create.go:47-68, list.go:51-87)
  --server http://...   the scheduler's HTTP admin API — standalone
                        deployments with no apiserver

    python -m kube_batch_tpu.cli.queue create --master https://10.0.0.1:6443 \
        --name q1 --weight 2
    python -m kube_batch_tpu.cli.queue list --server http://127.0.0.1:8080

Connection flags are accepted both before and after the subcommand.
"""

from __future__ import annotations

import argparse
import sys

_QUEUES_PATH = "/apis/scheduling.incubator.k8s.io/v1alpha1/queues"


def _transport(args, server: str):
    from kube_batch_tpu.k8s.transport import ApiTransport

    return ApiTransport(
        server, token=args.token, token_file=args.token_file,
        ca_file=args.ca_file, insecure=args.insecure,
    )


def create(args) -> int:
    """(pkg/cli/queue/create.go:38-68) — in --master mode the authoritative
    queue store is the cluster: the CLI creates the Queue CRD and the
    scheduler picks it up through its watch, exactly like the reference."""
    if args.master:
        _transport(args, args.master).request(
            "POST",
            _QUEUES_PATH,
            {
                "apiVersion": "scheduling.incubator.k8s.io/v1alpha1",
                "kind": "Queue",
                "metadata": {"name": args.name},
                "spec": {"weight": args.weight},
            },
        )
    else:
        _transport(args, args.server).request(
            "POST", "/v1/queues", {"name": args.name, "weight": args.weight}
        )
    print(f"queue/{args.name} created")
    return 0


_LIST_FMT = "%-25s%-8s%-8s%-8s%-8s%-8s"


def list_(args) -> int:
    """(pkg/cli/queue/list.go:51-87): Name, Weight, then the Queue status
    podgroup-phase counts.

    In --master mode the phase counts come from the Queue CRD status. The
    reference never populates those fields (its filler controller arrived
    later, in Volcano), so its CLI prints zeros; THIS scheduler writes them
    back at session close (cache.update_queue_statuses), so the counts are
    live when it is the one scheduling the cluster.  The admin API
    (--server) computes the same counts directly from the scheduler cache."""
    if args.master:
        items = _transport(args, args.master).get_json(_QUEUES_PATH).get("items") or []
        rows = []
        for it in items:
            meta = it.get("metadata") or {}
            spec = it.get("spec") or {}
            status = it.get("status") or {}
            rows.append({
                "name": meta.get("name", ""),
                "weight": spec.get("weight", 1),
                "pending": status.get("pending", 0),
                "running": status.get("running", 0),
                "unknown": status.get("unknown", 0),
                "inqueue": status.get("inqueue", 0),
            })
    else:
        rows = _transport(args, args.server).get_json("/v1/queues")
    print(_LIST_FMT % ("Name", "Weight", "Pending", "Running", "Unknown",
                       "Inqueue"))
    for r in rows:
        print(_LIST_FMT % (r["name"], r["weight"], r["pending"], r["running"],
                           r["unknown"], r["inqueue"]))
    return 0


_CONN_DEFAULTS = {
    "server": "http://127.0.0.1:8080",
    "master": "",
    "token": None,
    "token_file": None,
    "ca_file": None,
    "insecure": False,
}


def main(argv=None) -> int:
    # connection flags live on a parent parser shared with the subcommands,
    # so `queue create --name q --master URL` and
    # `queue --master URL create --name q` both parse.  Defaults are
    # SUPPRESSed and applied after parsing: a subparser's default would
    # otherwise overwrite a value the top-level parser already consumed.
    conn = argparse.ArgumentParser(add_help=False, argument_default=argparse.SUPPRESS)
    conn.add_argument("--server",
                      help="scheduler admin API address (standalone mode)")
    conn.add_argument("--master",
                      help="Kubernetes API server URL — operate on Queue "
                           "CRDs instead of the scheduler admin API")
    conn.add_argument("--token", help="bearer token (--master)")
    conn.add_argument("--token-file")
    conn.add_argument("--ca-file")
    conn.add_argument("--insecure", action="store_true")
    parser = argparse.ArgumentParser(prog="kb-ctl queue", parents=[conn])
    sub = parser.add_subparsers(dest="cmd", required=True)
    pc = sub.add_parser("create", help="create a queue", parents=[conn])
    pc.add_argument("--name", required=True)
    pc.add_argument("--weight", type=int, default=1)
    pc.set_defaults(fn=create)
    pl = sub.add_parser("list", help="list queues", parents=[conn])
    pl.set_defaults(fn=list_)
    args = parser.parse_args(argv)
    for k, v in _CONN_DEFAULTS.items():
        if not hasattr(args, k):
            setattr(args, k, v)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
