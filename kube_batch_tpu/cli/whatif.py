"""`kb-ctl whatif` — the query-plane client (POST /v1/whatif).

Asks the scheduler's serve/ plane "would this gang fit, where, and what
would it evict?" without submitting anything:

    python -m kube_batch_tpu.cli.whatif --server http://127.0.0.1:8080 \
        --queue gold --count 4 --cpu 2000 --mem 2147483648

    # capacity sweep: 32 concurrent identical probes ride one (or few)
    # device dispatches server-side
    python -m kube_batch_tpu.cli.whatif --count 4 --cpu 2000 --repeat 32

`--json` supplies the raw request body instead of flags; `--expect`
(feasible|infeasible) turns the verdict into the exit code for CI smokes.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor


def _post(server: str, body: dict, timeout: float) -> dict:
    req = urllib.request.Request(
        f"{server}/v1/whatif",
        data=json.dumps(body).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _body_from_args(args) -> dict:
    if args.json:
        return json.loads(args.json)
    body = {
        "queue": args.queue,
        "count": args.count,
        "requests": {"cpu": args.cpu, "memory": args.mem},
        "priority": args.priority,
        "evictions": args.evictions,
    }
    if args.min_available is not None:
        body["min_available"] = args.min_available
    if args.selector:
        body["node_selector"] = dict(
            kv.split("=", 1) for kv in args.selector
        )
    return body


def _render(resp: dict) -> str:
    verdict = "FEASIBLE" if resp.get("feasible") else "INFEASIBLE"
    parts = [
        f"{verdict} v{resp.get('snapshot_version')}",
        f"nodes={resp.get('nodes')}",
    ]
    if resp.get("fit_errors"):
        parts.append(f"fit_errors={resp['fit_errors']}")
    ev = resp.get("evictions")
    if ev:
        parts.append(
            f"evict claim={ev['claim_nodes']} victims={len(ev['victims'])} "
            f"covered={ev['covered']}"
        )
    out = "  ".join(parts)
    # verdict honesty: model gaps the server declares for THIS request
    # (unmodeled victim gates, backfill-only BestEffort gangs) print on
    # their own marked lines so scripts and humans can't miss them
    for gap in resp.get("unmodeled") or []:
        out += f"\n  ! unmodeled: {gap}"
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kb-ctl whatif")
    p.add_argument("--server", default="http://127.0.0.1:8080",
                   help="scheduler admin API address")
    p.add_argument("--queue", default="default")
    p.add_argument("--count", type=int, default=1, help="gang size")
    p.add_argument("--min-available", type=int, default=None)
    p.add_argument("--cpu", type=float, default=1000.0, help="milli-cores per member")
    p.add_argument("--mem", type=float, default=float(1 << 30), help="bytes per member")
    p.add_argument("--priority", type=int, default=0)
    p.add_argument("--selector", action="append", default=[],
                   metavar="K=V", help="required node label (repeatable)")
    p.add_argument("--evictions", action="store_true",
                   help="also compute the hypothetical preemption set")
    p.add_argument("--json", default=None,
                   help="raw JSON request body (overrides the flags)")
    p.add_argument("--repeat", type=int, default=1,
                   help="fire N concurrent identical probes (amortization demo)")
    p.add_argument("--timeout", type=float, default=15.0)
    p.add_argument("--expect", choices=("feasible", "infeasible"), default=None,
                   help="exit 1 unless every verdict matches (CI smokes)")
    args = p.parse_args(argv)

    body = _body_from_args(args)
    try:
        if args.repeat <= 1:
            responses = [_post(args.server, body, args.timeout)]
        else:
            with ThreadPoolExecutor(max_workers=min(args.repeat, 64)) as pool:
                responses = list(pool.map(
                    lambda _: _post(args.server, body, args.timeout),
                    range(args.repeat),
                ))
    except urllib.error.HTTPError as e:
        detail = e.read().decode(errors="replace")
        print(f"whatif failed: HTTP {e.code} {detail}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"whatif failed: {e}", file=sys.stderr)
        return 2

    for resp in responses:
        print(_render(resp))
    if args.expect is not None:
        want = args.expect == "feasible"
        if not all(bool(r.get("feasible")) == want for r in responses):
            print(f"verdict mismatch: expected {args.expect}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
