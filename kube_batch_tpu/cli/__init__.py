"""kubectl-style CLI (cmd/cli, pkg/cli/queue in the reference)."""
