"""Standalone PV ledger — a real VolumeBinder behind the cache's seams.

The reference wraps the k8s volumebinder: AllocateVolumes assumes the pod's
PVC→PV bindings for a host (and can fail the placement), BindVolumes makes
them durable (cache.go:189-209, 258-269). Standalone there is no apiserver,
so the ledger itself is the source of truth: PersistentVolume objects are
ingested like nodes, claims resolve against them at allocate time, and a
node from which a required PV is unreachable fails the placement
(FitFailure → the action falls back to the next candidate).

Reservation semantics: allocate_volumes is IDEMPOTENT PER TASK — it first
drops the task's previous reservation, then re-reserves for the new host.
This makes the allocate action's bulk-path volume pre-check safe: a demoted
job's sequential replay re-allocates the same tasks without double-booking.
A reservation left behind by a discarded Statement is likewise superseded on
the next cycle's re-allocate (the reference's unallocate also leaves assumed
volumes to the next BindVolumes/re-assume — convergence by re-running).
"""

from __future__ import annotations

from typing import Dict, Optional

from kube_batch_tpu.api.pod import PersistentVolume


class StandalonePVBinder:
    """VolumeBinder over a local PV ledger."""

    noop = False  # the allocate bulk path must run the volume pre-check

    def __init__(self):
        self.pvs: Dict[str, PersistentVolume] = {}
        self.bound: Dict[str, str] = {}  # claim → pv name (durable binding)
        # task uid → {claim: pv name} (assumed, this cycle)
        self.reservations: Dict[str, Dict[str, str]] = {}
        self._sorted_pvs: list = None  # memo; invalidated on ledger change

    # -- ledger ingest (pv informer analog) ------------------------------
    def add_pv(self, pv: PersistentVolume) -> None:
        self.pvs[pv.name] = pv
        self._sorted_pvs = None

    def delete_pv(self, name: str) -> None:
        self.pvs.pop(name, None)
        self._sorted_pvs = None

    def _candidates(self) -> list:
        """PVs in match order (pre-bound first), memoized — _resolve runs
        once per (node, claim) on the sequential placement path and must not
        re-sort the ledger every probe."""
        if self._sorted_pvs is None:
            self._sorted_pvs = sorted(
                self.pvs.values(), key=lambda pv: (pv.claim is None, pv.name)
            )
        return self._sorted_pvs

    # -- internals --------------------------------------------------------
    def _reserved_pvs(self, excluding_task: Optional[str] = None) -> set:
        held = set(self.bound.values())
        for uid, res in self.reservations.items():
            if uid != excluding_task:
                held.update(res.values())
        return held

    def _resolve(self, claim: str, hostname: str, held: set) -> Optional[str]:
        """Pick a PV for the claim reachable from hostname: a durable
        binding wins, then a pre-bound PV, then any free wildcard PV."""
        bound_pv = self.bound.get(claim)
        if bound_pv is not None:
            pv = self.pvs.get(bound_pv)
            if pv is not None and pv.node in (None, hostname):
                return bound_pv
            return None
        for pv in self._candidates():
            if pv.claim is not None and pv.claim != claim:
                continue
            if pv.node not in (None, hostname):
                continue
            if pv.name in held:
                continue
            return pv.name
        return None

    def volume_feasible(self, task, hostname: str) -> bool:
        """Non-mutating probe: could allocate_volumes succeed right now?
        Used as an extra host predicate by the sequential placement path."""
        claims = getattr(task.pod, "volume_claims", ())
        if not claims:
            return True
        held = self._reserved_pvs(excluding_task=task.uid)
        picked: set = set()
        for claim in claims:
            pv = self._resolve(claim, hostname, held | picked)
            if pv is None:
                return False
            picked.add(pv)
        return True

    # -- VolumeBinder seam ------------------------------------------------
    def allocate_volumes(self, task, hostname: str) -> None:
        """Assume the task's claims onto PVs reachable from hostname.
        Raises FitFailure when any claim can't be satisfied there. Replaces
        any previous reservation the task held (idempotent per task)."""
        from kube_batch_tpu.framework.session import FitFailure

        claims = getattr(task.pod, "volume_claims", ())
        self.reservations.pop(task.uid, None)
        if not claims:
            return
        held = self._reserved_pvs(excluding_task=task.uid)
        picked: Dict[str, str] = {}
        for claim in claims:
            pv = self._resolve(claim, hostname, held | set(picked.values()))
            if pv is None:
                raise FitFailure(
                    f"volume claim {claim!r} has no PV reachable from {hostname}"
                )
            picked[claim] = pv
        self.reservations[task.uid] = picked

    def bind_volumes(self, task) -> None:
        """Make the task's assumed bindings durable (BindVolumes,
        cache.go:258-269)."""
        picked = self.reservations.pop(task.uid, None)
        if picked:
            self.bound.update(picked)

    def release_task(self, task_uid: str) -> None:
        """Drop a task's assumed (not yet bound) reservation — called when
        its pod leaves the cluster so the PVs free up."""
        self.reservations.pop(task_uid, None)
