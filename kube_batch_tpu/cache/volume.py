"""Standalone PV ledger — a real VolumeBinder behind the cache's seams.

The reference wraps the k8s volumebinder: AllocateVolumes assumes the pod's
PVC→PV bindings for a host (and can fail the placement), BindVolumes makes
them durable (cache.go:189-209, 258-269). Standalone there is no apiserver,
so the ledger itself is the source of truth: PersistentVolume objects are
ingested like nodes, claims resolve against them at allocate time, and a
node from which a required PV is unreachable fails the placement
(FitFailure → the action falls back to the next candidate).

Reservation semantics: allocate_volumes is IDEMPOTENT PER TASK — it first
drops the task's previous reservation, then re-reserves for the new host.
This makes the allocate action's bulk-path volume pre-check safe: a demoted
job's sequential replay re-allocates the same tasks without double-booking.
A reservation left behind by a discarded Statement is likewise superseded on
the next cycle's re-allocate (the reference's unallocate also leaves assumed
volumes to the next BindVolumes/re-assume — convergence by re-running).
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

from kube_batch_tpu.api.pod import (
    HOSTNAME_TOPOLOGY,
    PersistentVolume,
    PersistentVolumeClaim,
    node_selector_terms_match,
)

logger = logging.getLogger("kube_batch_tpu")


class StandalonePVBinder:
    """VolumeBinder over a local PV ledger."""

    noop = False  # the allocate bulk path must run the volume pre-check

    def __init__(self):
        self.pvs: Dict[str, PersistentVolume] = {}
        self.bound: Dict[str, str] = {}  # claim → pv name (durable binding)
        # task uid → {claim: pv name} (assumed, this cycle)
        self.reservations: Dict[str, Dict[str, str]] = {}
        # node name → labels, fed by the cache's node ingest: the full
        # nodeSelectorTerms of a topology-restricted PV evaluate against
        # these (the reference volumebinder reads node labels the same way)
        self.node_labels: Dict[str, Dict[str, str]] = {}
        self._sorted_pvs: list = None  # memo; invalidated on ledger change
        # ingest arrives from watch / admin-HTTP threads while the
        # scheduling cycle reads — one coarse lock covers both ledgers
        # (the reference's volumebinder rides the cache's big mutex)
        import threading

        self._lock = threading.RLock()

    # -- ledger ingest (pv informer analog) ------------------------------
    def add_pv(self, pv: PersistentVolume) -> None:
        with self._lock:
            self.pvs[pv.name] = pv
            self._sorted_pvs = None

    def delete_pv(self, name: str) -> None:
        with self._lock:
            self.pvs.pop(name, None)
            self._sorted_pvs = None

    # -- node-label ingest (cache.add_node/delete_node feed this) --------
    def set_node_labels(self, name: str, labels: Dict[str, str]) -> None:
        # synthesize the kubelet-set hostname label and the metadata.name
        # field ONCE here (both equal the node name) so the per-(PV, node)
        # _reachable probe evaluates terms without copying the label map
        merged = {HOSTNAME_TOPOLOGY: name, "metadata.name": name,
                  **(labels or {})}
        with self._lock:
            self.node_labels[name] = merged

    def forget_node_labels(self, name: str) -> None:
        with self._lock:
            self.node_labels.pop(name, None)

    def _reachable(self, pv: PersistentVolume, hostname: str) -> bool:
        """Can `hostname` attach `pv`? The single-node pin (or no affinity)
        answers without labels; a topology-restricted PV evaluates its full
        required nodeSelectorTerms against the candidate's labels. Unknown
        labels fail closed — the PV_NODE_RESTRICTED_UNKNOWN floor of
        ADVICE.md #1 — so an unlabeled/unseen node never fails open."""
        if pv.node is None or pv.node == hostname:
            return True
        terms = getattr(pv, "node_terms", ())
        if not terms:
            return False
        labels = self.node_labels.get(hostname)
        if labels is None:
            # no ingested labels for this node: only hostname-shaped terms
            # are decidable (the kubelet always sets the hostname label /
            # metadata.name IS the node name). Any other key must fail
            # closed — evaluating e.g. a zone NotIn against a synthesized
            # label map would match the absent key and fail OPEN
            hostname_keys = (HOSTNAME_TOPOLOGY, "metadata.name")
            if any(
                key not in hostname_keys
                for term in terms for key, _op, _vals in term
            ):
                return False
            labels = {HOSTNAME_TOPOLOGY: hostname, "metadata.name": hostname}
        # ingested maps already carry the synthesized hostname keys
        # (set_node_labels) — no per-probe copy
        return node_selector_terms_match(terms, labels)

    def _candidates(self) -> list:
        """PVs in match order (pre-bound first), memoized — _resolve runs
        once per (node, claim) on the sequential placement path and must not
        re-sort the ledger every probe."""
        if self._sorted_pvs is None:
            self._sorted_pvs = sorted(
                self.pvs.values(), key=lambda pv: (pv.claim is None, pv.name)
            )
        return self._sorted_pvs

    # -- internals --------------------------------------------------------
    def _reserved_pvs(self, excluding_task: Optional[str] = None) -> set:
        held = set(self.bound.values())
        for uid, res in self.reservations.items():
            if uid != excluding_task:
                held.update(res.values())
        return held

    def _resolve(self, claim: str, hostname: str, held: set) -> Optional[str]:
        """Pick a PV for the claim reachable from hostname: a durable
        binding wins, then a pre-bound PV, then any free wildcard PV."""
        bound_pv = self.bound.get(claim)
        if bound_pv is not None:
            pv = self.pvs.get(bound_pv)
            if pv is not None and self._reachable(pv, hostname):
                return bound_pv
            return None
        for pv in self._candidates():
            if pv.claim is not None and pv.claim != claim:
                continue
            if not self._reachable(pv, hostname):
                continue
            if pv.name in held:
                continue
            return pv.name
        return None

    def volume_feasible(self, task, hostname: str) -> bool:
        """Non-mutating probe: could allocate_volumes succeed right now?
        Used as an extra host predicate by the sequential placement path."""
        claims = getattr(task.pod, "volume_claims", ())
        if not claims:
            return True
        with self._lock:
            held = self._reserved_pvs(excluding_task=task.uid)
            picked: set = set()
            for claim in claims:
                pv = self._resolve(claim, hostname, held | picked)
                if pv is None:
                    return False
                picked.add(pv)
            return True

    # -- VolumeBinder seam ------------------------------------------------
    def allocate_volumes(self, task, hostname: str) -> None:
        """Assume the task's claims onto PVs reachable from hostname.
        Raises FitFailure when any claim can't be satisfied there. Replaces
        any previous reservation the task held (idempotent per task)."""
        from kube_batch_tpu.framework.session import FitFailure

        claims = getattr(task.pod, "volume_claims", ())
        with self._lock:
            self.reservations.pop(task.uid, None)
            if not claims:
                return
            held = self._reserved_pvs(excluding_task=task.uid)
            picked: Dict[str, str] = {}
            for claim in claims:
                pv = self._resolve(claim, hostname, held | set(picked.values()))
                if pv is None:
                    raise FitFailure(
                        f"volume claim {claim!r} has no PV reachable from {hostname}"
                    )
                picked[claim] = pv
            self.reservations[task.uid] = picked

    def bind_volumes(self, task) -> None:
        """Make the task's assumed bindings durable (BindVolumes,
        cache.go:258-269)."""
        with self._lock:
            picked = self.reservations.pop(task.uid, None)
            if picked:
                self.bound.update(picked)

    def release_task(self, task_uid: str) -> None:
        """Drop a task's assumed (not yet bound) reservation — called when
        its pod leaves the cluster so the PVs free up."""
        with self._lock:
            self.reservations.pop(task_uid, None)


# k8s dynamic-provisioning marker class; every other provisioner value means
# the cluster creates a volume on demand (the static marker is the k8s
# convention for local/manual PVs)
NO_PROVISIONER = "kubernetes.io/no-provisioner"
# the WaitForFirstConsumer hand-off annotation the scheduler writes so the
# PV controller binds the claim to a volume reachable from the chosen node
SELECTED_NODE_ANNOTATION = "volume.kubernetes.io/selected-node"


class K8sPVLedger(StandalonePVBinder):
    """The --master mode VolumeBinder, fed by the pv/pvc/storageclass
    watches (the reference's volumebinder informers,
    cache.go:189-209,258-269,311-320).

    Differences from the standalone ledger:
    - claim identity is NAMESPACED ("ns/name"); a pod's claim names resolve
      in the pod's own namespace
    - PVC objects are first-class: spec.volumeName is the durable binding,
      an unknown claim fails placement (the pod references a PVC the
      cluster doesn't have — FindPodVolumes errors the same way)
    - StorageClasses gate unbound claims: a provisioner-backed class is
      dynamically provisionable (feasible on every node — the volume is
      created after scheduling), while kubernetes.io/no-provisioner
      classes must match a free static PV from the ledger, storage class
      and node reachability included
    - bind_volumes makes the binding durable CLUSTER-SIDE too: static
      claims pre-bind their PV by claimRef PATCH (what the k8s volume
      binder's BindPodVolumes does), dynamic claims get the
      WaitForFirstConsumer selected-node annotation so the PV controller
      provisions on the chosen node; every write rides the shared kube-api
      token bucket and failed writes queue for retry on later binds
    """

    # failed cluster writes kept for retry — bounded so an apiserver outage
    # can't grow the queue (and replay staleness) without limit
    MAX_PENDING_WRITES = 256
    # seconds between timer-driven retry flushes while writes are queued —
    # an IDLE scheduler (no further binds) must still drain the queue
    # (ADVICE.md #2: retries used to wait for the next bind_volumes call)
    RETRY_FLUSH_INTERVAL = 5.0

    def __init__(self, transport=None, bucket=None):
        super().__init__()
        self.claims: Dict[str, PersistentVolumeClaim] = {}
        self.storage_classes: Dict[str, str] = {}  # name → provisioner
        self.transport = transport
        self.bucket = bucket  # shared egress TokenBucket (cmd/server.py)
        self._selected_node: Dict[str, str] = {}  # task uid → chosen host
        self._pending_writes: list = []  # failed PATCHes awaiting retry
        self._writer = None  # lazy single-thread pool for cluster writes
        self._retry_timer = None  # armed while _pending_writes is non-empty

    # -- ingest (pvc / storageclass informer analogs) --------------------
    def add_pvc(self, pvc: PersistentVolumeClaim) -> None:
        with self._lock:
            key = pvc.key()
            self.claims[key] = pvc
            if pvc.volume_name:
                self.bound[key] = pvc.volume_name
            # an unbound PVC event does NOT clear a local binding: our
            # claimRef patch / the PV controller round-trip lags the watch,
            # and dropping the entry here would free the PV for a second
            # claim while the first pod's binding is still in flight

    def delete_pvc(self, key: str) -> None:
        with self._lock:
            self.claims.pop(key, None)
            self.bound.pop(key, None)

    def add_storage_class(self, name: str, provisioner: str) -> None:
        with self._lock:
            self.storage_classes[name] = provisioner

    def delete_storage_class(self, name: str) -> None:
        with self._lock:
            self.storage_classes.pop(name, None)

    # -- resolution -------------------------------------------------------
    def _dynamic(self, pvc: PersistentVolumeClaim) -> bool:
        prov = self.storage_classes.get(pvc.storage_class)
        return bool(prov) and prov != NO_PROVISIONER

    def _resolve_k8s(self, key: str, hostname: str, held: set) -> Optional[str]:
        """Pick a PV for claim `key` reachable from hostname, or the empty
        string for a dynamically-provisionable claim (nothing to reserve),
        or None when the placement must fail."""
        pvc = self.claims.get(key)
        if pvc is None:
            return None  # unknown claim — the cluster can't satisfy it
        # a binding we already made locally wins even before the PVC watch
        # round-trips spec.volumeName back (the claimRef PATCH is in
        # flight): without this, the claim's own PV sits in the held set
        # and the claim reads as unsatisfiable everywhere
        bound_pv = self.bound.get(key) or pvc.volume_name
        if bound_pv:
            pv = self.pvs.get(bound_pv)
            if pv is not None and self._reachable(pv, hostname):
                return pv.name
            return None
        if self._dynamic(pvc):
            return ""  # provisioned after scheduling; feasible anywhere
        for pv in self._candidates():
            if pv.claim is not None and pv.claim != key:
                continue
            if pv.storage_class != pvc.storage_class:
                continue
            if not self._reachable(pv, hostname):
                continue
            if pv.name in held:
                continue
            return pv.name
        return None

    def _claim_keys(self, task) -> list:
        ns = task.pod.namespace
        return [f"{ns}/{c}" for c in getattr(task.pod, "volume_claims", ())]

    # -- VolumeBinder seam ------------------------------------------------
    def volume_feasible(self, task, hostname: str) -> bool:
        keys = self._claim_keys(task)
        if not keys:
            return True
        with self._lock:
            held = self._reserved_pvs(excluding_task=task.uid)
            picked: set = set()
            for key in keys:
                pv = self._resolve_k8s(key, hostname, held | picked)
                if pv is None:
                    return False
                if pv:
                    picked.add(pv)
            return True

    def allocate_volumes(self, task, hostname: str) -> None:
        from kube_batch_tpu.framework.session import FitFailure

        keys = self._claim_keys(task)
        with self._lock:
            self.reservations.pop(task.uid, None)
            self._selected_node.pop(task.uid, None)
            if not keys:
                return
            held = self._reserved_pvs(excluding_task=task.uid)
            picked: Dict[str, str] = {}
            for key in keys:
                pv = self._resolve_k8s(key, hostname, held | set(picked.values()))
                if pv is None:
                    raise FitFailure(
                        f"volume claim {key!r} has no PV reachable from {hostname}"
                    )
                # dynamic claims reserve the empty string: nothing to hold,
                # but bind time still needs the claim key for the hand-off
                picked[key] = pv
            self._selected_node[task.uid] = hostname
            self.reservations[task.uid] = picked

    def release_task(self, task_uid: str) -> None:
        with self._lock:
            self.reservations.pop(task_uid, None)
            self._selected_node.pop(task_uid, None)

    def bind_volumes(self, task) -> None:
        """Durable binding, ledger AND cluster: a static claim pre-binds its
        PV by claimRef PATCH (BindPodVolumes' UpdatePV), a dynamic claim
        gets the selected-node annotation so the PV controller provisions on
        the chosen node (BindVolumes, cache.go:258-269).  Failed writes
        queue and retry on later binds."""
        writes = []
        with self._lock:
            picked = self.reservations.pop(task.uid, None) or {}
            hostname = self._selected_node.pop(task.uid, None)
            for key, pv in picked.items():
                ns, name = key.split("/", 1)
                if pv:
                    self.bound[key] = pv
                    writes.append((
                        f"/api/v1/persistentvolumes/{pv}",
                        {"spec": {"claimRef": {
                            "apiVersion": "v1",
                            "kind": "PersistentVolumeClaim",
                            "namespace": ns, "name": name,
                        }}},
                    ))
                elif hostname:
                    writes.append((
                        f"/api/v1/namespaces/{ns}/persistentvolumeclaims/{name}",
                        {"metadata": {"annotations": {
                            SELECTED_NODE_ANNOTATION: hostname}}},
                    ))
        if (writes or self._pending_writes) and self.transport is not None:
            # the writes run OFF-CYCLE on a single worker (the cache's pod
            # binds are likewise async, cache.go:478-484): a slow apiserver
            # must not stall the scheduling cycle's bind loop.  Earlier
            # failures retry first (ordering preserved by the 1-thread
            # pool), and a bind with NO new writes still flushes the retry
            # queue — a stranded claimRef PATCH must not wait for another
            # volume-carrying bind that may never come.
            self._submit_writes(writes)

    def drain_writes(self) -> None:
        """Block until every submitted cluster write ran (tests, shutdown)."""
        with self._lock:
            writer = self._writer
        # result() outside the lock: the queued _run_writes needs it
        if writer is not None:
            writer.submit(lambda: None).result()

    def close(self) -> None:
        """Retire the pv-writes worker with a bounded drain (the tier-D
        worker-shutdown discipline: every pool this codebase spawns has a
        join on its owner's stop path — SchedulerCache.stop() calls this).
        Queued retries are NOT replayed first: shutdown must not block on
        an unreachable apiserver; they stay in _pending_writes and a later
        bind on a revived ledger re-submits them."""
        with self._lock:
            timer, self._retry_timer = self._retry_timer, None
            writer, self._writer = self._writer, None
        if timer is not None:
            timer.cancel()
        if writer is not None:
            writer.shutdown(wait=True)

    # -- throttled, retried, OFF-CYCLE cluster writes ---------------------
    def _submit_writes(self, writes) -> None:
        from kube_batch_tpu.utils.blocking import allow_blocking

        # create + submit under the lock: the retry timer races the bind
        # dispatch thread here, two lazily-built executors would break the
        # single-writer ordering (and drain_writes' fence), and submits must
        # enqueue in lock order for the earlier-failures-retry-first contract
        with self._lock:
            if self._writer is None:
                from concurrent.futures import ThreadPoolExecutor

                self._writer = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="pv-writes"
                )
            with allow_blocking(
                "only the FIRST submit blocks (one-time pv-writes worker "
                "spawn, bounded); the lock is the submit-ordering fence"
            ):
                self._writer.submit(self._run_writes, writes)

    def _run_writes(self, writes) -> None:
        with self._lock:
            pending, self._pending_writes = self._pending_writes, []
        for path, body in pending + list(writes):
            if self.bucket is not None:
                self.bucket.take()
            try:
                self.transport.request(
                    "PATCH", path, body,
                    content_type="application/merge-patch+json", timeout=10,
                )
            except Exception as e:  # noqa: BLE001 — queue for a later flush
                logger.warning("volume write %s failed (%s); queued for retry",
                               path, e)
                with self._lock:
                    self._pending_writes.append((path, body))
                    overflow = len(self._pending_writes) - self.MAX_PENDING_WRITES
                    if overflow > 0:
                        dropped = self._pending_writes[:overflow]
                        del self._pending_writes[:overflow]
                        self._forget_dropped_writes(dropped)
                        logger.warning(
                            "volume write retry queue full; dropped %d oldest "
                            "and released their ledger bindings so later "
                            "cycles re-derive them", overflow,
                        )
        with self._lock:
            timer = self._arm_retry_timer_locked() if self._pending_writes else None
        if timer is not None:
            # start OUTSIDE the lock: Thread.start blocks on the spawned
            # thread's startup handshake (lockdep: blocking-under-lock);
            # the timer can't fire before start, so arming under the lock
            # and starting after it is race-free
            timer.start()

    def _forget_dropped_writes(self, dropped) -> None:
        """A dropped claimRef PATCH must also drop its `bound` entry, or the
        cluster-side bind is lost for good: the unbound-PVC watch event
        deliberately doesn't clear `bound` (the in-flight-PATCH race above),
        so nothing else would ever re-derive the write (ADVICE.md #2).
        Selected-node annotation drops need no ledger undo — the claim re-
        annotates on the task's next allocate/bind. Caller holds the lock."""
        for path, body in dropped:
            ref = ((body.get("spec") or {}).get("claimRef") or {})
            if not ref.get("name"):
                continue
            key = f"{ref.get('namespace', 'default')}/{ref['name']}"
            pv = path.rsplit("/", 1)[-1]
            if self.bound.get(key) == pv:
                del self.bound[key]

    def _arm_retry_timer_locked(self):
        """Create + register a timer-driven flush so queued retries drain
        even when no further bind_volumes call arrives. One timer at a time;
        it disarms itself and re-arms from _run_writes while work remains.
        Returns the timer for the CALLER to start after releasing the lock
        (or None when one is already armed)."""
        if self._retry_timer is not None:
            return None
        import threading

        t = threading.Timer(self.RETRY_FLUSH_INTERVAL, self._timer_flush)
        t.daemon = True
        self._retry_timer = t
        return t

    def _timer_flush(self) -> None:
        with self._lock:
            self._retry_timer = None
            if not self._pending_writes or self.transport is None:
                return
        self._submit_writes([])
