from kube_batch_tpu.cache.interface import Binder, Evictor, StatusUpdater, VolumeBinder
from kube_batch_tpu.cache.fake import FakeBinder, FakeEvictor, FakeStatusUpdater, FakeVolumeBinder
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.cache.volume import StandalonePVBinder

__all__ = [
    "Binder",
    "Evictor",
    "StatusUpdater",
    "VolumeBinder",
    "FakeBinder",
    "FakeEvictor",
    "FakeStatusUpdater",
    "FakeVolumeBinder",
    "SchedulerCache",
    "StandalonePVBinder",
]
