"""Bounded resync/repair queue with per-task backoff + poison quarantine.

The reference repairs failed binds/evictions through a rate-limited
workqueue (cache.go:559-581 over client-go's default item backoff); the
seed replayed that as a flat list drained wholesale every repair tick — a
persistently failing task re-entered every cycle forever, and during an
apiserver brownout EVERY parked decision was retried every second.

This queue restores the workqueue's discipline, deterministically:

- Entries are keyed by task; the per-key attempt history SURVIVES a drain,
  so a task that keeps failing escalates its backoff across park cycles
  instead of restarting from attempt 1 each time.
- Backoff is counted in repair TICKS, not wall seconds — `tick()` is
  called once per repair pass, so behavior is identical under the
  simulator's virtual clock and carries no wall-clock read into cache/
  (KBT001's scope). A task parked for the n-th time waits
  ``min(2^(n-1), backoff_cap)`` ticks before its next repair.
- Parks whose reason is ``breaker-open`` (the egress breaker failing
  fast — the decision was never actually attempted against the server)
  back off but do NOT count toward the poison budget.
- A task that accumulates ``poison_after`` REAL failures is quarantined:
  shelved out of the retry flow with a condition for the operator,
  holding its claimed state, until an external change to its pod
  (update/delete through the watch) releases it. Retrying forever is how
  one poisoned object starves the queue.
- The pending backlog is bounded: beyond ``max_entries`` the OLDEST
  backlog is forced due (bounded *delay*, never dropped repair work).
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("kube_batch_tpu")

#: park reasons
REASON_ERROR = "error"            # the bind/evict call actually failed
REASON_BREAKER = "breaker-open"   # egress failing fast; never attempted


class _Entry:
    __slots__ = ("task", "attempts", "real_failures", "due_tick", "reason",
                 "pending")

    def __init__(self, task):
        self.task = task
        self.attempts = 0
        self.real_failures = 0
        self.due_tick = 0
        self.reason = REASON_ERROR
        self.pending = False


class ResyncQueue:
    """Deterministic per-task backoff queue for the cache's repair loop.

    Not thread-safe by itself — the owning SchedulerCache serializes all
    access under its lock, exactly like the err_tasks list it replaces."""

    def __init__(self, backoff_cap: int = 8, poison_after: int = 5,
                 max_entries: int = 4096):
        self.backoff_cap = max(1, backoff_cap)
        self.poison_after = max(1, poison_after)
        self.max_entries = max(1, max_entries)
        self._tick = 0
        self._entries: Dict[str, _Entry] = {}
        self.quarantined: Dict[str, _Entry] = {}
        # counters (the sim report and /metrics surface these)
        self.parked_total = 0
        self.parked_by_reason: Dict[str, int] = {}
        self.quarantined_total = 0
        self.released_total = 0

    def __len__(self) -> int:
        """Pending (awaiting-repair) depth."""
        return sum(1 for e in self._entries.values() if e.pending)

    def pending_tasks(self) -> List[object]:
        return [e.task for e in self._entries.values() if e.pending]

    def has_history(self) -> bool:
        """Cheap lock-free hint: is there ANY per-key bookkeeping that a
        successful bind should clear? (Empty in the steady state, so the
        bulk ack path pays nothing.)"""
        return bool(self._entries)

    # -- intake ----------------------------------------------------------
    def park(self, task, reason: str = REASON_ERROR) -> bool:
        """Admit (or re-admit) a failed decision; returns False when the
        park was a no-op (the key is quarantined) so callers don't count
        it. Each park of the same key escalates its backoff; breaker-open
        parks never escalate the poison budget (the call was refused
        locally, not rejected)."""
        key = task.key()
        if key in self.quarantined:
            # shelved: an external change releases it, not a re-park
            return False
        e = self._entries.get(key)
        if e is None:
            e = self._entries[key] = _Entry(task)
        e.task = task
        e.attempts += 1
        e.real_failures += int(reason != REASON_BREAKER)
        e.reason = reason
        e.pending = True
        e.due_tick = self._tick + min(2 ** (e.attempts - 1), self.backoff_cap)
        self.parked_total += 1
        self.parked_by_reason[reason] = self.parked_by_reason.get(reason, 0) + 1
        return True

    # -- per-repair-pass drain -------------------------------------------
    def tick(self) -> Tuple[List[object], List[object]]:
        """Advance one repair tick; returns (due_tasks, newly_poisoned).

        Poisoned tasks leave the retry flow here — the caller writes their
        condition and shelves their state. Overflow beyond max_entries
        forces the oldest pending backlog due regardless of backoff."""
        self._tick += 1
        due: List[object] = []
        poisoned: List[object] = []
        overflow = len(self) - self.max_entries
        # dict preserves insertion order → the oldest entries come first
        for key, e in list(self._entries.items()):
            if not e.pending:
                continue
            if e.real_failures >= self.poison_after:
                del self._entries[key]
                self.quarantined[key] = e
                self.quarantined_total += 1
                poisoned.append(e.task)
                continue
            if e.due_tick <= self._tick or overflow > 0:
                if e.due_tick > self._tick:
                    overflow -= 1  # forced due by the bound
                e.pending = False
                due.append(e.task)
        return due, poisoned

    # -- lifecycle hooks --------------------------------------------------
    def forget(self, key: str) -> None:
        """The pod left the store (deleted) — drop all bookkeeping."""
        self._entries.pop(key, None)
        if self.quarantined.pop(key, None) is not None:
            self.released_total += 1

    def release(self, key: str) -> Optional[object]:
        """An external change touched a quarantined pod: give it a fresh
        start (returns the shelved task for an immediate resync)."""
        e = self.quarantined.pop(key, None)
        if e is None:
            return None
        self.released_total += 1
        return e.task

    def note_success(self, key: str) -> None:
        """A later attempt for this key landed — clear the backoff history
        so a future unrelated failure starts from attempt 1."""
        self._entries.pop(key, None)

    def reset_history(self) -> None:
        """Wholesale fresh start (leader failover): drop every pending
        entry and attempt history and release the whole quarantine — the
        rebuilt state supersedes the old reign's failure record."""
        self.released_total += len(self.quarantined)
        self.quarantined.clear()
        self._entries.clear()

    # -- observability ----------------------------------------------------
    def stats(self) -> Dict:
        return {
            "depth": len(self),
            "quarantined": len(self.quarantined),
            "parked_total": self.parked_total,
            "parked_by_reason": dict(self.parked_by_reason),
            "quarantined_total": self.quarantined_total,
            "released_total": self.released_total,
        }

    def apply(self, resync_one: Callable[[object], None],
              quarantine_one: Callable[[object], None]) -> int:
        """One repair pass: tick, resync every due task, shelve the newly
        poisoned. Returns the number of tasks resynced."""
        due, poisoned = self.tick()
        for task in poisoned:
            quarantine_one(task)
        for task in due:
            resync_one(task)
        return len(due)
