"""SchedulerCache — the host-side cluster mirror (pkg/scheduler/cache).

Mirrors cache.go:71-736 + event_handlers.go: a mutex-guarded in-memory image
of pods/nodes/podgroups/queues/priorityclasses, fed by event-handler calls
(the standalone analog of the 10 informers wired at cache.go:256-336), with
Bind/Evict egress through pluggable Binder/Evictor seams, a failed-write
resync queue, and a deep-clone Snapshot consumed by each session.

The device snapshot (api/snapshot.py) is built *from* the session's clone;
this cache stays pure host Python — it is not on the hot path (one snapshot
per cycle)."""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.api.job_info import JobInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.pod import Node, Pod, PodGroup, PriorityClass, Queue
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.resources import DEFAULT_SPEC, ResourceSpec
from kube_batch_tpu.api.task_info import TaskInfo, job_id_for_pod
from kube_batch_tpu.api.types import (
    PodGroupPhase,
    TaskStatus,
    is_allocated,
    queue_phase_counts,
)
from kube_batch_tpu.cache.fake import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
)
from kube_batch_tpu.k8s.transport import CircuitOpenError
from kube_batch_tpu.utils import telemetry
from kube_batch_tpu.utils.assertions import graft_assert

logger = logging.getLogger("kube_batch_tpu")


class EventLog:
    """The k8s Events recorder analog: an append-only record of
    (kind, object_key, message) tuples with BOUNDED retention (the k8s
    event recorder's queue is bounded too; this is a diagnostic record, not
    a durable store).

    `append_scheduled_batch` records a whole cycle's Scheduled events by
    REFERENCE to the dispatcher's staged list and expands them lazily on
    iteration — building 50k tuples inside the bind drain cost ~30 ms of
    the close phase for a record nothing reads on the hot path.  Because a
    batch pins its staged (task, hostname, pod) triples, the retention
    bound matters doubly: once the log exceeds `max_events`, the oldest
    entries (and the object graphs a batch holds) are dropped and counted."""

    __slots__ = ("_entries", "_n", "max_events", "dropped")

    def __init__(self, max_events: int = 200_000):
        from collections import deque

        self._entries = deque()
        self._n = 0
        self.max_events = max_events
        self.dropped = 0

    def _trim(self) -> None:
        while self._n > self.max_events and len(self._entries) > 1:
            e = self._entries.popleft()
            k = len(e) if type(e) is _ScheduledBatch else 1
            self._n -= k
            self.dropped += k

    def append(self, ev: tuple) -> None:
        self._entries.append(ev)
        self._n += 1
        self._trim()

    def extend(self, evs) -> None:
        for ev in evs:
            self.append(ev)

    def append_scheduled_batch(self, staged) -> None:
        """staged: [(task, hostname, pod)] — key/hostname are read at
        iteration time (both immutable once the bind dispatched)."""
        batch = _ScheduledBatch(staged)
        self._entries.append(batch)
        self._n += len(batch)
        self._trim()

    def clear(self) -> None:
        self._entries.clear()
        self._n = 0

    def __iter__(self):
        for e in list(self._entries):
            if type(e) is _ScheduledBatch:
                yield from e
            else:
                yield e

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return bool(self._entries)


class StatusFlush:
    """One cycle's staged status egress — the value-snapshotted handoff
    between the close-derive stage and the writeback stage (see
    SchedulerCache.stage_status_flush / run_status_flush).  Carries no live
    session or job references by construction: PodGroup CLONES to write,
    pre-rendered event/condition ops, decided queue writes, the queue shed
    count, and the degraded verdict taken at stage time."""

    __slots__ = ("to_write", "ops", "qwrites", "shed_queues", "degraded")

    def __init__(self, to_write, ops, qwrites, shed_queues, degraded):
        self.to_write = to_write
        self.ops = ops
        self.qwrites = qwrites
        self.shed_queues = shed_queues
        self.degraded = degraded

    def __bool__(self) -> bool:
        return bool(self.to_write or self.ops or self.qwrites
                    or self.shed_queues)


class _ScheduledBatch:
    __slots__ = ("_staged",)

    def __init__(self, staged):
        self._staged = staged

    def __iter__(self):
        for task, hostname, pod in self._staged:
            if pod is not None:
                yield ("Scheduled", task._key, hostname)

    def __len__(self):
        return sum(1 for _t, _h, pod in self._staged if pod is not None)


class SchedulerCache:
    def __init__(
        self,
        spec: ResourceSpec = DEFAULT_SPEC,
        scheduler_name: str = "volcano",
        default_queue: str = "default",
        binder=None,
        evictor=None,
        status_updater=None,
        volume_binder=None,
        resolve_priority: bool = True,
    ):
        self.spec = spec
        self.scheduler_name = scheduler_name
        self.default_queue = default_queue
        # the persistent columnar host model (api/columns.py): rows assigned
        # at ingest, ledgers shared as views, snapshots built from columns
        from kube_batch_tpu.api.columns import ColumnStore

        self.columns = ColumnStore(spec)
        # cross-cycle churn bookkeeping (cache/dirty.py): ingest handlers
        # stamp a monotonic version + per-kind dirty sets so a low-churn
        # session open can hand out a delta against the previous cycle's
        # open state instead of re-deriving every per-job structure
        from kube_batch_tpu.cache.dirty import DirtyTracker, OpenCache

        self.dirty = DirtyTracker()
        self.open_cache = OpenCache()
        # jobs carrying per-session fit diagnostics (nodes_fit_delta/
        # nodes_fit_errors/job_fit_errors) — the delta open clears exactly
        # these instead of probing all 12.5k jobs (Session.note_fit_state)
        self.fit_state_jobs: set = set()
        import os as _os

        self.delta_enabled = _os.environ.get(
            "KB_SNAPSHOT_DELTA", "1"
        ).strip().lower() not in ("0", "false", "off", "no")
        # fraction of session jobs dirty above which the open falls back to
        # the full rebuild (delta bookkeeping would cost more than it saves)
        self.delta_churn_threshold = float(
            _os.environ.get("KB_DELTA_CHURN_THRESHOLD", "0.25")
        )
        # diagnostics: which path the most recent open took, and its churn
        self.last_open_path = "full"
        self.last_churn = 0.0
        # dirty-tracker version token of the most recent session open — the
        # query plane's snapshot_version (serve/lease.py): a lease published
        # for cycle N reports exactly the ingest state that open consumed
        self.last_open_version = 0
        # the serve/ query plane, when one is attached (QueryPlane.__init__
        # sets it); the allocate action publishes its per-cycle lease here
        self.query_plane = None
        # --priority-class toggle (options.go:30, consumed cache.go:352,378)
        self.resolve_priority = resolve_priority
        self.binder = binder if binder is not None else FakeBinder()
        self.evictor = evictor if evictor is not None else FakeEvictor()
        self.status_updater = status_updater or FakeStatusUpdater()
        self.volume_binder = volume_binder or FakeVolumeBinder()
        self._lock = threading.RLock()
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.priority_classes: Dict[str, PriorityClass] = {}
        self.default_priority: int = 0
        # failed bind/evict tasks awaiting resync (cache.go:559-581) — a
        # bounded backoff queue with poison quarantine (cache/resync.py)
        # instead of the seed's flat retry-every-tick list
        from kube_batch_tpu.cache.resync import ResyncQueue

        self.resync = ResyncQueue(
            backoff_cap=int(_os.environ.get("KB_RESYNC_BACKOFF_CAP", "8")),
            poison_after=int(_os.environ.get("KB_RESYNC_POISON", "5")),
            max_entries=int(_os.environ.get("KB_RESYNC_MAX", "4096")),
        )
        # degraded-cycle signal: while True (set by the scheduler when the
        # cycle's soft time budget elapsed) or while the writeback breaker
        # is open, close-time status flushes shed to the async pool / skip
        self.shed_status_writes = False
        # pod store: the standalone source of truth the resync loop re-GETs
        # from (the apiserver analog)
        self.pods: Dict[str, Pod] = {}
        self.events = EventLog()  # (kind, object_key, message) record
        # last written PodScheduled condition per pod key (dedup,
        # cache.go:151-173 podConditionHaveUpdate)
        self.pod_conditions: Dict[str, dict] = {}
        # per-job earliest next condition-only status write (job_updater.go:20-31)
        self._status_next_write: Dict[str, float] = {}
        # last written QueueStatus counts per queue (delta suppression)
        self._queue_status_written: Dict[str, dict] = {}
        # async dispatcher for binder calls (the `go func` at cache.go:478):
        # cache bookkeeping stays under the lock, the API write happens off
        # the scheduling cycle; failures re-enter via resync_task
        self._dispatch_pool = None
        self._dispatch_futures: List = []
        # leaf mutex over the futures list: the writeback worker's
        # flush_binds races the cycle thread's _dispatch_async in the
        # pipelined loop (never held across a join or a binder call)
        self._dispatch_mu = threading.Lock()
        # close-time status-writeback pool (jobUpdater's 16 workers,
        # job_updater.go:18) — created lazily for parallel-safe updaters
        self._status_pool = None
        # background repair loop (cache.go:342-384) — started by run()
        self._repair_thread: Optional[threading.Thread] = None
        self._repair_stop = threading.Event()
        # initial-sync barrier (WaitForCacheSync analog, cache.go:363-384)
        self._synced = threading.Event()
        # exclusive-session gate: while a scheduling cycle owns the cache
        # (the no-clone session mode), ingest/repair mutations are DEFERRED
        # and applied at session close — the same once-per-cycle staleness an
        # informer snapshot has, without paying the deep clone
        self._session_active = False
        self._deferred: List = []
        # read-side ingest staging (the pipelined loop's ingest stage): when
        # enabled, the public ingest surface appends (fn, args) under a small
        # LEAF lock instead of contending on the big lock, so a watch/ingest
        # thread never stalls behind a snapshot or replay in progress; the
        # cycle applies the whole buffer under ONE big-lock acquisition at
        # its ingest stage (drain_staged_ingest)
        self._ingest_lock = threading.Lock()
        self._ingest_staged: List = []
        self.ingest_staging = False
        # thread idents currently applying ingest DIRECTLY (the staged
        # drain, a batched apply): their re-entrant handler calls must
        # not re-stage.  A SET, not a single slot — the cycle's drain
        # and a /v1 batch apply can overlap, and a shared slot's
        # save/restore would clobber the other thread's marker (the
        # drain would then re-stage its own events and apply nothing).
        # Adds/discards of own ident only; reads are GIL-atomic.
        self._direct_apply_threads: set = set()
        # threads inside the cycle's staged-ingest DRAIN specifically:
        # their dirty advances must not re-wake the trigger (see
        # _dirty_advanced) — a subset of the direct appliers
        self._cycle_drain_threads: set = set()
        # the event-driven cycle trigger's wake callback (pipeline.py
        # CycleTrigger.notify): fired on staged ingest arrival and on dirty
        # version advances that happen outside a session (repair rebuilds,
        # deferred-ingest application) — never on the cycle's own close-time
        # status bookkeeping, which would re-trigger every cycle
        self._ingest_listener = None
        self.dirty.on_advance = self._dirty_advanced
        # binder dispatches in flight (pod key → hostname), staged when the
        # async dispatcher takes a batch and cleared by its ack/failure:
        # update_pod consults it so a client update arriving between the
        # dispatch and the ack cannot clobber the in-flight binding (the
        # pipelined loop overlaps the binder drain with the next cycle's
        # ingest, which widens that window from ~0 to a whole stage)
        self._inflight_bind_hosts: Dict[str, str] = {}
        # pod-arrival timestamps (key → perf_counter) for the arrival→
        # bind-decision latency histogram; stamped at ingest for pending
        # unbound owned pods, popped at the bind decision or pod deletion
        self._arrival_ts: Dict[str, float] = {}

    # ------------------------------------------------------------------
    # exclusive-session gate (no-clone session mode)
    # ------------------------------------------------------------------
    def begin_exclusive_session(self) -> None:
        with self._lock:
            graft_assert(not self._session_active,
                         "nested exclusive sessions are not supported")
            self._session_active = True

    def end_exclusive_session(self) -> None:
        """Release the cycle's ownership and apply every mutation that
        arrived during it, in order."""
        with self._lock:
            self._session_active = False
            deferred, self._deferred = self._deferred, []
            for fn, args in deferred:
                try:
                    fn(*args)
                except Exception:  # noqa: BLE001 — one bad event must not
                    logger.exception("deferred ingest event failed")

    def _gate(self, fn, *args) -> bool:
        """Returns True when the mutation was deferred (session active)."""
        if self._session_active:
            self._deferred.append((fn, args))
            return True
        return False

    # ------------------------------------------------------------------
    # ingest staging + event trigger (the pipelined loop's ingest stage)
    # ------------------------------------------------------------------
    def _dirty_advanced(self) -> None:
        """DirtyTracker version-advance hook: wake the cycle trigger for
        out-of-session churn (ingest, repair rebuilds, deferred events).
        In-session advances are the cycle's own bookkeeping — the deferred
        events that carry real churn re-stamp when they apply at close.
        The cycle's OWN staged-ingest drain is suppressed too: the session
        about to open consumes exactly that churn, and re-waking would
        schedule a guaranteed no-op follow-up cycle after every burst.  A
        direct batch apply (ingest_batch with staging off) is NOT a drain
        — its one coalesced advance must wake the loop."""
        # kbt: allow[KBT301] lock-free wake hint — a stale read costs at
        # most one extra (cheap, idempotent) trigger wake, never a miss
        if self._session_active:
            return
        if threading.get_ident() in self._cycle_drain_threads:
            return
        fn = self._ingest_listener
        if fn is not None:
            fn()

    def set_ingest_signal(self, fn) -> None:
        """Register (or clear, fn=None) the event-trigger wake callback.
        Must never block: it runs under the cache's big lock from dirty
        stamps and under the ingest staging lock from _stage."""
        self._ingest_listener = fn

    def enable_ingest_staging(self) -> None:
        with self._ingest_lock:
            self.ingest_staging = True

    def disable_ingest_staging(self) -> None:
        with self._ingest_lock:
            self.ingest_staging = False
        self.drain_staged_ingest()

    def _stage(self, fn, *args) -> bool:
        """Stage an ingest mutation instead of applying it (True when
        staged).  OFF by default (one attribute read); the drain thread
        itself always applies directly (its re-entrant calls must not
        re-stage).  The wake signal fires OUTSIDE the staging lock so the
        trigger's condition lock stays unordered against it."""
        # kbt: allow[KBT301] double-checked peek — re-read under the lock
        if not self.ingest_staging:
            return False
        # kbt: allow[KBT301] own-ident set membership is GIL-atomic
        if threading.get_ident() in self._direct_apply_threads:
            return False
        with self._ingest_lock:
            if not self.ingest_staging:
                return False
            self._ingest_staged.append((fn, args))
        fn2 = self._ingest_listener
        if fn2 is not None:
            fn2()
        return True

    def _note_staged_arrival(self, obj) -> None:
        """Arrival→decision clocks start at TRUE ingest: a staged pending
        pod is stamped when it lands in the staging buffer, not when the
        next cycle's drain applies it — otherwise the latency metric
        undercounts the stage→drain wait in exactly the mode it exists to
        measure.  The apply-time stamp in _add_task is conditional on the
        key being absent, so this earlier stamp survives the drain.
        Setdefault on a plain dict is GIL-atomic; non-pod kinds no-op."""
        if isinstance(obj, Pod) and obj.node_name is None:
            # kbt: allow[KBT301] setdefault on a plain dict is GIL-atomic
            self._arrival_ts.setdefault(obj.key(), telemetry.perf_counter())

    def drain_staged_ingest(self) -> int:
        """Apply every staged ingest event under ONE big-lock acquisition —
        the pipeline's ingest stage.  Events apply in arrival order; a bad
        event logs and is skipped (informer handler semantics)."""
        with self._ingest_lock:
            staged, self._ingest_staged = self._ingest_staged, []
        if not staged:
            return 0
        ident = threading.get_ident()
        # kbt: allow[KBT301] own-ident set ops are GIL-atomic: each thread
        # only ever adds/discards ITS OWN ident, so no two threads contend
        # on the same element and a torn composite read is impossible
        nested = ident in self._direct_apply_threads
        # kbt: allow[KBT301] own-ident set add is GIL-atomic (see above)
        self._direct_apply_threads.add(ident)
        self._cycle_drain_threads.add(ident)
        try:
            with self._lock:
                for fn, args in staged:
                    try:
                        fn(*args)
                    except Exception:  # noqa: BLE001 — one bad event
                        logger.exception("staged ingest event failed")
        finally:
            self._cycle_drain_threads.discard(ident)
            if not nested:
                # kbt: allow[KBT301] own-ident set discard is GIL-atomic
                self._direct_apply_threads.discard(ident)
        return len(staged)

    def ingest_batch(self, ops) -> int:
        """Apply ``[(fn, obj)]`` ingest operations under one lock
        acquisition and ONE dirty-version advance (the batched ``/v1/*``
        ingest path: high-QPS clients pay a single lock round-trip per
        batch, and the lease/delta version token moves once).  With
        staging enabled the whole batch stages under one staging-lock
        acquisition + one wake instead.

        Returns the number of operations APPLIED (staging: accepted for the
        next cycle's drain).  A handler that raises drops only its own
        element — callers compare against ``len(ops)`` to detect partial
        failure."""
        if not ops:
            return 0
        if (self.ingest_staging  # kbt: allow[KBT301] double-checked peek
                # kbt: allow[KBT301] own-ident set membership is GIL-atomic
                and threading.get_ident() not in self._direct_apply_threads):
            with self._ingest_lock:
                if self.ingest_staging:
                    self._ingest_staged.extend(
                        (fn, (obj,)) for fn, obj in ops
                    )
                    staged = True
                else:
                    staged = False
            if staged:
                for _fn, obj in ops:
                    self._note_staged_arrival(obj)
                fn2 = self._ingest_listener
                if fn2 is not None:
                    fn2()
                return len(ops)
        with self._lock:
            # mark this thread as a direct applier so a handler re-entered
            # here never re-stages (staging could flip on mid-batch)
            ident = threading.get_ident()
            nested = ident in self._direct_apply_threads
            self._direct_apply_threads.add(ident)
            self.dirty.hold_version()
            applied = 0
            try:
                for fn, obj in ops:
                    try:
                        fn(obj)
                        applied += 1
                    except Exception:  # noqa: BLE001 — one bad event
                        logger.exception("batched ingest event failed")
            finally:
                self.dirty.release_version()
                if not nested:
                    self._direct_apply_threads.discard(ident)
        return applied

    # ------------------------------------------------------------------
    # background repair loops (cache.go:342-384)
    # ------------------------------------------------------------------
    def run(self, resync_period: float = 1.0) -> None:
        """Start the background repair thread — the processResyncTask +
        processCleanupJob goroutines (cache.go:342-384, 533-581). Idempotent;
        the thread drains err_tasks and collects terminated jobs every
        resync_period seconds until stop()."""
        if self._repair_thread is not None and self._repair_thread.is_alive():
            return
        self._repair_stop = threading.Event()
        stop = self._repair_stop

        def loop():
            while not stop.wait(resync_period):
                try:
                    self.process_resync_tasks()
                    self.process_cleanup_jobs()
                except Exception:  # noqa: BLE001 — repair must not die
                    logger.exception("cache repair iteration failed")

        self._repair_thread = threading.Thread(
            target=loop, name="kb-cache-repair", daemon=True
        )
        self._repair_thread.start()

    def mark_synced(self) -> None:
        """Signal that the initial cluster sync is complete (the informer
        HasSynced analog) — set by load_state, by POST /v1/sync on the ingest
        API, or implicitly by the wait timeout below."""
        self._synced.set()

    def wait_for_cache_sync(self, timeout: Optional[float] = None) -> bool:
        """WaitForCacheSync (cache.go:363-384): block the scheduling loop
        until the initial state has landed. Standalone there are no LIST
        watermarks, so "synced" is an explicit signal (mark_synced / the
        ingest API's sync barrier) with a bounded wait: on timeout the loop
        proceeds with whatever arrived — convergence-by-re-running covers a
        late-arriving remainder exactly like any other cluster change."""
        if timeout is None:
            return self._synced.is_set()
        ok = self._synced.wait(timeout)
        if not ok:
            logger.warning(
                "cache sync signal not received within %.1fs; scheduling over "
                # kbt: allow[KBT301] log-only dict sizes — a stale count is fine
                "%d nodes / %d jobs as-is", timeout, len(self.nodes), len(self.jobs),
            )
        return ok

    def stop(self) -> None:
        self._repair_stop.set()
        if self._repair_thread is not None:
            self._repair_thread.join(timeout=5.0)
            self._repair_thread = None
        # drain + retire the async bind dispatcher so a stopped cache is
        # quiescent (no lingering kb-dispatch thread, no post-stop binder
        # calls); _dispatch_async lazily recreates the pool if needed again
        pool, self._dispatch_pool = self._dispatch_pool, None
        if pool is not None:
            pool.shutdown(wait=True)
        with self._dispatch_mu:
            self._dispatch_futures = []
        spool, self._status_pool = self._status_pool, None
        if spool is not None:
            spool.shutdown(wait=True)
        # the PV ledger owns a lazy pv-writes pool (cache/volume.py);
        # FakeVolumeBinder has no close — seam-probe like the other
        # volume_binder capabilities above
        close = getattr(self.volume_binder, "close", None)
        if close is not None:
            close()

    # ------------------------------------------------------------------
    # ingest: pods (event_handlers.go:42-200)
    # ------------------------------------------------------------------
    def _owns(self, pod: Pod) -> bool:
        """Informer filter (cache.go:283-305): our scheduler's pods, or pods
        already bound anywhere (needed for node accounting)."""
        return pod.scheduler_name == self.scheduler_name or pod.node_name is not None

    def _resolve_pod_priority(self, pod: Pod) -> None:
        if not self.resolve_priority:
            return
        if pod.priority == 0 and pod.priority_class:
            pc = self.priority_classes.get(pod.priority_class)
            if pc is not None:
                pod.priority = pc.value
        elif pod.priority == 0 and self.default_priority:
            pod.priority = self.default_priority

    def _get_or_create_job(self, task: TaskInfo, pod: Pod) -> JobInfo:
        """(event_handlers.go:42-67) jobs keyed by group annotation; plain
        pods owned by this scheduler get a shadow PodGroup with minMember=1
        (cache/util.go:42-60)."""
        job = self.jobs.get(task.job)
        if job is None:
            job = JobInfo(task.job, self.spec)
            self.jobs[task.job] = job
            self.columns.bind_job(job)
        if job.pod_group is None and pod.group_name is None and job.pdb is None:
            shadow = PodGroup(
                name=pod.name,
                namespace=pod.namespace,
                min_member=1,
                queue=self.default_queue,
                creation_index=pod.creation_index,
                shadow=True,
            )
            job.set_pod_group(shadow)
        return job

    def add_pod(self, pod: Pod) -> None:
        if self._stage(self.add_pod, pod):
            self._note_staged_arrival(pod)
            return
        with self._lock:
            if self._gate(self.add_pod, pod):
                return
            if pod.key() in self.pods:
                # informer semantics are add-or-update: a duplicate ADDED
                # (watch reconnect races, replayed seeds) must upsert, not
                # trip the duplicate-task invariant.  Checked BEFORE the
                # ownership gate: the new state may have LEFT our ownership
                # (rebound to another scheduler) and update_pod drops the
                # stale cached task either way
                self.update_pod(pod)
                return
            if not self._owns(pod):
                return
            self._resolve_pod_priority(pod)
            self.pods[pod.key()] = pod
            task = TaskInfo(pod, self.spec)
            self._add_task(task, pod)

    def _add_task(self, task: TaskInfo, pod: Pod) -> None:
        job = self._get_or_create_job(task, pod)
        self.dirty.note_pod(task._key)
        self.dirty.note_job(job.uid)
        if task.node_name is None and task._key not in self._arrival_ts:
            # arrival→bind-decision latency clock starts at first ingest of
            # an unbound pod; kubelet status replays keep the original stamp
            self._arrival_ts[task._key] = telemetry.perf_counter()
        job.add_task(task)
        self.columns.bind_task(task, job)
        if task.node_name:
            node = self.nodes.get(task.node_name)
            if node is None:
                # pod arrived before its node: hold a nodeless NodeInfo;
                # set_node replays accounting when the node shows up
                node = NodeInfo(None, self.spec)
                node.name = task.node_name
                self.nodes[task.node_name] = node
                self.columns.bind_node(node)
            node.add_task(task)

    def update_pod(self, pod: Pod) -> None:
        """delete + add (event_handlers.go:116-130).

        pod.spec.nodeName is write-once and scheduler-owned (k8s semantics:
        clients can't unbind via update; the Binding subresource sets it):
        an incoming update without a node keeps the stored pod's binding —
        without this, a client update raced against the scheduler's own bind
        (or deferred past it by the exclusive-session gate) would clobber the
        placement and the next cycle would double-bind the pod."""
        if self._stage(self.update_pod, pod):
            self._note_staged_arrival(pod)
            return
        with self._lock:
            if self._gate(self.update_pod, pod):
                return
            stored = self.pods.get(pod.key())
            if stored is not None and not pod.node_name:
                # an UNACKED async bind counts as a binding too: the
                # pipelined loop drains the binder behind the next cycle's
                # ingest, so an update landing in that window must keep the
                # dispatched placement (the ack or the failure handler
                # settles it); _dispatch_async clears a failed dispatch's
                # optimistic stamp
                pod.node_name = (stored.node_name
                                 or self._inflight_bind_hosts.get(pod.key()))
            # an external change to a QUARANTINED pod releases it back into
            # the ordinary flow — the rebuild below IS its fresh resync
            self.resync.release(pod.key())
            # the arrival→decision clock starts at FIRST ingest: a status
            # replay on a still-pending pod must not reset it through the
            # delete+add rebuild below
            t_arr = self._arrival_ts.get(pod.key())
            # the add below would immediately recreate a placeholder the
            # delete retired — keep it alive across an update, or every
            # status event for such a pod flushes the node feature cache
            self._delete_pod_locked(pod, retire_placeholder=not self._owns(pod))
            if self._owns(pod):
                self._resolve_pod_priority(pod)
                self.pods[pod.key()] = pod
                self._add_task(TaskInfo(pod, self.spec), pod)
                if t_arr is not None and pod.key() in self._arrival_ts:
                    self._arrival_ts[pod.key()] = t_arr

    def delete_pod(self, pod: Pod) -> None:
        if self._stage(self.delete_pod, pod):
            return
        with self._lock:
            if self._gate(self.delete_pod, pod):
                return
            self._delete_pod_locked(pod)

    def _delete_pod_locked(self, pod: Pod, retire_placeholder: bool = True,
                           forget_resync: bool = True) -> None:
        self.pods.pop(pod.key(), None)
        self.pod_conditions.pop(pod.key(), None)  # fresh pod ⇒ fresh dedup
        self._arrival_ts.pop(pod.key(), None)
        self._inflight_bind_hosts.pop(pod.key(), None)
        if forget_resync:
            # external change/delete: all repair bookkeeping (incl. the
            # quarantine) starts over. The resync pass's OWN delete+add
            # rebuild passes False — it must not erase the very attempt
            # history whose backoff it implements.
            self.resync.forget(pod.key())
        self.dirty.note_pod(pod.key())
        self.dirty.note_job(job_id_for_pod(pod))
        release = getattr(self.volume_binder, "release_task", None)
        if release is not None:
            release(pod.uid)  # free assumed-but-unbound PV reservations
        job_id = job_id_for_pod(pod)
        job = self.jobs.get(job_id)
        if job is not None:
            task = job.tasks.get(pod.key())
            if task is not None:
                job.delete_task(task)
                node = self.nodes.get(task.node_name) if task.node_name else None
                if node is not None and task.key() in node.tasks:
                    node.remove_task(task)
                    # a deleted-node placeholder exists only to carry its
                    # residents; the last one leaving retires it
                    if retire_placeholder and node.node is None and not node.tasks:
                        self.nodes.pop(node.name, None)
                        self.columns.free_node(node)
                self.columns.free_task(task)
            self._maybe_collect_job(job)

    def _maybe_collect_job(self, job: JobInfo) -> None:
        """processCleanupJob analog (cache.go:533-557, JobTerminated
        helpers.go:102-106): drop a job once it has no tasks, no (non-shadow)
        PodGroup, and no PDB."""
        if (
            not job.tasks
            and (job.pod_group is None or job.pod_group.shadow)
            and job.pdb is None
        ):
            if self.jobs.pop(job.uid, None) is not None:
                self.dirty.note_job(job.uid)
                self.columns.free_job(job)
                from kube_batch_tpu import metrics

                metrics.prune_job_series(job.uid)
            self._status_next_write.pop(job.uid, None)

    # ------------------------------------------------------------------
    # ingest: nodes (event_handlers.go:261-360)
    # ------------------------------------------------------------------
    def add_node(self, node: Node) -> None:
        if self._stage(self.add_node, node):
            return
        with self._lock:
            if self._gate(self.add_node, node):
                return
            self.dirty.note_node(node.name)
            existing = self.nodes.get(node.name)
            if existing is None:
                info = NodeInfo(node, self.spec)
                self.nodes[node.name] = info
                self.columns.bind_node(info)
            else:
                existing.set_node(node)
            # topology-restricted PVs evaluate their nodeSelectorTerms
            # against these labels in the volume ledger (cache/volume.py)
            set_labels = getattr(self.volume_binder, "set_node_labels", None)
            if set_labels is not None:
                set_labels(node.name, node.labels)

    def update_node(self, node: Node) -> None:
        self.add_node(node)

    def delete_node(self, name: str) -> None:
        if self._stage(self.delete_node, name):
            return
        with self._lock:
            if self._gate(self.delete_node, name):
                return
            node = self.nodes.get(name)
            if node is None:
                return
            self.dirty.note_node(name)
            # a gone node can't attach volumes: drop its labels so ledger
            # reachability fails closed for it immediately
            forget = getattr(self.volume_binder, "forget_node_labels", None)
            if forget is not None:
                forget(name)
            if node.tasks:
                # resident pods outlive the Node object (their NodeName
                # persists, like the reference's); demote to the nodeless
                # placeholder the pod-before-node ingest uses instead of
                # orphaning them — a re-added node then replays their
                # accounting via set_node, and a kubelet update can't
                # re-account a task into already-consumed fresh capacity
                node.demote_to_placeholder()
                return
            self.nodes.pop(name)
            self.columns.free_node(node)

    # ------------------------------------------------------------------
    # ingest: podgroups (event_handlers.go:362-481)
    # ------------------------------------------------------------------
    def add_pod_group(self, pg: PodGroup) -> None:
        if self._stage(self.add_pod_group, pg):
            return
        with self._lock:
            if self._gate(self.add_pod_group, pg):
                return
            if not pg.queue:
                pg.queue = self.default_queue  # default fill
            job_id = pg.key()
            self.dirty.note_job(job_id)
            job = self.jobs.get(job_id)
            if job is None:
                job = JobInfo(job_id, self.spec)
                self.jobs[job_id] = job
                self.columns.bind_job(job)
            job.set_pod_group(pg)

    def update_pod_group(self, pg: PodGroup) -> None:
        self.add_pod_group(pg)

    def delete_pod_group(self, key: str) -> None:
        if self._stage(self.delete_pod_group, key):
            return
        with self._lock:
            if self._gate(self.delete_pod_group, key):
                return
            self.dirty.note_job(key)
            job = self.jobs.get(key)
            if job is not None:
                job.pod_group = None
                if not job.tasks:
                    if self.jobs.pop(key, None) is not None:
                        self.columns.free_job(job)
            self._status_next_write.pop(key, None)

    # ------------------------------------------------------------------
    # ingest: pod disruption budgets — the legacy gang source
    # (event_handlers.go:484-594)
    # ------------------------------------------------------------------
    def add_pdb(self, pdb) -> None:
        """setPDB: the job is keyed by the PDB's controller UID (the same
        key owner-linked pods land on, cache/util.go:42-46); min-available
        comes from the PDB; queue is always the default (PDB has no queue
        concept, event_handlers.go:497-498)."""
        if not pdb.owner:
            logger.error("PodDisruptionBudget %s has no controller; ignored",
                         pdb.name)
            return
        if self._stage(self.add_pdb, pdb):
            return
        with self._lock:
            if self._gate(self.add_pdb, pdb):
                return
            job_id = f"{pdb.namespace}/{pdb.owner}"
            self.dirty.note_job(job_id)
            job = self.jobs.get(job_id)
            if job is None:
                job = JobInfo(job_id, self.spec)
                self.jobs[job_id] = job
                self.columns.bind_job(job)
            # a shadow PodGroup synthesized for owner pods that arrived
            # before their PDB yields to the PDB as the gang source (its
            # min_member=1 would otherwise mask the PDB's min-available and
            # divert status writeback from the events-only path)
            if job.pod_group is not None and job.pod_group.shadow:
                job.pod_group = None
            job.set_pdb(pdb)
            job.queue = self.default_queue

    def update_pdb(self, pdb) -> None:
        self.add_pdb(pdb)

    def delete_pdb(self, pdb) -> None:
        if not pdb.owner:
            return
        if self._stage(self.delete_pdb, pdb):
            return
        with self._lock:
            if self._gate(self.delete_pdb, pdb):
                return
            job = self.jobs.get(f"{pdb.namespace}/{pdb.owner}")
            if job is None:
                return
            self.dirty.note_job(job.uid)
            job.unset_pdb()
            if job.tasks and job.pod_group is None:
                # re-synthesize the shadow PodGroup the PDB displaced so the
                # owner's pods keep scheduling as singletons (divergence
                # from the reference, which leaves the job excluded from
                # snapshots — cache.go:625-633 — until its pods are deleted)
                any_pod = next(iter(job.tasks.values())).pod
                job.set_pod_group(PodGroup(
                    name=any_pod.name,
                    namespace=any_pod.namespace,
                    min_member=1,
                    queue=self.default_queue,
                    creation_index=any_pod.creation_index,
                    shadow=True,
                ))
            self._maybe_collect_job(job)

    # ------------------------------------------------------------------
    # ingest: queues / priority classes (event_handlers.go:597-785)
    # ------------------------------------------------------------------
    def add_queue(self, queue: Queue) -> None:
        if self._stage(self.add_queue, queue):
            return
        with self._lock:
            if self._gate(self.add_queue, queue):
                return
            self.dirty.mark_queues()
            qinfo = QueueInfo(queue)
            self.queues[queue.name] = qinfo
            self.columns.bind_queue(qinfo)

    def update_queue(self, queue: Queue) -> None:
        self.add_queue(queue)

    def delete_queue(self, name: str) -> None:
        if self._stage(self.delete_queue, name):
            return
        with self._lock:
            if self._gate(self.delete_queue, name):
                return
            self.dirty.mark_queues()
            self.queues.pop(name, None)
            # a recreated queue must get a fresh status write even when its
            # first counts happen to equal the deleted one's last record
            self._queue_status_written.pop(name, None)
            self.columns.free_queue(name)

    def add_priority_class(self, pc: PriorityClass) -> None:
        if not self.resolve_priority:
            return  # informer not wired when disabled (cache.go:352,378)
        if self._stage(self.add_priority_class, pc):
            return
        with self._lock:
            if self._gate(self.add_priority_class, pc):
                return
            self.dirty.mark_priority_classes()
            self.priority_classes[pc.name] = pc
            if pc.global_default:
                self.default_priority = pc.value

    def delete_priority_class(self, name: str) -> None:
        if self._stage(self.delete_priority_class, name):
            return
        with self._lock:
            if self._gate(self.delete_priority_class, name):
                return
            self.dirty.mark_priority_classes()
            pc = self.priority_classes.pop(name, None)
            if pc is not None and pc.global_default:
                self.default_priority = 0

    # ------------------------------------------------------------------
    # egress: bind / evict (cache.go:404-487)
    # ------------------------------------------------------------------
    def _own_task(self, task: TaskInfo) -> Optional[TaskInfo]:
        job = self.jobs.get(task.job)
        return job.tasks.get(task.key()) if job else None

    def bind(self, task: TaskInfo, hostname: str) -> None:
        """Mark Binding in the cache, then call the binder; a binder failure
        queues the task for resync (cache.go:447-487; synchronous here — the
        async goroutine is replaced by the resync repair path)."""
        with self._lock:
            if not self._session_active:
                own = self._own_task(task)
                if own is not None:
                    job = self.jobs[task.job]
                    job.update_task_status(own, TaskStatus.BINDING)
                    own.node_name = hostname
                    node = self.nodes.get(hostname)
                    if node is not None and own.key() not in node.tasks:
                        node.add_task(own)
            # exclusive session: the session already holds this very task in
            # the right state; the caller (Statement/dispatch) finishes the
            # BINDING transition itself
            pod = self.pods.get(task.key())
            t0 = (self._arrival_ts.pop(task.key(), None)
                  if pod is not None else None)
        if t0 is not None:
            from kube_batch_tpu import metrics

            lat_ms = [(telemetry.perf_counter() - t0) * 1e3]
            metrics.observe_decision_latencies(lat_ms)
            tr = getattr(self, "tracer", None)
            if tr is not None:
                # span-stamped twin of the histogram sample (obs/trace.py):
                # the cycle's trace tree carries the same values, and an
                # SLO breach arms a flight-recorder dump
                tr.note_decision_latencies(lat_ms)
        try:
            if pod is not None:
                self.binder.bind(pod, hostname)
                # binding ack → durable in the pod store (the apiserver
                # Binding subresource analog)
                pod.node_name = hostname
                self.events.append(("Scheduled", task.key(), hostname))
                if self.resync.has_history():
                    with self._lock:
                        self.resync.note_success(task.key())
        except CircuitOpenError:
            # egress failing fast — park without charging the poison budget
            logger.warning("bind of %s parked: egress breaker open", task.key())
            self.resync_task(task, reason="breaker-open")
        except Exception as e:  # noqa: BLE001 — repair path mirrors resyncTask
            logger.error("bind of %s to %s failed: %s", task.key(), hostname, e)
            self.resync_task(task)

    def bulk_bind(self, tasks_hosts, job_sums=None, node_sums=None) -> None:
        """bind() for a batch under ONE lock acquisition — the allocate
        replay's commit takes this path with every placement of the cycle;
        per-task semantics are identical to bind().  Job and node accounting
        are applied groupwise (bulk_transition / bulk_add_tasks) with
        presummed resreq, so the per-task work is the dict moves and the
        binder call.

        `job_sums` / `node_sums` optionally carry the replay's already-
        computed resreq segment sums as {key: (task_count, vec)}; a presum is
        trusted only when its count matches the group actually applied here
        AND every task's resreq Resource is the identical object the session
        snapshot cloned (TaskInfo.clone shares resreq; a mid-cycle pod update
        replaces the TaskInfo with a fresh Resource, making the session's sum
        stale) — otherwise the group falls back to accumulation."""
        with self._lock:
            if self._session_active:
                # exclusive (no-clone) session: the replay already applied
                # job/node accounting on these very objects — only stage the
                # binder dispatch + Scheduled events.  task.pod IS the stored
                # pod here (ingest replaces the TaskInfo with the pod, and
                # deletes are deferred while the session owns the cache), so
                # the per-task store lookup is skipped.  The dispatch itself
                # runs AFTER the lock releases, like the non-exclusive path:
                # the executor's first submit spawns its worker thread, and
                # blocking on a thread start under the cache's big lock is
                # exactly what the lockdep check flags (and flagged here)
                staged = [(t, h, t.pod) for t, h in tasks_hosts]
            else:
                staged = self._bulk_bind_locked(tasks_hosts, job_sums, node_sums)
            lat_ms = self._note_bind_decisions_locked(staged)
        if lat_ms:
            from kube_batch_tpu import metrics

            metrics.observe_decision_latencies(lat_ms)
            tr = getattr(self, "tracer", None)
            if tr is not None:
                # the trace-tree twin of the histogram samples (obs/trace)
                tr.note_decision_latencies(lat_ms)
        self._dispatch_async(staged)

    def _note_bind_decisions_locked(self, staged) -> list:
        """Mark every staged dispatch in flight (update_pod's unacked-bind
        guard) and close the arrival→decision latency clocks; returns the
        ms latencies for the histogram (observed outside the lock)."""
        now = telemetry.perf_counter()
        pop_ts = self._arrival_ts.pop
        inflight = self._inflight_bind_hosts
        lat_ms = []
        for task, hostname, pod in staged:
            if pod is None:
                continue
            inflight[task._key] = hostname
            t0 = pop_ts(task._key, None)
            if t0 is not None:
                lat_ms.append((now - t0) * 1e3)
        return lat_ms

    def _settle_inflight(self, entries, bound: bool) -> None:
        """Clear in-flight bind markers once the dispatcher settled them.
        ``entries`` is [(key, pod, hostname)].  For FAILED dispatches, an
        optimistic stamp that update_pod copied onto a REPLACEMENT pod
        object is rolled back (the apiserver never bound it) and the pod is
        marked dirty so the repair rebuild re-derives it as Pending."""
        from kube_batch_tpu.api.task_info import job_id_for_pod as _jid

        now = telemetry.perf_counter()
        with self._lock:
            for key, pod, hostname in entries:
                if self._inflight_bind_hosts.get(key) == hostname:
                    del self._inflight_bind_hosts[key]
                if not bound:
                    cur = self.pods.get(key)
                    # the failed pod's original arrival clock was closed at
                    # its (failed) decision — re-arm it at settle time so
                    # the repair path's eventual re-decision produces a
                    # latency sample instead of silently undercounting
                    # exactly the slow retried binds.  Only for pods still
                    # IN the store: a pod deleted while its dispatch was in
                    # flight must not leak a never-popped entry.
                    if cur is not None:
                        self._arrival_ts.setdefault(key, now)
                    if (cur is not None and cur is not pod
                            and cur.node_name == hostname):
                        cur.node_name = None
                        self.dirty.note_pod(key)
                        self.dirty.note_job(_jid(cur))

    def _bulk_bind_locked(self, tasks_hosts, job_sums, node_sums) -> list:
        """The non-exclusive bulk_bind body: apply job/node accounting under
        the (held) cache lock and return the staged binder dispatch."""
        pods_get = self.pods.get
        staged = []
        jobs_get = self.jobs.get
        nodes_get = self.nodes.get
        by_job: Dict[str, list] = {}
        by_node: Dict[str, list] = {}
        # the allocate replay emits binds grouped by job — run-length
        # the job lookup instead of paying two dict probes per task
        prev_job_uid = None
        job = None
        jlst: list = []
        stale_jobs: set = set()
        stale_nodes: set = set()
        for task, hostname in tasks_hosts:
            key = task._key
            if task.job != prev_job_uid:
                prev_job_uid = task.job
                job = jobs_get(task.job)
                jlst = by_job.get(task.job)
                if jlst is None and job is not None:
                    jlst = by_job[task.job] = []
            own = job.tasks.get(key) if job is not None else None
            if own is not None:
                if own.resreq is not task.resreq:  # pod updated mid-cycle
                    stale_jobs.add(task.job)
                    stale_nodes.add(hostname)
                own.node_name = hostname
                jlst.append(own)
                node = nodes_get(hostname)
                if node is not None and key not in node.tasks:
                    nlst = by_node.get(hostname)
                    if nlst is None:
                        nlst = by_node[hostname] = []
                    nlst.append(own)
            staged.append((task, hostname, pods_get(key)))
        nR = self.spec.n
        for job_uid, owns in by_job.items():
            job = self.jobs[job_uid]
            # bulk_transition needs a homogeneous allocated-ness flip;
            # a rebound task may already carry an allocated status
            flip = [t for t in owns if not is_allocated(t.status)]
            noflip = [t for t in owns if is_allocated(t.status)]
            if flip:
                pre = None
                if (
                    job_sums is not None and not noflip
                    and job_uid not in stale_jobs
                ):
                    entry = job_sums.get(job_uid)
                    if entry is not None and entry[0] == len(flip):
                        pre = entry[1]
                if pre is None:
                    # tight accumulation beats np.sum-over-list at gang sizes
                    pre = np.zeros(nR)
                    for t in flip:
                        pre += t.resreq.vec
                pre_r = self.spec.wrap_vec(pre)
                job.bulk_transition(flip, TaskStatus.BINDING, pre_r,
                                    pending_sum=pre_r)
            if noflip:
                job.bulk_transition(noflip, TaskStatus.BINDING, self.spec.empty())
        for hostname, owns in by_node.items():
            node = self.nodes[hostname]
            pre = None
            if node_sums is not None and hostname not in stale_nodes:
                entry = node_sums.get(hostname)
                if entry is not None and entry[0] == len(owns):
                    pre = entry[1]
            if pre is None:
                pre = np.zeros(nR)
                for t in owns:
                    pre += t.resreq.vec
            node.bulk_add_tasks(owns, [], self.spec.wrap_vec(pre), self.spec.empty())
        return staged

    def _dispatch_async(self, staged) -> None:
        """Run the binder calls off-cycle (the async goroutine,
        cache.go:478-484); cache state was already updated under the lock."""
        bind_many = getattr(self.binder, "bind_many", None)

        def run():
            if bind_many is not None:
                # batch path: one call for the whole cycle's placements (the
                # per-pod loop competes with the scheduling thread for the
                # GIL); per-task failure isolation falls back to bind()
                pairs = [(pod, hostname) for task, hostname, pod in staged
                         if pod is not None]
                try:
                    bind_many(pairs)
                    # the binder's ack makes the binding durable in the pod
                    # store (the apiserver Binding subresource analog):
                    # resync/rebuild and stale client updates now see it
                    for pod, hostname in pairs:
                        pod.node_name = hostname
                    self._settle_inflight(
                        [(pod.key(), pod, h) for pod, h in pairs], bound=True
                    )
                    self.events.append_scheduled_batch(staged)
                    if self.resync.has_history():
                        with self._lock:
                            for pod, _h in pairs:
                                self.resync.note_success(pod.key())
                    return
                except CircuitOpenError:
                    # egress failing fast: park the WHOLE batch for resync
                    # without a per-pod call (or a per-pod log line) — the
                    # degraded cycle keeps solving, decisions wait it out
                    logger.warning(
                        "binder breaker open; parking %d binds for resync",
                        len(pairs))
                    self._settle_inflight(
                        [(pod.key(), pod, h) for pod, h in pairs], bound=False
                    )
                    for task, hostname, pod in staged:
                        if pod is not None:
                            self.resync_task(task, reason="breaker-open")
                    return
                except Exception:  # noqa: BLE001 — retry per-task below
                    logger.exception("bind_many failed; retrying per task")
            breaker_parked = 0
            acked, failed = [], []
            for task, hostname, pod in staged:
                try:
                    if pod is not None:
                        self.binder.bind(pod, hostname)
                        pod.node_name = hostname  # binding ack (see above)
                        acked.append((task._key, pod, hostname))
                        self.events.append(("Scheduled", task._key, hostname))
                        if self.resync.has_history():
                            with self._lock:
                                self.resync.note_success(task._key)
                except CircuitOpenError:
                    breaker_parked += 1
                    failed.append((task._key, pod, hostname))
                    self.resync_task(task, reason="breaker-open")
                except Exception as e:  # noqa: BLE001 — resyncTask repair path
                    logger.error("bind of %s to %s failed: %s", task._key, hostname, e)
                    failed.append((task._key, pod, hostname))
                    self.resync_task(task)
            if acked:
                self._settle_inflight(acked, bound=True)
            if failed:
                self._settle_inflight(failed, bound=False)
            if breaker_parked:
                logger.warning("binder breaker open; parked %d binds for "
                               "resync", breaker_parked)

        from concurrent.futures import ThreadPoolExecutor

        if self._dispatch_pool is None:
            self._dispatch_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="kb-dispatch"
            )
        # submit OUTSIDE the mutex: the pool's first submit spawns its
        # worker thread, and Thread.start blocks on the thread's started
        # event — a blocking call no lock may be held across (lockdep)
        fut = self._dispatch_pool.submit(run)
        with self._dispatch_mu:
            # leaf mutex: the pipelined loop's writeback worker drains binds
            # (flush_binds) concurrently with the cycle thread staging the
            # NEXT cycle's dispatch — an unguarded prune/rebind here could
            # drop a freshly appended future from tracking
            self._dispatch_futures = [
                f for f in self._dispatch_futures if not f.done()
            ]
            self._dispatch_futures.append(fut)

    def flush_binds(self, timeout: Optional[float] = None) -> None:
        """Wait for every in-flight async binder call — tests and the bench
        use this to observe a deterministic post-cycle state."""
        with self._dispatch_mu:
            pending = list(self._dispatch_futures)
        for f in pending:
            f.result(timeout=timeout)
        with self._dispatch_mu:
            self._dispatch_futures = [
                f for f in self._dispatch_futures if not f.done()
            ]

    def evict(self, task: TaskInfo, reason: str) -> None:
        """(cache.go:404-444)"""
        with self._lock:
            if not self._session_active:
                own = self._own_task(task)
                if own is not None:
                    job = self.jobs[task.job]
                    job.update_task_status(own, TaskStatus.RELEASING)
                    node = self.nodes.get(own.node_name) if own.node_name else None
                    if node is not None:
                        node.update_task(own)
            # exclusive session: the Statement already moved this very task
            # to Releasing and re-accounted its node; re-applying here would
            # double-charge (the session may since have pipelined a
            # preemptor onto the freed Releasing budget)
            pod = self.pods.get(task.key())
        try:
            if pod is not None:
                self.evictor.evict(pod)
                self.events.append(("Evict", task.key(), reason))
        except CircuitOpenError:
            logger.warning("evict of %s parked: egress breaker open",
                           task.key())
            self.resync_task(task, reason="breaker-open")
        except Exception as e:  # noqa: BLE001
            logger.error("evict of %s failed: %s", task.key(), e)
            self.resync_task(task)

    # volume seams (cache.go:189-209; real ledger in cache/volume.py,
    # no-op fake by default)
    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        self.volume_binder.allocate_volumes(task, hostname)
        task.volume_ready = True

    def bind_volumes(self, task: TaskInfo) -> None:
        self.volume_binder.bind_volumes(task)

    def volume_feasible(self, task: TaskInfo, hostname: str) -> bool:
        probe = getattr(self.volume_binder, "volume_feasible", None)
        return probe(task, hostname) if probe is not None else True

    # ------------------------------------------------------------------
    # repair: resync (cache.go:559-581, event_handlers.go:96-122)
    # ------------------------------------------------------------------
    @property
    def err_tasks(self) -> List[TaskInfo]:
        """The pending repair backlog (read-only view; the queue itself
        lives at ``self.resync``). Kept for the seed's observers/tests."""
        with self._lock:
            return self.resync.pending_tasks()

    def resync_task(self, task: TaskInfo, reason: str = "error") -> None:
        """Park a failed bind/evict decision for repair (cache.go:447-487).
        ``reason="breaker-open"`` marks a decision the egress breaker
        refused locally — it backs off but never counts toward the poison
        budget (the server never saw it)."""
        from kube_batch_tpu import metrics

        with self._lock:
            counted = self.resync.park(task, reason)
            depth, quarantined = len(self.resync), len(self.resync.quarantined)
        if counted:  # a quarantined key's park is a no-op — don't count it
            metrics.register_resync_parked(reason)
        metrics.set_resync_depth(depth, quarantined)

    def _resync_one_locked(self, task: TaskInfo) -> None:
        """Re-sync one errored task from the pod store: gone → forget;
        present → rebuild (delete + add)."""
        pod = self.pods.get(task.key())
        if pod is None:
            self.resync.forget(task.key())
            return
        self._delete_pod_locked(pod, forget_resync=False)
        self.pods[pod.key()] = pod
        self._add_task(TaskInfo(pod, self.spec), pod)

    def process_resync_tasks(self) -> None:
        """One repair pass over the backoff queue: due tasks rebuild from
        the pod store (and re-place next cycle); tasks that exhausted their
        poison budget are shelved with a PodScheduled condition instead of
        retrying forever."""
        from kube_batch_tpu import metrics

        poisoned: List[TaskInfo] = []
        with self._lock:
            if self._session_active:
                return  # a cycle owns the cache; retry next repair tick
            self.resync.apply(self._resync_one_locked, poisoned.append)
            depth, quarantined = len(self.resync), len(self.resync.quarantined)
        for task in poisoned:
            logger.error(
                "task %s failed %d bind/evict repairs; quarantined until an "
                "external change to its pod", task.key(),
                self.resync.poison_after,
            )
            self.task_unschedulable(
                task,
                f"bind/evict failed {self.resync.poison_after} times; "
                "quarantined pending an external pod change",
            )
        metrics.set_resync_depth(depth, quarantined)

    def rebuild_from_pod_store(self) -> None:
        """Re-list recovery (the informer re-list + WaitForCacheSync analog,
        cache.go:342-384): rebuild every job's and node's task state from the
        authoritative pod store. The scheduler loop invokes this after a
        cycle dies mid-mutation in exclusive-session mode, where the session
        objects ARE the cache and a half-applied replay would otherwise leak
        phantom allocations. Completed bindings survive the rebuild because
        every binder ack writes pod.node_name (the Binding subresource
        analog); in-flight unacked binds rebuild as Pending and re-place
        next cycle."""
        with self._lock:
            # everything below mutates task/job state wholesale — the next
            # open must not trust any cross-cycle delta state
            self.dirty.mark_full()
            self.open_cache.invalidate()
            spec = self.spec
            for job in self.jobs.values():
                for task in job.tasks.values():
                    self.columns.free_task(task)
                job.tasks.clear()
                job.task_status_index.clear()
                # in-place zeroing: the ledgers may be live column views
                # (api/columns.py) — rebinding would orphan them
                job.allocated.vec[:] = 0.0
                job.total_request.vec[:] = 0.0
                job.pending_request.vec[:] = 0.0
                job._note_alloc()
                if job._cols is not None:
                    job._cols.j_counts[job._row] = 0
                    job._cols.j_touched[job._row] = True
                job.nodes_fit_delta = {}
                job.nodes_fit_errors = {}
            for node in self.nodes.values():
                node.tasks.clear()
                node._acct.clear()
                if node._cols is not None:
                    node._cols.note_node_ledger(node._row)
                node.idle.vec[:] = node.allocatable.vec
                node.used.vec[:] = 0.0
                node.releasing.vec[:] = 0.0
                node._set_state()
                if node._cols is not None:
                    node._cols.sync_node_meta(node)
            for pod in list(self.pods.values()):
                if not self._owns(pod):
                    continue
                self._resolve_pod_priority(pod)
                self._add_task(TaskInfo(pod, spec), pod)
            for job in list(self.jobs.values()):
                self._maybe_collect_job(job)
        logger.warning("cache rebuilt from the pod store (%d pods, %d jobs)",
                       # kbt: allow[KBT301] log-only sizes — stale is fine
                       len(self.pods), len(self.jobs))

    def failover_recover(self) -> Dict:
        """Warm-standby takeover (leader failover): rebuild the host model
        from the pod store (the re-list a fresh leader performs anyway),
        then revalidate the surviving per-cycle device caches
        (columns.revalidate_resident — version token + check_consistency).
        On success the compiled executables and resident buffers are KEPT:
        the next cycle's mirror diffs absorb any divergence as ordinary
        scatter deltas, so failover pays no recompile/re-upload. Only a
        failed revalidation cold-starts the residency.

        Also flushes the repair queue's quarantine: the new leader's
        rebuilt state supersedes the old leader's failure history."""
        from kube_batch_tpu import metrics

        self.rebuild_from_pod_store()
        with self._lock:
            report = self.columns.revalidate_resident(self)
            # the rebuild re-derived every task from the store — stale
            # failure history must not shelve tasks the new leader never
            # saw fail
            self.resync.reset_history()
        metrics.register_leader_failover(report["mode"])
        logger.warning(
            "leader failover recovery: %s (resident tokens %s%s)",
            report["mode"], report["resident_tokens"],
            f"; errors: {report['errors']}" if report["errors"] else "",
        )
        return report

    def process_cleanup_jobs(self) -> None:
        """processCleanupJob analog (cache.go:533-557): sweep-collect jobs
        that are terminated per JobTerminated (helpers.go:102-106 — no real
        PodGroup AND no tasks). Tasks always leave through delete_pod, which
        also clears the pod store and node task copies; this sweep is the
        belt-and-braces pass for jobs that lost their last task on a code
        path that didn't call _maybe_collect_job."""
        with self._lock:
            if self._session_active:
                return  # a cycle owns the cache; retry next repair tick
            for job in list(self.jobs.values()):
                self._maybe_collect_job(job)

    # ------------------------------------------------------------------
    # status egress (cache.go:688-736)
    # ------------------------------------------------------------------
    def task_unschedulable(self, task: TaskInfo, message: str) -> None:
        """PodScheduled=False condition + FailedScheduling event for one task
        (cache.go:500-525), deduplicated like podConditionHaveUpdate
        (cache.go:151-173)."""
        self._task_unschedulable_key(task.key(), message)

    def _task_unschedulable_key(self, key: str, message: str,
                                require_pod: bool = False) -> None:
        """task_unschedulable by pod key.  ``require_pod=True`` (the
        pipelined writeback stage) skips the dedup record when the pod has
        since left the store — a staged condition must not plant a stale
        dedup entry that would suppress a recreated pod's first write."""
        cond = {
            "type": "PodScheduled",
            "status": "False",
            "reason": "Unschedulable",
            "message": message,
        }
        with self._lock:
            if self.pod_conditions.get(key) == cond:
                return  # no-op update suppressed
            pod = self.pods.get(key)
            if pod is not None or not require_pod:
                self.pod_conditions[key] = cond
        if pod is not None:
            self.status_updater.update_pod_condition(pod, cond)
        self.events.append(("FailedScheduling", key, message))

    def record_job_status_event(self, job: JobInfo) -> None:
        """Unschedulable event (gated like RecordJobStatusEvent,
        cache.go:688-702: non-shadow PodGroup in Pending/Unknown phase, or a
        PDB job with Pending tasks) + fit-error conditions for Allocated and
        Pending tasks (cache.go:704-719). Called once per job at session
        close via update_job_status / the PDB events-only path."""
        self._apply_status_ops(self._render_job_status_ops(job))

    def _render_job_status_ops(self, job: JobInfo) -> list:
        """record_job_status_event's effects as VALUE-snapshotted ops
        (("event", tuple) / ("cond", key, message)) — the pipelined close
        renders them while the session's fit diagnostics are still live and
        hands the list across the stage boundary; applying them later reads
        no session state.  record_job_status_event == render + apply, so
        serial and staged closes share one rendering."""
        pg = job.pod_group
        shadow = pg is not None and pg.shadow
        pg_unsched = (
            pg is not None
            and not shadow
            and pg.phase in (PodGroupPhase.PENDING, PodGroupPhase.UNKNOWN)
        )
        pdb_unsched = job.pdb is not None and bool(
            job.task_status_index.get(TaskStatus.PENDING)
        )
        has_stuck = job.task_status_index.get(TaskStatus.ALLOCATED) or \
            job.task_status_index.get(TaskStatus.PENDING)
        if not (pg_unsched or pdb_unsched or has_stuck):
            return []  # nothing to report — skip the fit-error rendering
        base = job.job_fit_errors or job.fit_error()
        ops = []
        if pg_unsched or pdb_unsched:
            ops.append(("event", ("Unschedulable", job.uid, base)))
        for status in (TaskStatus.ALLOCATED, TaskStatus.PENDING):
            for task in job.task_status_index.get(status, {}).values():
                fe = job.nodes_fit_errors.get(task.uid)
                ops.append(("cond", task.key(),
                            fe.error() if fe is not None else base))
        return ops

    def _apply_status_ops(self, ops, staged: bool = False) -> None:
        for op in ops:
            if op[0] == "event":
                self.events.append(op[1])
            else:
                self._task_unschedulable_key(op[1], op[2], require_pod=staged)

    def update_job_status(self, job: JobInfo, prev_status=None) -> None:
        """Write the session's derived PodGroup status back to the
        authoritative store (UpdatePodGroup, cache.go:722-736).

        Condition-only updates (phase and counts unchanged) are rate-limited
        to one write per minute plus jitter, like the jobUpdater
        (job_updater.go:20-31,55-100) — conditions churn every cycle for a
        stuck job, and the write stream must not."""
        import random
        import time as _time

        pg = job.pod_group
        if pg is None:
            return
        write = True
        with self._lock:
            own = self.jobs.get(job.uid)
            if own is None:
                return  # job deleted mid-cycle — nothing to write status for
            own_pg = own.pod_group if own is not None else None
            if prev_status is not None:
                # exclusive session: own_pg IS pg (mutated in place), so the
                # change detection compares against the status saved at open
                # (session.go:102-105 podGroupStatus)
                condition_only = prev_status == (
                    pg.phase, pg.running, pg.failed, pg.succeeded
                )
            else:
                condition_only = (
                    own_pg is not None
                    and own_pg.phase == pg.phase
                    and (own_pg.running, own_pg.failed, own_pg.succeeded)
                    == (pg.running, pg.failed, pg.succeeded)
                )
            # kbt: allow[KBT001] status-write rate-limit cadence is wall-clock
            # by design (job_updater.go:20-31); scheduling decisions never read it
            now = _time.monotonic()
            if condition_only and now < self._status_next_write.get(job.uid, 0.0):
                write = False  # rate-limited; session state already updated
            if write:
                self._status_next_write[job.uid] = now + 60.0 + random.uniform(0, 30.0)
                if own_pg is not None:
                    own_pg.phase = pg.phase
                    own_pg.conditions = list(pg.conditions)
                    own_pg.running = pg.running
                    own_pg.failed = pg.failed
                    own_pg.succeeded = pg.succeeded
                # the authoritative PodGroup changed: the next delta open
                # must re-read this job's status/schedulability
                self.dirty.note_job(job.uid)
        if write:
            if self._status_degraded():
                from kube_batch_tpu import metrics

                metrics.register_status_writes_shed(1)
            else:
                self.status_updater.update_pod_group(pg)
        # events accompany every status pass, rate-limited or not, once per
        # job per close (UpdateJobStatus → RecordJobStatusEvent,
        # cache.go:722-736); task_unschedulable dedups the conditions
        self.record_job_status_event(job)

    def update_job_statuses_bulk(self, updates) -> None:
        """The exclusive close's status pass: update_job_status semantics for
        a pre-filtered batch under one lock.  `updates` is
        [(job, changed, need_record)]; exclusive sessions mutate the
        authoritative PodGroup in place, so the own_pg copy-back of the
        per-job path is a no-op here and only the rate-limit bookkeeping,
        the updater call, and event recording remain.

        Implemented as stage + run back-to-back: the pipelined close runs
        the same two halves with a stage boundary between them, so serial
        and overlapped writeback are one code path by construction."""
        self.run_status_flush(self.stage_status_flush(updates))

    def stage_status_flush(self, updates, qcounts=None) -> "StatusFlush":
        """The synchronous half of the close-time status pass — the
        double-buffer handoff for the pipelined cycle.  EVERYTHING the next
        session open depends on happens here, before the cycle ends: the
        dirty stamps for changed jobs (the delta open re-reads exactly
        them), the rate-limit window bookkeeping, the queue-status delta
        decisions, and the degraded verdict.  What crosses the stage
        boundary is value-snapshotted: PodGroup status CLONES (the live
        object mutates again next cycle; the reference's jobUpdater writes
        an informer copy the same way), pre-rendered event/condition ops,
        and the decided queue writes — run_status_flush reads no session
        or live-job state.

        The rate-limit jitter (60s + U[0,30), job_updater.go:20-31) is
        drawn as one numpy batch."""
        import time as _time

        to_write = []
        ops: List = []
        with self._lock:
            # kbt: allow[KBT001] same wall-clock rate-limit cadence as
            # update_job_status above — write-stream pacing, not scenario time
            now = _time.monotonic()
            next_write = self._status_next_write
            jitter = np.random.uniform(60.0, 90.0, size=len(updates)).tolist()
            note_job = self.dirty.note_job
            for i, (job, changed, need_record) in enumerate(updates):
                pg = job.pod_group
                if pg is None or self.jobs.get(job.uid) is None:
                    continue  # deleted mid-cycle: no write, no events
                if changed:
                    # phase/counts moved this cycle (exclusive close mutates
                    # the authoritative PodGroup in place) — the next delta
                    # open re-reads exactly these jobs' open-state
                    note_job(job.uid)
                if need_record:
                    ops.extend(self._render_job_status_ops(job))
                if not changed and now < next_write.get(job.uid, 0.0):
                    continue  # condition-only churn, rate-limited
                next_write[job.uid] = now + jitter[i]
                to_write.append(pg.clone())
            qwrites, shed_queues = self._stage_queue_statuses_locked(qcounts)
        return StatusFlush(to_write, ops, qwrites, shed_queues,
                           self._status_degraded())

    def run_status_flush(self, flush: "StatusFlush") -> None:
        """The egress half: pod-group writes, rendered events/conditions,
        then the queue-status writes — the serial close's order.  Runs on
        the cycle thread (serial) or the pipeline's writeback worker
        (overlapped); either way it touches only the flush's snapshots plus
        the updater/event seams.

        Degraded cycles (soft budget elapsed / writeback breaker open at
        stage time) shed the flush — async pool for parallel-safe updaters,
        skip otherwise.  Status writes are re-derived every close, so the
        next healthy cycle converges; what matters now is that the
        scheduling loop keeps ticking instead of stalling in egress."""
        updater = self.status_updater
        to_write = flush.to_write
        parallel_safe = getattr(updater, "parallel_safe", False)
        if to_write and flush.degraded:
            from kube_batch_tpu import metrics

            metrics.register_status_writes_shed(len(to_write))
            logger.warning("degraded cycle: shedding %d status writes%s",
                           len(to_write),
                           " to the async pool" if parallel_safe else "")
            if parallel_safe:
                self._update_pod_groups_pooled(to_write, wait=False)
        elif len(to_write) > 16 and parallel_safe:
            try:
                self._update_pod_groups_pooled(to_write)
            except Exception:  # noqa: BLE001 — re-derived next close
                logger.exception("pooled podgroup status writes failed")
        else:
            for pg in to_write:
                # per-write guard: one failing updater call must not abort
                # the remaining writes, the rendered event/condition ops, or
                # the queue writes below — the stage already recorded those
                # queue deltas as written, so skipping them here would
                # suppress the external QueueStatus until the counts change
                try:
                    updater.update_pod_group(pg)
                except Exception:  # noqa: BLE001 — re-derived next close
                    logger.exception("podgroup status write failed")
        self._apply_status_ops(flush.ops, staged=True)
        if flush.shed_queues:
            from kube_batch_tpu import metrics

            metrics.register_status_writes_shed(flush.shed_queues)
        write = getattr(updater, "update_queue_status", None)
        for name, c in flush.qwrites:
            try:
                write(name, c)
            except Exception as e:  # noqa: BLE001 — next close re-derives
                logger.error("queue status write %s failed: %s", name, e)
                with self._lock:
                    # un-record so the next close retries the delta
                    # kbt: allow[KBT002] dict .get on the delta-record map
                    # (the "queue" in its name is QueueStatus, not a Queue)
                    if self._queue_status_written.get(name) == c:
                        del self._queue_status_written[name]

    def _stage_queue_statuses_locked(self, counts) -> tuple:
        """Decide the per-queue status deltas (caller holds the lock):
        returns ([(name, counts)], shed_count).  Bookkeeping is recorded
        optimistically at stage time so the NEXT cycle's delta decisions
        never race the flush; a failed write un-records (run_status_flush)."""
        if counts is None:
            return [], 0
        write = getattr(self.status_updater, "update_queue_status", None)
        if write is None:
            return [], 0
        if self._status_degraded():
            # deltas-only writeback: an unwritten count stays "dirty" in
            # _queue_status_written and lands on the next healthy close
            return [], len(counts)
        # queues previously written but absent from this cycle's counts
        # (their podgroups all left) zero out rather than going stale
        zero = queue_phase_counts()
        names = set(counts) | set(self._queue_status_written)
        qwrites = []
        for name in names:
            if self.queues.get(name) is None:
                continue  # deleted mid-cycle
            c = counts.get(name, zero)
            if self._queue_status_written.get(name) == c:
                continue
            self._queue_status_written[name] = dict(c)
            qwrites.append((name, dict(c)))
        return qwrites, 0

    def update_queue_statuses(self, counts: Dict[str, dict]) -> None:
        """Write changed per-queue podgroup-phase counts (QueueStatus,
        types.go:195-204) through the StatusUpdater seam. BEYOND the
        reference — it declares the fields but never fills them; here the
        close pass hands the counts it already derived and only deltas are
        written. Updaters without the seam (older fakes) are skipped."""
        with self._lock:
            qwrites, shed = self._stage_queue_statuses_locked(counts)
        self.run_status_flush(StatusFlush([], [], qwrites, shed, False))

    def _status_degraded(self) -> bool:
        """Should close-time status flushes shed? True while the scheduler
        flagged a blown cycle budget, or while the updater reports its
        writeback path failing fast (K8sBackend.degraded → breaker open)."""
        if self.shed_status_writes:
            return True
        probe = getattr(self.status_updater, "degraded", None)
        return bool(probe()) if probe is not None else False

    def _update_pod_groups_pooled(self, pgs, wait: bool = True) -> None:
        """16-worker status writeback (the jobUpdater's ParallelizeUntil,
        job_updater.go:18,51-53). Per-object failures log and continue —
        the next cycle re-derives and re-writes (convergence by re-running,
        the reference ignores UpdatePodGroup errors the same way).
        ``wait=False`` is the degraded cycle's shed: the writes drain on
        the pool behind the ticking loop (stop() still reaps them)."""
        from concurrent.futures import ThreadPoolExecutor

        if self._status_pool is None:
            self._status_pool = ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="kb-status"
            )
        update = self.status_updater.update_pod_group

        def write(pg):
            try:
                update(pg)
            except Exception as e:  # noqa: BLE001
                logger.error("podgroup status write %s/%s failed: %s",
                             pg.namespace, pg.name, e)

        if wait:
            list(self._status_pool.map(write, pgs))
        else:
            for pg in pgs:
                self._status_pool.submit(write, pg)

    # ------------------------------------------------------------------
    # snapshot (cache.go:584-654)
    # ------------------------------------------------------------------
    def _job_in_session(self, uid: str, job: JobInfo) -> bool:
        """Membership filter shared by snapshot() and session_view(): jobs
        enter a session with a PodGroup or a PDB (cache.go:625-633) and a
        known queue."""
        if job.pod_group is None and job.pdb is None:
            return False
        if job.queue not in self.queues:
            logger.warning("job %s queue %s not found, skipped", uid, job.queue)
            return False
        return True

    def _resolve_job_priority(self, job: JobInfo) -> int:
        """PriorityClass resolution (cache.go:610-620): named class if it
        exists, else the global default — recomputed every session so a
        deleted class stops conferring its value."""
        pc = self.priority_classes.get(
            job.pod_group.priority_class
        ) if job.pod_group and job.pod_group.priority_class else None
        if pc is not None:
            return pc.value
        return self.default_priority

    def snapshot(self) -> ClusterInfo:
        """Deep-clone ready nodes, all queues, and every job that has a
        PodGroup and whose queue exists."""
        with self._lock:
            ci = ClusterInfo(self.spec)
            for name, node in self.nodes.items():
                if node.ready:
                    ci.nodes[name] = node.clone()
            for name, q in self.queues.items():
                ci.queues[name] = q.clone()
            for uid, job in self.jobs.items():
                if not self._job_in_session(uid, job):
                    continue
                clone = job.clone()
                clone.priority = self._resolve_job_priority(job)
                ci.jobs[uid] = clone
            return ci

    def take_dirty(self):
        """Consume the accumulated ingest churn (one exclusive open's input).
        Taken under the lock so it races nothing; during the session the
        ingest gate defers mutations, so no marks land mid-cycle except the
        cache's own status writebacks at close."""
        with self._lock:
            delta = self.dirty.take()
            self.last_open_version = delta.version
            return delta

    def session_view_delta(self, delta) -> ClusterInfo:
        """session_view() by delta: refresh only the dirty jobs in the
        persistent open cache (cache/dirty.py), then hand the session
        shallow copies.  End state is bit-exact with session_view() — the
        same membership filter and priority resolution run, just only for
        jobs whose inputs could have moved since the last open."""
        oc = self.open_cache
        with self._lock:
            ci = ClusterInfo(self.spec)
            ci.nodes = {
                name: n for name, n in self.nodes.items() if n.ready
            }
            ci.queues = dict(self.queues)
            jobs = oc.jobs
            pg_status = oc.pg_status
            queues = self.queues
            pcs_get = self.priority_classes.get
            default_prio = self.default_priority
            for uid in delta.jobs:
                job = self.jobs.get(uid)
                member = (
                    job is not None
                    and (job.pod_group is not None or job.pdb is not None)
                )
                if member and job.queue not in queues:
                    logger.warning(
                        "job %s queue %s not found, skipped", uid, job.queue
                    )
                    member = False
                if not member:
                    jobs.pop(uid, None)
                    pg_status.pop(uid, None)
                    continue
                pg = job.pod_group
                pc = (
                    pcs_get(pg.priority_class)
                    if pg is not None and pg.priority_class else None
                )
                job.priority = pc.value if pc is not None else default_prio
                jobs[uid] = job
                if pg is not None:
                    pg_status[uid] = (pg.phase, pg.running, pg.failed,
                                      pg.succeeded)
                else:
                    pg_status.pop(uid, None)
            ci.jobs = dict(jobs)
            return ci

    def rebuild_open_cache(self, cluster: ClusterInfo, pg_status) -> None:
        """Reseed the cross-cycle open cache after a FULL session open —
        `cluster.jobs`/`pg_status` are the freshly derived structures the
        session was just handed."""
        oc = self.open_cache
        oc.jobs = dict(cluster.jobs)
        oc.pg_status = dict(pg_status)
        oc.gate_dropped_rows = set()
        oc.valid = True
        # the full open cleared every session job's fit diagnostics
        self.fit_state_jobs.clear()

    def session_view(self) -> ClusterInfo:
        """The exclusive (no-clone) session's ClusterInfo: the same
        membership filters as snapshot(), as shallow views over the live
        objects — caller must hold the exclusive-session gate.  The
        membership/priority checks are inlined (vs the shared helpers the
        cold snapshot() uses): this loop runs over every job every cycle."""
        with self._lock:
            ci = ClusterInfo(self.spec)
            ci.nodes = {
                name: n for name, n in self.nodes.items() if n.ready
            }
            ci.queues = dict(self.queues)
            jobs = {}
            queues = self.queues
            pcs_get = self.priority_classes.get
            default_prio = self.default_priority
            for uid, job in self.jobs.items():
                pg = job.pod_group
                if pg is None and job.pdb is None:
                    continue
                if job.queue not in queues:
                    logger.warning(
                        "job %s queue %s not found, skipped", uid, job.queue
                    )
                    continue
                pc = pcs_get(pg.priority_class) if pg is not None and pg.priority_class else None
                job.priority = pc.value if pc is not None else default_prio
                jobs[uid] = job
            ci.jobs = jobs
            return ci
