"""Cache side-effect seams (cache/interface.go:27-78).

These are the process boundary: everything above them is in-memory scheduling
state; implementations talk to whatever actually runs pods (a k8s apiserver
adapter, the synthetic cluster backend, or test fakes)."""

from __future__ import annotations

from typing import Protocol

from kube_batch_tpu.api.pod import Pod


class Binder(Protocol):
    def bind(self, pod: Pod, hostname: str) -> None:
        """Place the pod; raise to signal failure (→ resync)."""


class Evictor(Protocol):
    def evict(self, pod: Pod) -> None:
        """Delete/evict the pod; raise to signal failure (→ resync)."""


class StatusUpdater(Protocol):
    def update_pod_condition(self, pod: Pod, condition: dict) -> None: ...

    def update_pod_group(self, pod_group) -> None: ...


class VolumeBinder(Protocol):
    def allocate_volumes(self, task, hostname: str) -> None: ...

    def bind_volumes(self, task) -> None: ...
