"""Cache side-effect seams (cache/interface.go:27-78).

These are the process boundary: everything above them is in-memory scheduling
state; implementations talk to whatever actually runs pods (a k8s apiserver
adapter, the synthetic cluster backend, or test fakes)."""

from __future__ import annotations

from typing import Protocol

from kube_batch_tpu.api.pod import Pod


class Binder(Protocol):
    """A binder MAY additionally expose `bind_many(pairs)` — a batch fast
    path the dispatcher prefers when present (duck-typed, deliberately NOT
    declared here: a Protocol stub body would be inherited as a silent no-op
    by explicit subclasses).  bind_many's contract is ALL-OR-NOTHING:
    raising must mean no pod in the batch was durably bound, because the
    dispatcher retries the whole batch per-pod after a bind_many exception —
    a partially-successful bind_many would get its successful prefix
    re-bound (duplicate bind calls + duplicate Scheduled events).  A binder
    that cannot give that guarantee should expose per-pod idempotent bind()
    only."""

    def bind(self, pod: Pod, hostname: str) -> None:
        """Place the pod; raise to signal failure (→ resync)."""


class Evictor(Protocol):
    def evict(self, pod: Pod) -> None:
        """Delete/evict the pod; raise to signal failure (→ resync)."""


class StatusUpdater(Protocol):
    def update_pod_condition(self, pod: Pod, condition: dict) -> None: ...

    def update_pod_group(self, pod_group) -> None: ...


class VolumeBinder(Protocol):
    """Scheduling-side volume seam (AllocateVolumes/BindVolumes) plus the
    ingest surface the k8s watch feeds (pv/pvc/storageclass informer
    analogs).  Structural: implementations do NOT subclass this, so the
    declarations here are the contract, not inherited behavior.  A binder
    that cannot ingest a kind (the standalone ledger has no PVC objects)
    simply lacks the method — the translate layer's dispatcher logs the
    drop loudly instead of failing open (KBT008)."""

    def allocate_volumes(self, task, hostname: str) -> None: ...

    def bind_volumes(self, task) -> None: ...

    # -- ingest (fed by k8s/translate.apply_event) ----------------------
    def add_pv(self, pv) -> None: ...

    def delete_pv(self, name: str) -> None: ...

    def add_pvc(self, pvc) -> None: ...

    def delete_pvc(self, key: str) -> None: ...

    def add_storage_class(self, name: str, provisioner: str) -> None: ...

    def delete_storage_class(self, name: str) -> None: ...
