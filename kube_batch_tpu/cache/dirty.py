"""Cross-cycle dirty tracking for the SchedulerCache.

The reference kube-batch never rebuilds its cache from scratch — informers
mutate it incrementally and only the once-per-second Snapshot() pays a full
walk (SURVEY §cache, event_handlers.go).  Firmament (OSDI '16) showed the
same lesson at the solver layer: incremental re-optimization, not faster
from-scratch solves, is what holds sub-second placement at 10k+ nodes.  This
module gives the cache the bookkeeping both layers need to go incremental:

- ``DirtyTracker``: a monotonic ingest version plus per-kind dirty sets
  (job uids, node names, pod keys) and coarse invalidation flags (queue
  row-space changed, priority-class universe changed, full rebuild forced).
  Every ingest handler stamps it; ``take()`` hands the accumulated delta to
  the next exclusive session open and resets the accumulators.

- ``OpenCache``: the previous cycle's session-open state, kept alive across
  cycles so a low-churn open can hand the session a *delta* instead of
  re-deriving every per-job structure: the membership-filtered jobs dict
  (priorities resolved), the PodGroup statuses as they stood at open, the
  job-row arrays the vectorized gang gate reads, and the rows the gate
  dropped last cycle (restored before this cycle's gate re-votes).

The contract is bit-exact equivalence: the delta-opened session and the
delta-built device snapshot must be indistinguishable from a full rebuild
(tests/test_snapshot_delta.py churns both paths against each other), and the
full rebuild remains the always-correct fallback for high churn, row-space
changes, or a cold cache.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, NamedTuple, Set


class DirtyDelta(NamedTuple):
    """The immutable churn record one exclusive open consumes."""

    version: int
    jobs: FrozenSet[str]
    nodes: FrozenSet[str]
    pods: FrozenSet[str]
    queues_changed: bool
    priority_classes_changed: bool
    full: bool

    def churn_fraction(self, n_jobs: int) -> float:
        """Dirty-job fraction against the previous cycle's session size."""
        if self.full or self.queues_changed or self.priority_classes_changed:
            return 1.0
        return len(self.jobs) / max(n_jobs, 1)


_EMPTY: FrozenSet[str] = frozenset()


class DirtyTracker:
    """Accumulates ingest churn between session opens.  All mutation entry
    points run under the cache's big lock, so plain sets suffice.

    ``on_advance`` (optional, set by the cache) is invoked on every version
    bump — the event-driven cycle trigger's wake signal: an arrival burst
    schedules a cycle immediately instead of waiting out the tick.  It must
    never block (the stamps run under the cache's big lock).

    ``hold_version()``/``release_version()`` bracket a batched ingest: the
    per-kind dirty sets still accumulate per item, but the monotonic version
    advances ONCE for the whole batch (one lease/delta token, one trigger
    wake) instead of once per item."""

    __slots__ = ("version", "jobs", "nodes", "pods", "queues_changed",
                 "priority_classes_changed", "full", "on_advance", "_held",
                 "_held_pending")

    def __init__(self) -> None:
        self.version = 0
        self.jobs: Set[str] = set()
        self.nodes: Set[str] = set()
        self.pods: Set[str] = set()
        self.queues_changed = False
        self.priority_classes_changed = False
        # a cold tracker reads as "everything changed": the first open after
        # construction (or after a forced invalidation) must rebuild fully
        self.full = True
        self.on_advance = None
        self._held = False
        self._held_pending = False

    def _advance(self) -> None:
        if self._held:
            self._held_pending = True
            return
        self.version += 1
        if self.on_advance is not None:
            self.on_advance()

    def hold_version(self) -> None:
        self._held = True
        self._held_pending = False

    def release_version(self) -> None:
        self._held = False
        if self._held_pending:
            self._held_pending = False
            self._advance()

    # -- stamps (called from the cache's ingest/status choke points) -------
    def note_job(self, uid: str) -> None:
        self.jobs.add(uid)
        self._advance()

    def note_node(self, name: str) -> None:
        self.nodes.add(name)
        self._advance()

    def note_pod(self, key: str) -> None:
        self.pods.add(key)
        self._advance()

    def mark_queues(self) -> None:
        self.queues_changed = True
        self._advance()

    def mark_priority_classes(self) -> None:
        self.priority_classes_changed = True
        self._advance()

    def mark_full(self) -> None:
        self.full = True
        self._advance()

    # -- consumption -------------------------------------------------------
    def take(self) -> DirtyDelta:
        """Snapshot-and-reset: the caller owns the returned delta; new churn
        accumulates toward the next open."""
        delta = DirtyDelta(
            version=self.version,
            jobs=frozenset(self.jobs) if self.jobs else _EMPTY,
            nodes=frozenset(self.nodes) if self.nodes else _EMPTY,
            pods=frozenset(self.pods) if self.pods else _EMPTY,
            queues_changed=self.queues_changed,
            priority_classes_changed=self.priority_classes_changed,
            full=self.full,
        )
        self.jobs.clear()
        self.nodes.clear()
        self.pods.clear()
        self.queues_changed = False
        self.priority_classes_changed = False
        self.full = False
        return delta


class OpenCache:
    """The previous cycle's session-open state (see module docstring).

    ``jobs`` holds the membership-passed LIVE JobInfo objects with their
    priorities resolved — each open hands the session a shallow dict copy so
    gate drops (``Session.drop_job``) never mutate the master.  ``pg_status``
    mirrors ``Session.pod_group_status_at_open``; the cache's status-write
    methods keep it current by marking changed jobs dirty, and the delta
    open re-reads exactly the dirty uids."""

    __slots__ = ("valid", "jobs", "pg_status", "gate_dropped_rows")

    def __init__(self) -> None:
        self.valid = False
        self.jobs: Dict[str, object] = {}
        self.pg_status: Dict[str, tuple] = {}
        # rows the gang gate cleared from j_sess last cycle — restored
        # before this cycle's gate re-votes (a job that regained validity
        # must re-enter the device snapshot)
        self.gate_dropped_rows: Set[int] = set()

    def invalidate(self) -> None:
        self.valid = False
        self.jobs = {}
        self.pg_status = {}
        self.gate_dropped_rows = set()
