"""Test fakes (pkg/scheduler/util/test_utils.go:94-163): record effects into
maps and signal a channel-like event so tests can await async binds."""

from __future__ import annotations

import threading
from typing import Dict, List

from kube_batch_tpu.api.pod import Pod


class FakeBinder:
    def __init__(self):
        self.binds: Dict[str, str] = {}  # "ns/name" → node
        self.channel: List[str] = []
        self.event = threading.Event()

    def bind(self, pod: Pod, hostname: str) -> None:
        key = f"{pod.namespace}/{pod.name}"
        self.binds[key] = hostname
        self.channel.append(key)
        if not self.event.is_set():  # set() takes a lock — skip when already up
            self.event.set()

    def bind_many(self, pairs) -> None:
        """Batch bind — one call per cycle from the cache's async
        dispatcher; must be all-or-nothing (the dispatcher retries per-task
        through bind() on failure). Subclasses overriding bind() must
        override bind_many() too — the dispatcher prefers this batch
        entrypoint whenever the binder exposes it."""
        keys = [f"{pod.namespace}/{pod.name}" for pod, _ in pairs]
        self.binds.update(zip(keys, (h for _, h in pairs)))
        self.channel.extend(keys)
        if not self.event.is_set():
            self.event.set()


class FakeEvictor:
    def __init__(self):
        self.evicts: List[str] = []
        self.event = threading.Event()

    def evict(self, pod: Pod) -> None:
        self.evicts.append(f"{pod.namespace}/{pod.name}")
        self.event.set()


class FakeStatusUpdater:
    def __init__(self):
        self.pod_conditions: List[tuple] = []
        self.pod_groups: List[object] = []
        self.queue_statuses: dict = {}  # queue name → last written counts

    def update_pod_condition(self, pod, condition) -> None:
        self.pod_conditions.append((f"{pod.namespace}/{pod.name}", condition))

    def update_pod_group(self, pod_group) -> None:
        self.pod_groups.append(pod_group)

    def update_queue_status(self, name: str, counts: dict) -> None:
        self.queue_statuses[name] = dict(counts)


class FakeVolumeBinder:
    # lets the allocate replay skip the per-task volume calls wholesale
    noop = True

    def __init__(self):
        # empty ledgers: the watch reconcile iterates them (finding nothing
        # stale) instead of probing for their existence
        self.pvs: dict = {}
        self.claims: dict = {}
        self.storage_classes: dict = {}

    def allocate_volumes(self, task, hostname) -> None:
        pass

    def bind_volumes(self, task) -> None:
        pass

    # explicit no-op ingest (the reference's fake volume binder drops these
    # the same way) — declared so the translate dispatcher sees a complete
    # seam instead of a silent getattr miss
    def add_pv(self, pv) -> None:
        pass

    def delete_pv(self, name) -> None:
        pass

    def add_pvc(self, pvc) -> None:
        pass

    def delete_pvc(self, key) -> None:
        pass

    def add_storage_class(self, name, provisioner) -> None:
        pass

    def delete_storage_class(self, name) -> None:
        pass
