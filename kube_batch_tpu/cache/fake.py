"""Test fakes (pkg/scheduler/util/test_utils.go:94-163): record effects into
maps and signal a channel-like event so tests can await async binds."""

from __future__ import annotations

import threading
from typing import Dict, List

from kube_batch_tpu.api.pod import Pod


class FakeBinder:
    def __init__(self):
        self.binds: Dict[str, str] = {}  # "ns/name" → node
        self.channel: List[str] = []
        self.event = threading.Event()

    def bind(self, pod: Pod, hostname: str) -> None:
        key = f"{pod.namespace}/{pod.name}"
        self.binds[key] = hostname
        self.channel.append(key)
        self.event.set()


class FakeEvictor:
    def __init__(self):
        self.evicts: List[str] = []
        self.event = threading.Event()

    def evict(self, pod: Pod) -> None:
        self.evicts.append(f"{pod.namespace}/{pod.name}")
        self.event.set()


class FakeStatusUpdater:
    def __init__(self):
        self.pod_conditions: List[tuple] = []
        self.pod_groups: List[object] = []

    def update_pod_condition(self, pod, condition) -> None:
        self.pod_conditions.append((f"{pod.namespace}/{pod.name}", condition))

    def update_pod_group(self, pod_group) -> None:
        self.pod_groups.append(pod_group)


class FakeVolumeBinder:
    # lets the allocate replay skip the per-task volume calls wholesale
    noop = True

    def allocate_volumes(self, task, hostname) -> None:
        pass

    def bind_volumes(self, task) -> None:
        pass
