"""Cache state persistence — the standalone durable-state story.

The reference keeps ALL durable state in the API server/etcd and rebuilds its
cache by re-list + re-watch on restart (cache.go:342-384, SURVEY.md §5.4);
the `Inqueue` phase persisted on PodGroup.Status survives restarts
(enqueue.go:115). Standalone there is no etcd, so the cache itself snapshots
to a JSON state file: save after each cycle (atomic tmp+rename), load at
startup. Shadow PodGroups are skipped — add_pod regenerates them."""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile

from kube_batch_tpu.api import serialize
from kube_batch_tpu.api.pod import PersistentVolume, PodDisruptionBudget, PriorityClass


def save_state(cache, path: str) -> None:
    # Snapshot object references under the lock (shallow list/dict copies —
    # O(objects), no serialization); build the dicts and write the file
    # outside it, so a per-cycle save at 50k pods doesn't block the ingest /
    # bind / evict handlers for the full serialization time. Pod/Node/Queue
    # objects are immutable-by-convention after ingest (handlers replace,
    # not mutate) EXCEPT pod.node_name, which the async binder ack mutates —
    # drain in-flight dispatches first so the state file can't miss a
    # just-acked binding (restoring such a pod as Pending).
    flush = getattr(cache, "flush_binds", None)
    if flush is not None:
        flush()
    with cache._lock:
        pods = list(cache.pods.values())
        nodes = [n.node for n in cache.nodes.values() if n.node is not None]
        pod_groups = [
            j.pod_group
            for j in cache.jobs.values()
            if j.pod_group is not None and not j.pod_group.shadow
        ]
        pdbs = [j.pdb for j in cache.jobs.values() if j.pdb is not None]
        queues = [q.queue for q in cache.queues.values()]
        priority_classes = list(cache.priority_classes.values())
        pod_conditions = dict(cache.pod_conditions)
        pvs = list(getattr(cache.volume_binder, "pvs", {}).values())
        pv_bound = dict(getattr(cache.volume_binder, "bound", {}))
    state = {
        "pods": [serialize.pod_to_dict(p) for p in pods],
        "nodes": [serialize.node_to_dict(n) for n in nodes],
        "pod_groups": [serialize.pod_group_to_dict(pg) for pg in pod_groups],
        "queues": [serialize.queue_to_dict(q) for q in queues],
        "priority_classes": [
            {"name": pc.name, "value": pc.value, "global_default": pc.global_default}
            for pc in priority_classes
        ],
        "pod_conditions": pod_conditions,
        "pdbs": [dataclasses.asdict(p) for p in pdbs],
        "pvs": [dataclasses.asdict(p) for p in pvs],
        "pv_bound": pv_bound,
    }
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(os.path.abspath(path)))
    with os.fdopen(fd, "w") as f:
        json.dump(state, f)
    os.replace(tmp, path)


def load_state(cache, path: str) -> bool:
    """Replay a saved state file through the cache's ingest handlers (the
    re-list analog). Returns False when no state file exists."""
    try:
        with open(path) as f:
            state = json.load(f)
    except FileNotFoundError:
        return False
    for q in state.get("queues", []):
        cache.add_queue(serialize.queue_from_dict(q))
    for pc in state.get("priority_classes", []):
        cache.add_priority_class(PriorityClass(**pc))
    for n in state.get("nodes", []):
        cache.add_node(serialize.node_from_dict(n))
    for pg in state.get("pod_groups", []):
        cache.add_pod_group(serialize.pod_group_from_dict(pg))
    for pdb in state.get("pdbs", []):
        cache.add_pdb(PodDisruptionBudget(**pdb))
    for p in state.get("pods", []):
        cache.add_pod(serialize.pod_from_dict(p))
    cache.pod_conditions.update(state.get("pod_conditions", {}))
    # capability = "carries a durable pv binding ledger", probed on the
    # ledger itself — NOT on add_pv presence: the fake binder implements
    # the full ingest surface as explicit no-ops (cache/interface.py), so
    # a method probe would pass and then write into ledgers it lacks
    binder = cache.volume_binder
    if getattr(binder, "bound", None) is not None:
        for pv in state.get("pvs", []):
            binder.add_pv(PersistentVolume(**pv))
        binder.bound.update(state.get("pv_bound", {}))
    return True
