"""Explicit-collective shard_map solve bodies over the device mesh.

The pjit path (parallel/mesh.py) shards the node axis declaratively and
lets XLA's SPMD partitioner insert collectives — correct, but the
cross-host traffic is whatever GSPMD decides, and nothing bounds it as the
mesh grows to multi-host ICI+DCN.  This module rewrites the sharded solves
as ``shard_map`` bodies in which every cross-shard byte is AUTHORED:

- each shard computes its local block of the [T, N]-scale round head
  (feasibility, score, masked two-key argmax) over its node shard (and,
  on a 2-D ``(tasks, nodes)`` mesh, its task block);
- per round the shards reduce the TASK-SIZED winner vectors with explicit
  ``pmax``/``pmin``/``psum`` collectives (the two-key argmax decomposes
  into three O(T) reductions) — only O(tasks) crosses hosts per round,
  never O(tasks × nodes) or O(nodes);
- the node ledgers are all-gathered ONCE per solve (O(N·R) per cycle, not
  per round) so the conflict-resolution / gang-commit tail runs as
  replicated compute — literally the same :func:`ops.assignment.
  allocate_rounds` / :func:`ops.eviction.evict_rounds` machinery the
  single-device solve runs, which is what makes the shard_map path
  bit-exact against the pjit path by construction.

Collective inventory per allocate round (see utils/jitstats.
collective_inventory, which derives this from the traced program rather
than trusting this comment):

  pmax [T] f32   — global max score per task
  pmax [T] i32   — max tie-hash among max-score shards
  pmin [T] i32   — lowest global node index among (score, hash) ties
  psum [T] i32   — the winning shard contributes chose_idle
  (+ all_gather [T_blk] → [T] ×3 over the task axis when it is sharded)

Task-axis sharding (the second mesh dim): the [T, N] intermediates are
the HBM hogs at the 500k×50k north star (~2.5e10 elements); sharding the
task axis too divides them by the task-shard count.  The body slices its
task block out of the replicated task columns (no extra inputs), computes
[T_blk, N_loc] matrices, and reassembles the O(T) winner vectors with one
tiled ``all_gather`` per round over the task axis.  The replicated tail
is unchanged — its arrays are O(T) and O(N), never O(T × N).

Exactness notes (why bit-equal, not just equivalent):
- every [T_blk, N_loc] matrix element is computed by the same scalar
  expression as the corresponding element of the full matrix (the block
  view slices inputs; the tie-hash takes global offsets);
- the two-key argmax decomposition (max value → max hash among value
  ties → min global index among (value, hash) ties) reproduces
  ``jnp.argmax``'s first-max-index semantics exactly — integer and exact
  f32 comparisons only, no arithmetic on the reduced values;
- per-node accumulations (victim capacity) sum the same values in the
  same task order per node as the global program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from kube_batch_tpu.ops import assignment as asg
from kube_batch_tpu.ops import eviction as evi
from kube_batch_tpu.ops.admission import gate_scan
from kube_batch_tpu.ops.feasibility import (
    FeasibilityMasks,
    failure_histogram,
    fits,
    static_predicates,
)
from kube_batch_tpu.ops.scoring import score_matrix

NEG = asg.NEG
BIG = jnp.int32(1 << 30)

# axis names live in parallel.mesh (shard_solve is imported lazily from
# there, so this import is acyclic at module load)
from kube_batch_tpu.parallel.mesh import NODE_AXIS, TASK_AXIS  # noqa: E402


def _axis_sizes(mesh):
    shape = dict(mesh.shape)
    return shape.get(TASK_AXIS, 1), shape[NODE_AXIS]


def _gather_tasks(x, task_shards):
    """Reassemble a [T_blk, ...] per-task-shard vector into the full [T]
    vector (tiled all_gather over the task axis; identity when the task
    axis is unsharded)."""
    if task_shards == 1:
        return x
    return jax.lax.all_gather(x, TASK_AXIS, axis=0, tiled=True)


def _gather_nodes(x, node_shards):
    """One-per-solve reassembly of a node-sharded [N_loc, ...] column into
    the replicated global [N, ...] array the solve tail consumes."""
    if node_shards == 1:
        return x
    return jax.lax.all_gather(x, NODE_AXIS, axis=0, tiled=True)


def _block_view(snap, t0, T_blk, task_shards):
    """``snap`` restricted to this shard's task block.  Node-axis arrays
    arrive shard-local under shard_map and pass through; task-axis arrays
    are sliced to [t0, t0+T_blk); the sparse affinity/preference row
    indices are remapped into block coordinates (out-of-block rows park at
    -1, which their consumers treat as padding).  Per-element math over
    the view equals the same elements of the global matrices — the
    bit-exactness contract of the SPMD round head."""
    if task_shards == 1:
        return snap
    ts = partial(jax.lax.dynamic_slice_in_dim, start_index=t0,
                 slice_size=T_blk, axis=0)
    aff = snap.task_aff_idx
    aff_l = jnp.where((aff >= t0) & (aff < t0 + T_blk), aff - t0, -1)
    pref = snap.task_pref_idx
    pref_l = jnp.where((pref >= t0) & (pref < t0 + T_blk), pref - t0, -1)
    return snap._replace(
        task_req=ts(snap.task_req),
        task_resreq=ts(snap.task_resreq),
        task_job=ts(snap.task_job),
        task_prio=ts(snap.task_prio),
        task_creation=ts(snap.task_creation),
        task_status=ts(snap.task_status),
        task_valid=ts(snap.task_valid),
        task_pending=ts(snap.task_pending),
        task_best_effort=ts(snap.task_best_effort),
        task_sel_bits=ts(snap.task_sel_bits),
        task_sel_impossible=ts(snap.task_sel_impossible),
        task_tol_bits=ts(snap.task_tol_bits),
        task_node=ts(snap.task_node),
        task_critical=ts(snap.task_critical),
        task_needs_host=ts(snap.task_needs_host),
        task_aff_idx=aff_l,
        task_pref_idx=pref_l,
    )


def _local_best(masked, tie_blk, n0):
    """Per-shard two-key winner triple: (lval, lkey, lidx_global) with the
    EXACT semantics of ops.assignment._best_node restricted to this block
    — max score, then max tie-hash among score ties, first index among
    (score, hash) ties (jnp.argmax first-max semantics)."""
    lval = jnp.max(masked, axis=1)
    cand = jnp.where(masked >= lval[:, None], tie_blk, -1)
    pick = jnp.argmax(cand, axis=1).astype(jnp.int32)
    lkey = jnp.max(cand, axis=1)
    return lval, lkey, pick, pick + n0


def _combine_best(lval, lkey, lidx, lextra=None):
    """The cross-shard two-key argmax as ONE stacked-payload collective.

    The first cut ran four DEPENDENT O(T) reductions per round — pmax
    value → pmax key among value ties → pmin global index among (value,
    key) ties → one-hot psum of the winner's extra — four cross-host
    latency hops on DCN.  Since the per-shard triple is tiny (3-4 i32
    rows of T), a single ``all_gather`` of the stacked payload followed by
    a replicated lexicographic reduce over the shard axis computes the
    same winner with ONE collective: the f32 value rides as its
    order-preserving i32 sort key (ops.assignment.f32_sort_key — integer
    compare ≡ float compare), so max-by-(value, key, −index) over the
    gathered [S, ·, T] block is exact.  Equivalent to jnp.argmax over the
    concatenated node axis, bit-for-bit (the pjit oracle and the
    equivalence tests hold it to that)."""
    from kube_batch_tpu.ops.assignment import f32_sort_key

    vkey = f32_sort_key(lval)
    parts = [vkey, lkey, lidx]
    if lextra is not None:
        parts.append(lextra)
    g = jax.lax.all_gather(
        jnp.stack(parts, axis=0), NODE_AXIS, axis=0, tiled=False
    )                                                  # [S, 3|4, T]
    gv, gk, gi = g[:, 0], g[:, 1], g[:, 2]
    vmax_k = jnp.max(gv, axis=0)
    # the key map is a bijection, so the max key's preimage IS the max value
    vmax = _inv_sort_key(vmax_k)
    eq = gv == vmax_k
    kmax = jnp.max(jnp.where(eq, gk, jnp.asarray(-1, gk.dtype)), axis=0)
    eqk = eq & (gk == kmax)
    imin = jnp.min(jnp.where(eqk, gi, BIG), axis=0)
    if lextra is None:
        return vmax, imin
    win = eqk & (gi == imin)
    shard = jnp.argmax(win, axis=0)[None]              # [1, T]
    extra = jnp.take_along_axis(g[:, 3], shard, axis=0)[0]
    return vmax, imin, extra


def _inv_sort_key(k):
    """Inverse of ops.assignment.f32_sort_key (exact bijection)."""
    b = jnp.where(k < 0, k ^ jnp.int32(0x7FFFFFFF), k)
    return jax.lax.bitcast_convert_type(b, jnp.float32)


# --------------------------------------------------------------------------
# allocate
# --------------------------------------------------------------------------


def _allocate_body(snap, *, config, node_shards, task_shards):
    N_loc = snap.node_idle.shape[0]
    T = snap.task_req.shape[0]
    T_blk = T // task_shards
    n0 = jax.lax.axis_index(NODE_AXIS) * N_loc
    t0 = (
        jax.lax.axis_index(TASK_AXIS) * T_blk if task_shards > 1
        else 0
    )
    view = _block_view(snap, t0, T_blk, task_shards)
    # the loop-invariant [T_blk, N_loc] blocks, computed once per solve
    static_ok = static_predicates(view)
    score = score_matrix(view, config.weights)
    score_static = jnp.where(static_ok, score, NEG)
    tie_blk = asg._tie_break_hash(T_blk, N_loc, t0=t0, n0=n0)
    req_blk = view.task_req
    quanta = snap.quanta

    def head(idle_g, releasing_g, pending):
        idle_b = jax.lax.dynamic_slice_in_dim(idle_g, n0, N_loc, axis=0)
        rel_b = jax.lax.dynamic_slice_in_dim(releasing_g, n0, N_loc, axis=0)
        pending_b = (
            pending if task_shards == 1
            else jax.lax.dynamic_slice_in_dim(pending, t0, T_blk, axis=0)
        )
        if config.use_pallas:
            from kube_batch_tpu.ops.pallas_kernels import masked_best_node_raw

            pick, lval, lkey, lchose = masked_best_node_raw(
                score, static_ok, req_blk, idle_b, rel_b, pending_b,
                quanta, t0=t0, n0=n0,
                interpret=jax.default_backend() != "tpu",
            )
            lidx = pick + n0
        else:
            fit_idle = fits(req_blk, idle_b, quanta)
            # per-shard zero-releasing skip: exact for solver outputs (see
            # local_round_head), and finer-grained than the global test —
            # a shard with no releasing budget skips its block fit alone
            fit_rel = jax.lax.cond(
                jnp.any(rel_b > 0.0),
                lambda rel: fits(req_blk, rel, quanta),
                lambda rel: jnp.zeros_like(fit_idle),
                rel_b,
            )
            masked = jnp.where(
                (fit_idle | fit_rel) & pending_b[:, None], score_static, NEG
            )
            lval, lkey, pick, lidx = _local_best(masked, tie_blk, n0)
            lchose = jnp.take_along_axis(fit_idle, pick[:, None], axis=1)[:, 0]
        vmax, best_b, chose_b = _combine_best(
            lval, lkey, lidx, lchose.astype(jnp.int32)
        )
        best = _gather_tasks(best_b, task_shards)
        has = _gather_tasks(vmax > NEG, task_shards)
        chose = _gather_tasks(chose_b > 0, task_shards)
        return best, has, chose

    # the conflict/gang tail runs replicated on the explicitly gathered
    # ledgers — one O(N·R) all_gather per solve, zero per-round node bytes
    idle0 = _gather_nodes(snap.node_idle, node_shards)
    rel0 = _gather_nodes(snap.node_releasing, node_shards)
    used0 = _gather_nodes(snap.node_used, node_shards)
    res = asg.allocate_rounds(snap, config, head, idle0, rel0, used0)
    # emit the node ledgers as this shard's local blocks (out_specs
    # reassemble the node-sharded placement the pjit path produces)
    sl = partial(jax.lax.dynamic_slice_in_dim, start_index=n0,
                 slice_size=N_loc, axis=0)
    return res._replace(
        node_idle=sl(res.node_idle),
        node_releasing=sl(res.node_releasing),
        node_used=sl(res.node_used),
    )


# --------------------------------------------------------------------------
# compacted allocate (KB_TOPK) — zero per-round cross-shard collectives
# --------------------------------------------------------------------------


def _allocate_topk_body(snap, pend_rows, *, config, node_shards):
    """The compacted sharded solve: each shard ranks its local [P, N_loc]
    block into a [P, K] candidate list (exact lex order, global node
    indices, offset tie hash), the lists merge via ONE per-solve
    ``all_gather`` + replicated top-K merge, and the bidding rounds then
    run fully replicated on the merged table + the gathered ledgers — ZERO
    per-round cross-shard collectives (``collective_stats`` proves it from
    the traced program).  The exhaustion re-entry computes the full-matrix
    head over the bucket from per-solve-gathered node columns, so even the
    rare fallback rounds stay collective-free."""
    from kube_batch_tpu.ops import assignment as _asg

    N_loc = snap.node_idle.shape[0]
    N = N_loc * node_shards
    T = snap.task_req.shape[0]
    K = config.topk
    n0 = jax.lax.axis_index(NODE_AXIS) * N_loc
    quanta = snap.quanta
    P_rows = pend_rows.shape[0]

    # ---- local block build + single-gather merge ------------------------
    view_l = _asg.pend_view(snap, pend_rows)
    ki, ks, kh, n_feas_l, _ss, _tie = _asg.compact_candidates(
        view_l, pend_rows, snap.node_idle, snap.node_releasing, quanta,
        config, n0=n0,
    )
    payload = jnp.concatenate(
        [ks, kh, ki, n_feas_l[:, None]], axis=1
    )                                                  # [P, 3K+1] i32
    g = jax.lax.all_gather(payload, NODE_AXIS, axis=0, tiled=False)
    # shard-major concat: positions ascend with the global node index, so
    # the merge's first-position tie rule keeps jnp.argmax semantics
    skeys = jnp.transpose(g[:, :, 0:K], (1, 0, 2)).reshape(P_rows, -1)
    hashes = jnp.transpose(g[:, :, K:2 * K], (1, 0, 2)).reshape(P_rows, -1)
    idxs = jnp.transpose(g[:, :, 2 * K:3 * K], (1, 0, 2)).reshape(P_rows, -1)
    n_feas = jnp.sum(g[:, :, 3 * K], axis=0)
    mi, ms, mh = _asg.lex_topk(skeys, hashes, idxs, K, block=max(K, 8))
    truncated = n_feas > K

    # ---- per-solve gathers: ledgers + the fallback's node columns -------
    idle0 = _gather_nodes(snap.node_idle, node_shards)
    rel0 = _gather_nodes(snap.node_releasing, node_shards)
    used0 = _gather_nodes(snap.node_used, node_shards)

    def _gn(x):
        return _gather_nodes(x, node_shards)

    def _gn1(x):  # [K?, N_loc] sharded along axis 1
        if node_shards == 1:
            return x
        return jax.lax.all_gather(x, NODE_AXIS, axis=1, tiled=True)

    snap_repl = snap._replace(
        node_idle=idle0, node_releasing=rel0, node_used=used0,
        node_alloc=_gn(snap.node_alloc), node_valid=_gn(snap.node_valid),
        node_sched=_gn(snap.node_sched),
        node_label_bits=_gn(snap.node_label_bits),
        node_taint_bits=_gn(snap.node_taint_bits),
        task_aff_mask=_gn1(snap.task_aff_mask),
        task_pref_node=_gn1(snap.task_pref_node),
        task_pref_pod=_gn1(snap.task_pref_pod),
    )
    view_repl = _asg.pend_view(snap_repl, pend_rows)
    safe_rows = jnp.maximum(pend_rows, 0)

    def fallback(idle, releasing, pending_exh):
        # traced inside the exhaustion cond — the [P, N] planes are only
        # computed in rounds that actually re-enter the full-matrix head
        static_ok = static_predicates(view_repl)
        score = score_matrix(view_repl, config.weights)
        ss = jnp.where(static_ok, score, NEG)
        tie = _asg.tie_break_hash_rows(
            safe_rows, jnp.arange(N, dtype=jnp.int32)
        )
        return _asg.make_bucket_fallback(view_repl, ss, tie, quanta)(
            idle, releasing, pending_exh
        )

    head = _asg.make_compact_head(
        mi, ms, mh, truncated, view_repl.task_req, quanta, N, fallback,
    )
    # rounds run replicated AND bucket-native: the rank/gate/conflict
    # machinery shrinks from [T] to [P] exactly like the single-device
    # compacted solve (scatter_bucket_result documents the exactness)
    res = _asg.allocate_rounds(
        view_repl, config, None, idle0, rel0, used0, compact_head=head
    )
    res = _asg.scatter_bucket_result(res, pend_rows, T)
    sl = partial(jax.lax.dynamic_slice_in_dim, start_index=n0,
                 slice_size=N_loc, axis=0)
    return res._replace(
        node_idle=sl(res.node_idle),
        node_releasing=sl(res.node_releasing),
        node_used=sl(res.node_used),
    )


def allocate_topk_shard_map(mesh, config):
    """jitted shard_map compacted allocate solve for (mesh, config) — the
    pending-row bucket rides replicated; node-axis inputs shard-local like
    the full solve.  Task-axis (2-D) meshes are not compacted — the
    dispatch routes them to the full path (their regime is the cold-start
    HBM escape, where the whole task axis is pending anyway)."""
    from kube_batch_tpu.ops.assignment import AllocateResult

    task_shards, node_shards = _axis_sizes(mesh)
    if task_shards != 1:
        raise ValueError("KB_TOPK compaction requires a 1-D node mesh")
    node2 = P(NODE_AXIS, None)
    out_specs = AllocateResult(
        assigned=P(), pipelined=P(), committed=P(),
        node_idle=node2, node_releasing=node2, node_used=node2,
        deserved=P(), rounds_run=P(),
        topk_exhausted=P(), topk_reentries=P(),
    )
    body = partial(_allocate_topk_body, config=config,
                   node_shards=node_shards)
    return _shard_map(body, mesh, (_snapshot_specs(mesh), P()), out_specs)


# --------------------------------------------------------------------------
# warm-started compacted allocate (KB_WARM) — cross-cycle table carry
# --------------------------------------------------------------------------


def _warm_allocate_body(snap, pend_rows, t_idx, t_skey, t_hash, t_trunc,
                        row_map, changed_nodes, rerank_rows, rerank_slots,
                        *, config, node_shards, k_min):
    """The sharded warm solve: the carried [P, W] table rides REPLICATED
    across cycles; per solve each shard contributes only delta-sized work —

    - the fresh keys of ITS OWN changed nodes (a [P, C] partial over the
      local node columns, merged with ONE psum: each changed node is owned
      by exactly one shard, so the masked-sum is the exact stacked value);
    - its local [Pi, W] candidate lists for the INVALIDATED sub-bucket,
      merged with one all_gather + replicated lex merge — the PR 10
      per-solve merge, now shipped only for the invalidated rows.

    Table refresh (permute / remove / θ-cut merge / re-rank scatter) and
    the bidding rounds run replicated on the merged state + the per-solve
    gathered ledgers, so the round loop keeps the compacted path's ZERO
    per-round cross-shard collectives."""
    from kube_batch_tpu.ops import assignment as _asg

    N_loc = snap.node_idle.shape[0]
    N = N_loc * node_shards
    T = snap.task_req.shape[0]
    W = config.topk
    n0 = jax.lax.axis_index(NODE_AXIS) * N_loc
    quanta = snap.quanta

    # ---- fresh changed-node keys over the [M] live prefix: per-shard
    # partial + one psum (each changed node is owned by exactly one shard)
    M = row_map.shape[0]
    rows_m = pend_rows[:M]
    view_lm = _asg.pend_view(snap, rows_m)
    loc = changed_nodes - n0
    own = (changed_nodes >= 0) & (loc >= 0) & (loc < N_loc)
    view_lc = _asg.node_view(view_lm, jnp.where(own, loc, -1))
    skey_part = _asg.fresh_block_skey(view_lc, quanta, config)
    skey_c = jax.lax.psum(
        jnp.where(own[None, :], skey_part, 0), NODE_AXIS
    )
    skey_c = jnp.where(
        (changed_nodes >= 0)[None, :], skey_c, _asg._I32_MIN
    )
    hash_c = _asg.tie_break_hash_rows(
        jnp.maximum(rows_m, 0), jnp.maximum(changed_nodes, 0)
    )

    # ---- invalidated sub-bucket: local build, gather, replicated merge --
    view_i = _asg.pend_view(snap, rerank_rows)
    ki, ks, kh, nf_l, _ss, _tie = _asg.compact_candidates(
        view_i, rerank_rows, snap.node_idle, snap.node_releasing, quanta,
        config, n0=n0,
    )
    Pi = rerank_rows.shape[0]
    payload = jnp.concatenate([ks, kh, ki, nf_l[:, None]], axis=1)
    g = jax.lax.all_gather(payload, NODE_AXIS, axis=0, tiled=False)
    skeys = jnp.transpose(g[:, :, 0:W], (1, 0, 2)).reshape(Pi, -1)
    hashes = jnp.transpose(g[:, :, W:2 * W], (1, 0, 2)).reshape(Pi, -1)
    idxs = jnp.transpose(g[:, :, 2 * W:3 * W], (1, 0, 2)).reshape(Pi, -1)
    n_feas = jnp.sum(g[:, :, 3 * W], axis=0)
    ri, rs, rh = _asg.lex_topk(skeys, hashes, idxs, W, block=max(W, 8))

    # ---- replicated table refresh + rounds ------------------------------
    ni, ns, nh, trunc, eroded = _asg.warm_refresh_table(
        t_idx, t_skey, t_hash, t_trunc, row_map, rows_m, changed_nodes,
        skey_c, hash_c, ri, rs, rh, n_feas > W, rerank_slots, N, k_min,
    )
    idle0 = _gather_nodes(snap.node_idle, node_shards)
    rel0 = _gather_nodes(snap.node_releasing, node_shards)
    used0 = _gather_nodes(snap.node_used, node_shards)

    def _gn(x):
        return _gather_nodes(x, node_shards)

    def _gn1(x):
        if node_shards == 1:
            return x
        return jax.lax.all_gather(x, NODE_AXIS, axis=1, tiled=True)

    snap_repl = snap._replace(
        node_idle=idle0, node_releasing=rel0, node_used=used0,
        node_alloc=_gn(snap.node_alloc), node_valid=_gn(snap.node_valid),
        node_sched=_gn(snap.node_sched),
        node_label_bits=_gn(snap.node_label_bits),
        node_taint_bits=_gn(snap.node_taint_bits),
        task_aff_mask=_gn1(snap.task_aff_mask),
        task_pref_node=_gn1(snap.task_pref_node),
        task_pref_pod=_gn1(snap.task_pref_pod),
    )
    view_repl = _asg.pend_view(snap_repl, pend_rows)
    fallback = _asg.make_lazy_bucket_fallback(
        view_repl, pend_rows, quanta, config
    )
    head = _asg.make_compact_head(
        ni, ns, nh, trunc, view_repl.task_req, quanta, N, fallback,
    )
    res = _asg.allocate_rounds(
        view_repl, config, None, idle0, rel0, used0, compact_head=head
    )
    res = _asg.scatter_bucket_result(res, pend_rows, T)
    sl = partial(jax.lax.dynamic_slice_in_dim, start_index=n0,
                 slice_size=N_loc, axis=0)
    res = res._replace(
        node_idle=sl(res.node_idle),
        node_releasing=sl(res.node_releasing),
        node_used=sl(res.node_used),
    )
    return res, (ni, ns, nh, trunc), eroded


def warm_allocate_shard_map(mesh, config, k_min: int):
    """jitted shard_map warm-started compacted solve for (mesh, config,
    k_min) — the carried table and every plan array ride replicated; only
    the node-axis snapshot columns are shard-local.  Like the cold
    compacted path, a 2-D task-sharded mesh declines (the dispatch never
    routes it here)."""
    from kube_batch_tpu.ops.assignment import AllocateResult

    task_shards, node_shards = _axis_sizes(mesh)
    if task_shards != 1:
        raise ValueError("KB_WARM carry requires a 1-D node mesh")
    node2 = P(NODE_AXIS, None)
    res_specs = AllocateResult(
        assigned=P(), pipelined=P(), committed=P(),
        node_idle=node2, node_releasing=node2, node_used=node2,
        deserved=P(), rounds_run=P(),
        topk_exhausted=P(), topk_reentries=P(),
    )
    out_specs = (res_specs, (P(), P(), P(), P()), P())
    body = partial(_warm_allocate_body, config=config,
                   node_shards=node_shards, k_min=k_min)
    in_specs = (_snapshot_specs(mesh),) + (P(),) * 9
    return _shard_map(body, mesh, in_specs, out_specs)


# --------------------------------------------------------------------------
# evict (reclaim / preempt)
# --------------------------------------------------------------------------


def _evict_body(snap, *, config, node_shards, task_shards):
    N_loc = snap.node_alloc.shape[0]
    N = N_loc * node_shards
    T = snap.task_req.shape[0]
    T_blk = T // task_shards
    R = snap.task_req.shape[1]
    Q = snap.queue_weight.shape[0]
    preempt = config.mode == "preempt"
    n0 = jax.lax.axis_index(NODE_AXIS) * N_loc
    t0 = (
        jax.lax.axis_index(TASK_AXIS) * T_blk if task_shards > 1
        else 0
    )
    view = _block_view(snap, t0, T_blk, task_shards)
    static_ok = static_predicates(view)
    score = score_matrix(view, config.weights)
    tie_blk = asg._tie_break_hash(T_blk, N_loc, t0=t0, n0=n0)
    task_queue = snap.job_queue[snap.task_job]          # [T] replicated
    tq_blk = view.job_queue[view.task_job]              # [T_blk]

    def tslice(x):
        if task_shards == 1:
            return x
        return jax.lax.dynamic_slice_in_dim(x, t0, T_blk, axis=0)

    def bids(victim_ok, claimant_ok):
        # ---- per-(queue, local-node) evictable capacity --------------
        # built from the REPLICATED task vectors, restricted to victims
        # resident on this shard's nodes: same values in the same task
        # order per (queue, node) cell as the global scatter
        vreq = jnp.where(victim_ok[:, None], snap.task_resreq, 0.0)
        vnode_l = snap.task_node - n0
        in_shard = (vnode_l >= 0) & (vnode_l < N_loc)
        vreq_l = jnp.where(in_shard[:, None], vreq, 0.0)
        tot_v = jax.ops.segment_sum(
            vreq_l,
            jnp.where(victim_ok & in_shard, vnode_l, N_loc),
            num_segments=N_loc + 1,
        )[:N_loc]                                        # [N_loc, R]
        per_qn = jnp.zeros((Q, N_loc, R), jnp.float32).at[
            task_queue, jnp.clip(vnode_l, 0, N_loc - 1)
        ].add(vreq_l)
        if preempt:
            cap = per_qn                  # same-queue victims
        else:
            cap = tot_v[None] - per_qn    # cross-queue victims

        # ---- block bids (one-hot queue gather, exact f32 matmul) -----
        co_b = tslice(claimant_ok)
        onehot_q = (tq_blk[:, None] == jnp.arange(Q)[None, :]).astype(
            jnp.float32
        )
        feas = static_ok & co_b[:, None]
        feas &= ((tq_blk >= 0) & (tq_blk < Q))[:, None]
        for r in range(R):
            # kbt: allow[KBT005] trace-time unroll over the small static
            # resource dim R inside jit (same rationale as the single path)
            cap_tr = jnp.matmul(
                onehot_q, cap[:, :, r], precision=jax.lax.Precision.HIGHEST
            )                                            # [T_blk, N_loc]
            feas &= view.task_req[:, r, None] <= cap_tr + snap.quanta[r]
        masked = jnp.where(feas, score, NEG)
        lval, lkey, _pick, lidx = _local_best(masked, tie_blk, n0)
        vmax, best_b = _combine_best(lval, lkey, lidx)
        best = _gather_tasks(best_b, task_shards)
        has = _gather_tasks(vmax > NEG, task_shards)
        return best, has

    fia = None
    if config.idle_gate and not preempt:
        any_l = jnp.any(
            fits(view.task_req, snap.node_idle, snap.quanta) & static_ok,
            axis=1,
        )
        any_g = jax.lax.psum(any_l.astype(jnp.int32), NODE_AXIS) > 0
        fia = _gather_tasks(any_g, task_shards)
    return evi.evict_rounds(snap, config, bids, fia, n_nodes=N)


# --------------------------------------------------------------------------
# fit-error histogram
# --------------------------------------------------------------------------


def _histogram_body(snap, *, node_shards, task_shards):
    T = snap.task_req.shape[0]
    T_blk = T // task_shards
    t0 = (
        jax.lax.axis_index(TASK_AXIS) * T_blk if task_shards > 1
        else 0
    )
    view = _block_view(snap, t0, T_blk, task_shards)
    static_ok = static_predicates(view)
    fit_i = fits(view.task_req, snap.node_idle, snap.quanta)
    fit_r = fits(view.task_req, snap.node_releasing, snap.quanta)
    h = failure_histogram(
        view,
        FeasibilityMasks(static_ok, fit_i, fit_r,
                         static_ok & (fit_i | fit_r)),
    )
    # every histogram column is an integer count over nodes — one exact
    # O(T × N_REASONS) psum reduces the per-shard partial counts
    h = jax.lax.psum(h, NODE_AXIS)
    return _gather_tasks(h, task_shards)


def _histogram_bucket_body(snap, pend_rows, *, node_shards):
    """The fit-error histogram on the [P] pending bucket: per-shard
    [P, N_loc] partial counts, one psum, scattered back to the [T] task
    axis (the compacted-allocate bucket idiom applied to the histogram —
    every consumer reads rows only for unplaced PENDING tasks, all of
    which the bucket covers)."""
    from kube_batch_tpu.ops import assignment as _asg
    from kube_batch_tpu.ops.feasibility import N_REASONS

    T = snap.task_req.shape[0]
    view = _asg.pend_view(snap, pend_rows)
    static_ok = static_predicates(view)
    fit_i = fits(view.task_req, snap.node_idle, snap.quanta)
    fit_r = fits(view.task_req, snap.node_releasing, snap.quanta)
    h = failure_histogram(
        view,
        FeasibilityMasks(static_ok, fit_i, fit_r,
                         static_ok & (fit_i | fit_r)),
    )
    h = jax.lax.psum(h, NODE_AXIS)
    scat = jnp.where(pend_rows >= 0, pend_rows, T)
    return jnp.zeros((T + 1, N_REASONS), jnp.int32).at[scat].set(h)[:T]


# --------------------------------------------------------------------------
# builders — jitted shard_map wrappers (memoized by parallel.mesh)
# --------------------------------------------------------------------------


def _shard_map(body, mesh, in_specs, out_specs):
    from jax.experimental.shard_map import shard_map

    try:
        mapped = shard_map(body, mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False)
    except TypeError:  # newer jax: check_rep renamed/removed
        mapped = shard_map(body, mesh, in_specs=in_specs,
                           out_specs=out_specs)
    return jax.jit(mapped)


def _snapshot_specs(mesh):
    from kube_batch_tpu.parallel.mesh import snapshot_shardings

    return jax.tree.map(lambda s: s.spec, snapshot_shardings(mesh))


def allocate_shard_map(mesh, config):
    """jitted shard_map allocate solve for (mesh, config) — node-axis
    inputs consumed shard-local, task/job/queue inputs replicated, node
    ledgers emitted node-sharded, task vectors replicated."""
    from kube_batch_tpu.ops.assignment import AllocateResult

    task_shards, node_shards = _axis_sizes(mesh)
    node2 = P(NODE_AXIS, None)
    out_specs = AllocateResult(
        assigned=P(), pipelined=P(), committed=P(),
        node_idle=node2, node_releasing=node2, node_used=node2,
        deserved=P(), rounds_run=P(),
        topk_exhausted=P(), topk_reentries=P(),
    )
    body = partial(_allocate_body, config=config,
                   node_shards=node_shards, task_shards=task_shards)
    return _shard_map(body, mesh, (_snapshot_specs(mesh),), out_specs)


def evict_shard_map(mesh, config):
    """jitted shard_map eviction solve — every EvictResult field is
    task-axis, so all outputs replicate."""
    from kube_batch_tpu.ops.eviction import EvictResult

    task_shards, node_shards = _axis_sizes(mesh)
    out_specs = EvictResult(
        claim_node=P(), evicted=P(), victim_claimant=P()
    )
    body = partial(_evict_body, config=config,
                   node_shards=node_shards, task_shards=task_shards)
    return _shard_map(body, mesh, (_snapshot_specs(mesh),), out_specs)


def failure_histogram_shard_map(mesh):
    """jitted shard_map fit-error histogram: per-shard partial counts, one
    psum over the node shards, replicated [T, N_REASONS] out."""
    task_shards, node_shards = _axis_sizes(mesh)
    body = partial(_histogram_body,
                   node_shards=node_shards, task_shards=task_shards)
    return _shard_map(body, mesh, (_snapshot_specs(mesh),), P())


def failure_histogram_bucket_shard_map(mesh):
    """jitted shard_map BUCKETED fit-error histogram (the [P] pending
    bucket instead of [T, N] — dispatched whenever the compacted allocate
    planned a bucket this cycle; 1-D node meshes only, like the compacted
    solve itself)."""
    task_shards, node_shards = _axis_sizes(mesh)
    if task_shards != 1:
        raise ValueError("bucketed histogram requires a 1-D node mesh")
    body = partial(_histogram_bucket_body, node_shards=node_shards)
    return _shard_map(body, mesh, (_snapshot_specs(mesh), P()), P())


def _probe_body(snap, batch, probe_rows, *, config, evict_config,
                with_evictions, node_shards):
    """The shard_map what-if probe (ops/probe.py): each shard computes the
    [G, N_loc] blocks — gang-view static predicates, scores, per-round
    fits, eviction bids, fit-error histogram partials — and the gang-sized
    winner vectors reduce with the SAME two-key pargmax decomposition the
    sharded allocate solve uses.  Everything downstream of the blocks is
    :func:`ops.probe.probe_gang_core`, verbatim — the bit-exactness story
    is the one the solves already proved.

    The task axis of a 2-D mesh is untouched (a gang's G rows are tiny);
    on such meshes every task-shard row computes identical replicated
    results with zero task-axis collectives."""
    from kube_batch_tpu.ops import probe as prb

    N_loc = snap.node_idle.shape[0]
    N = N_loc * node_shards
    n0 = jax.lax.axis_index(NODE_AXIS) * N_loc
    # the replicated ledgers for the allocate-rounds tail: one O(N·R)
    # all_gather per DISPATCH (not per gang — hoisted out of the vmap),
    # mirroring the sharded allocate body's once-per-solve gather
    idle0 = _gather_nodes(snap.node_idle, node_shards)
    rel0 = _gather_nodes(snap.node_releasing, node_shards)
    used0 = _gather_nodes(snap.node_used, node_shards)
    # admission budget: local used-sum + one O(R) psum
    used_l = jnp.sum(
        jnp.where(snap.node_valid[:, None], snap.node_used, 0.0), axis=0
    )
    used = jax.lax.psum(used_l, NODE_AXIS)
    oc_idle = jnp.maximum(snap.total * prb.OVERCOMMIT_FACTOR - used, 0.0)

    def one(g):
        view = prb._gang_view(
            snap, g.req, g.valid, g.min_avail, g.queue, g.prio,
            g.sel_bits, g.sel_impossible, g.tol_bits,
        )
        static_ok = static_predicates(view)            # [G, N_loc]
        score = score_matrix(view, config.weights)
        score_static = jnp.where(static_ok, score, NEG)
        tie_blk = asg.tie_break_hash_rows(
            probe_rows, jnp.arange(N_loc, dtype=jnp.int32) + n0
        )

        def head(idle_g, releasing_g, pending):
            idle_b = jax.lax.dynamic_slice_in_dim(idle_g, n0, N_loc, axis=0)
            rel_b = jax.lax.dynamic_slice_in_dim(
                releasing_g, n0, N_loc, axis=0
            )
            fit_idle = fits(view.task_req, idle_b, snap.quanta)
            fit_rel = jax.lax.cond(
                jnp.any(rel_b > 0.0),
                lambda rel: fits(view.task_req, rel, snap.quanta),
                lambda rel: jnp.zeros_like(fit_idle),
                rel_b,
            )
            masked = jnp.where(
                (fit_idle | fit_rel) & pending[:, None], score_static, NEG
            )
            lval, lkey, pick, lidx = _local_best(masked, tie_blk, n0)
            lchose = jnp.take_along_axis(fit_idle, pick[:, None], axis=1)[:, 0]
            vmax, best, chose = _combine_best(
                lval, lkey, lidx, lchose.astype(jnp.int32)
            )
            return best, vmax > NEG, chose > 0

        def bid_fn(claimant_ok, cap):
            cap_b = jax.lax.dynamic_slice_in_dim(cap, n0, N_loc, axis=0)
            feas = static_ok & claimant_ok[:, None]
            feas &= jnp.all(
                g.req[:, None, :] <= cap_b[None, :, :] + snap.quanta, axis=-1
            )
            masked = jnp.where(feas, score, NEG)
            lval, lkey, _pick, lidx = _local_best(masked, tie_blk, n0)
            vmax, best = _combine_best(lval, lkey, lidx)
            return best, vmax > NEG

        def hist_fn():
            fit_idle0 = fits(view.task_req, snap.node_idle, snap.quanta)
            fit_rel0 = fits(view.task_req, snap.node_releasing, snap.quanta)
            h = failure_histogram(
                view,
                FeasibilityMasks(
                    static_ok, fit_idle0, fit_rel0,
                    static_ok & (fit_idle0 | fit_rel0),
                ),
            )
            # every histogram column is an integer count over nodes — one
            # exact psum reduces the per-shard partials (same argument as
            # the sharded failure-histogram solve)
            return jax.lax.psum(h, NODE_AXIS)

        return prb.probe_gang_core(
            snap, view, g, config, evict_config, with_evictions,
            head=head, bid_fn=bid_fn, hist_fn=hist_fn, oc_idle=oc_idle,
            idle0=idle0, rel0=rel0, used0=used0, n_nodes=N,
        )

    return jax.vmap(one)(batch)


def probe_shard_map(mesh, config, evict_config, with_evictions):
    """jitted shard_map what-if probe for (mesh, config, evict_config,
    with_evictions) — node-axis snapshot columns consumed shard-local, the
    probe batch and row oracle replicated, every ProbeResult field
    replicated (all are B/G/T-axis)."""
    from kube_batch_tpu.ops.probe import ProbeBatch, ProbeResult

    _task_shards, node_shards = _axis_sizes(mesh)
    repl = P()
    batch_specs = ProbeBatch(*([repl] * len(ProbeBatch._fields)))
    out_specs = ProbeResult(*([repl] * len(ProbeResult._fields)))
    body = partial(_probe_body, config=config, evict_config=evict_config,
                   with_evictions=with_evictions, node_shards=node_shards)
    return _shard_map(
        body, mesh, (_snapshot_specs(mesh), batch_specs, repl), out_specs
    )


def enqueue_gate_shard_map(mesh):
    """jitted mesh-replicated enqueue admission scan: the scan is
    sequentially dependent (each admission shrinks the idle the next
    candidate sees), so it cannot decompose across shards — instead every
    device runs the identical ``gate_scan`` program on replicated inputs
    and ZERO bytes cross shards.  The point on a multi-host mesh is
    placement consistency: every process computes the same admitted mask
    from the same replicated operands, so the multi-controller cycle never
    diverges on admission."""
    repl = P()
    return _shard_map(
        gate_scan, mesh,
        (repl, repl, repl, repl), repl,
    )
