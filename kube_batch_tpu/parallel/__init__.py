"""Device-mesh parallelism. Exports resolve lazily (PEP 562): importing
this package must not pull in ops.assignment's module-level jnp constants,
which would initialise the XLA backend before a multi-host deployment's
jax.distributed.initialize (parallel/distributed.py) gets to run."""

__all__ = ["make_mesh", "sharded_allocate_solve", "snapshot_shardings"]


def __getattr__(name):
    if name in __all__:
        from kube_batch_tpu.parallel import mesh

        return getattr(mesh, name)
    raise AttributeError(name)
