from kube_batch_tpu.parallel.mesh import make_mesh, sharded_allocate_solve, snapshot_shardings

__all__ = ["make_mesh", "sharded_allocate_solve", "snapshot_shardings"]
