"""Multi-host distributed setup — the DCN/ICI scaling story.

The reference scales out with active/passive HA replicas (leader election,
server.go:106-151); scheduling itself is single-process. Here the *solve*
scales across chips and hosts: the node axis shards over a global
`jax.sharding.Mesh` whose devices may span hosts — XLA/GSPMD inserts the
collectives, which ride ICI within a host slice and DCN across hosts. The
host-side cache/ingest stays on one leader process (elected via
cmd/leader_election.py); follower hosts only contribute devices through
`jax.distributed`.

Per-cycle cross-host traffic is the same O(tasks) per round as the
single-host sharded solve (parallel/mesh.py): budgets and score columns are
node-local, only the per-task winner (value, index) pairs all-reduce.

Usage on each host of the cluster:

    from kube_batch_tpu.parallel.distributed import initialize, global_mesh
    initialize(coordinator="host0:9000", num_processes=4, process_id=rank)
    mesh = global_mesh()          # 1-D 'nodes' mesh over ALL devices
    # leader: sharded_allocate_solve(snap, config, mesh)
"""

from __future__ import annotations

from typing import Optional

import jax

# NOTE: no top-level kube_batch_tpu.parallel.mesh import — its import chain
# (ops.assignment's module-level jnp constants) initialises the XLA backend,
# which must not happen before jax.distributed.initialize runs

# fallback re-init guard for jax versions without
# jax.distributed.is_initialized: without it a second initialize() call
# skipped the guard entirely and raised from jax.distributed.initialize
# (ADVICE.md #4). Set only on success, so a failed attempt stays retryable.
_initialized = False


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """jax.distributed.initialize wrapper. With no arguments, relies on the
    environment (TPU pod auto-configuration); no-op when already
    initialized or single-process.

    The already-initialized probe must NOT touch the backend:
    jax.process_count() would initialise XLA and make a subsequent
    jax.distributed.initialize impossible (the bug the two-process smoke
    test pinned, tests/test_distributed.py).  jax.distributed.is_initialized
    checks only the coordination-service client — backend-safe, and a
    failed earlier attempt (which leaves coordinator_address residue but no
    client) stays retryable."""
    global _initialized
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        if is_init():
            return  # already initialized
    elif _initialized:
        return  # module-level fallback guard (no is_initialized probe)
    _enable_cpu_collectives()
    if coordinator is None and num_processes is None:
        try:
            jax.distributed.initialize()
        except (RuntimeError, ValueError):
            return  # single-process / no cluster env — stay local
        _initialized = True
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def _enable_cpu_collectives() -> None:
    """Select the gloo cross-process collectives implementation for
    multi-process CPU backends.  The pjit path's GSPMD programs happened
    to tolerate the default ("none") in the two-process smoke, but the
    shard_map bodies' explicit psum/pmax/pmin/all_gather dispatch fails
    there with "Multiprocess computations aren't implemented on the CPU
    backend" unless a real collectives impl is registered.  Must run
    BEFORE the CPU client is created; harmless on TPU/GPU backends (the
    flag only affects make_cpu_client) and silently skipped on jaxlib
    builds without gloo."""
    try:
        from jax._src.lib import xla_client

        if hasattr(xla_client._xla, "make_gloo_tcp_collectives"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 — best-effort, version-dependent
        pass


def global_mesh():
    """1-D 'nodes' mesh over every device in the (possibly multi-host)
    cluster. Device order follows jax.devices(), so the mesh axis is
    contiguous per host — node shards stay host-local and the all-reduces
    are hierarchical (ICI within a host, DCN across)."""
    from kube_batch_tpu.parallel.mesh import make_mesh

    return make_mesh(None)
