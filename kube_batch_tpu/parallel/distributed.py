"""Multi-host distributed setup — the DCN/ICI scaling story.

The reference scales out with active/passive HA replicas (leader election,
server.go:106-151); scheduling itself is single-process. Here the *solve*
scales across chips and hosts: the node axis shards over a global
`jax.sharding.Mesh` whose devices may span hosts — XLA/GSPMD inserts the
collectives, which ride ICI within a host slice and DCN across hosts. The
host-side cache/ingest stays on one leader process (elected via
cmd/leader_election.py); follower hosts only contribute devices through
`jax.distributed`.

Per-cycle cross-host traffic is the same O(tasks) per round as the
single-host sharded solve (parallel/mesh.py): budgets and score columns are
node-local, only the per-task winner (value, index) pairs all-reduce.

Usage on each host of the cluster:

    from kube_batch_tpu.parallel.distributed import initialize, global_mesh
    initialize(coordinator="host0:9000", num_processes=4, process_id=rank)
    mesh = global_mesh()          # 1-D 'nodes' mesh over ALL devices
    # leader: sharded_allocate_solve(snap, config, mesh)
"""

from __future__ import annotations

from typing import Optional

import jax

from kube_batch_tpu.parallel.mesh import make_mesh


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """jax.distributed.initialize wrapper. With no arguments, relies on the
    environment (TPU pod auto-configuration); no-op when already
    initialized or single-process."""
    if jax.process_count() > 1:
        return  # already initialized
    if coordinator is None and num_processes is None:
        try:
            jax.distributed.initialize()
        except (RuntimeError, ValueError):
            pass  # single-process / no cluster env — stay local
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh():
    """1-D 'nodes' mesh over every device in the (possibly multi-host)
    cluster. Device order follows jax.devices(), so the mesh axis is
    contiguous per host — node shards stay host-local and the all-reduces
    are hierarchical (ICI within a host, DCN across)."""
    return make_mesh(None)
