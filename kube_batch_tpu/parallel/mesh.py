"""Device-mesh sharding of the allocate solve over ICI.

SURVEY.md §5.7/§5.8: the reference scales its per-cycle problem with
16-worker goroutine fan-outs; the TPU-native analog partitions the **node
axis** across a `jax.sharding.Mesh` (the way a sequence axis is partitioned
in sequence parallelism). Every [N, R] budget tensor and the [T, N]
feasibility/score intermediates shard over the 'nodes' axis; task-axis
tensors replicate. XLA/GSPMD then inserts the collectives: the per-task
argmax over nodes becomes a sharded argmax + all-reduce of (value, index)
pairs, and the post-conflict budget updates stay node-local — the only
cross-chip traffic per round is O(T) "who won", never O(T × N) — riding ICI,
with DCN reserved for host↔cluster-API traffic.

Two implementations share the mesh and the snapshot shardings:

- **shard_map (default)** — parallel/shard_solve.py: the solves run as
  ``shard_map`` bodies with AUTHORED collectives; per-round cross-host
  traffic is the explicit O(tasks) pmax/pmin/psum reductions of the
  winner vectors, auditable via ``collective_stats``.
- **pjit (KB_SHARD_MAP=0)** — the original declarative path: NamedSharding
  on the snapshot pytree and jit's in_shardings/out_shardings, collectives
  compiler-inserted by GSPMD.  Kept as the bit-exactness oracle.

A second mesh dim shards the TASK axis too (KB_TASK_SHARDS=k or
``make_mesh(task_shards=k)``) for when node-axis sharding alone no longer
fits the [T, N] round intermediates in HBM (shard_map path only)."""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kube_batch_tpu.api.snapshot import DeviceSnapshot
from kube_batch_tpu.ops.assignment import AllocateConfig, AllocateResult, allocate_solve
from kube_batch_tpu.ops.eviction import EvictConfig, EvictResult, evict_solve
from kube_batch_tpu.utils import jitstats

NODE_AXIS = "nodes"
TASK_AXIS = "tasks"

# below this padded node-axis size a single chip wins: the per-round
# cross-chip argmax reduction costs more than the sharded [T, N] work saves
SHARD_MIN_NODES = 256

_default_mesh: dict = {}
_bad_task_shards: set = set()  # warn once per bad KB_TASK_SHARDS value


def _env_off(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in (
        "0", "false", "off", "no"
    )


def shard_map_enabled() -> bool:
    """KB_SHARD_MAP=0 selects the pjit oracle path; default is the
    explicit-collective shard_map path."""
    return not _env_off("KB_SHARD_MAP")


def task_shards() -> int:
    """KB_TASK_SHARDS=k splits the mesh into a (tasks=k, nodes=d/k) grid —
    the HBM escape hatch for cycles whose [T, N] round intermediates no
    longer fit when only the node axis shards.  Default 1 (node-only)."""
    try:
        return max(1, int(os.environ.get("KB_TASK_SHARDS", "1")))
    except ValueError:
        return 1


def default_mesh() -> Optional[Mesh]:
    """The production mesh over every visible device — None on single-chip
    parts.  Cached per task-shard count: the device list is fixed for the
    process lifetime, but KB_TASK_SHARDS may select a different grid.  A
    KB_TASK_SHARDS that does not divide the device count falls back to the
    1-D node mesh WITH a warning — it must degrade the grid, never
    silently disable sharding wholesale."""
    ts = task_shards()
    n_dev = len(jax.devices())
    if n_dev <= 1:
        return None
    if ts > 1 and n_dev % ts:
        if ts not in _bad_task_shards:
            _bad_task_shards.add(ts)
            import logging

            logging.getLogger("kube_batch_tpu").warning(
                "KB_TASK_SHARDS=%d does not divide the %d-device count; "
                "falling back to the 1-D node mesh", ts, n_dev,
            )
        ts = 1
    mesh = _default_mesh.get(ts)
    if mesh is None:
        mesh = _default_mesh[ts] = make_mesh(task_shards=ts)
    return mesh


def should_shard(n_nodes_padded: int) -> bool:
    """The production actions' auto-selection gate: a mesh exists and the
    node axis is big enough that sharding beats one chip (the reference's
    16-worker fan-out is always on, scheduler_helper.go:34-64; here the
    analog turns on with the hardware).  KB_SHARD=0 forces the single-chip
    path (the sharded-vs-single equivalence tests' knob)."""
    if _env_off("KB_SHARD"):
        return False
    return n_nodes_padded >= SHARD_MIN_NODES and default_mesh() is not None


def make_mesh(n_devices: Optional[int] = None, task_shards: int = 1) -> Mesh:
    """Mesh over the node axis — 1-D by default; ``task_shards`` > 1 folds
    the device list into a (tasks, nodes) grid whose node axis carries the
    ICI-contiguous fast dim.  Multi-host: pass the global device list
    order; ICI rings form along the axes automatically."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    if task_shards > 1:
        arr = np.asarray(devices).reshape(
            task_shards, len(devices) // task_shards
        )
        return Mesh(arr, (TASK_AXIS, NODE_AXIS))
    return Mesh(np.asarray(devices), (NODE_AXIS,))


@lru_cache(maxsize=8)
def snapshot_shardings(mesh: Mesh) -> DeviceSnapshot:
    """A DeviceSnapshot-shaped pytree of NamedShardings: node-axis arrays
    sharded, everything else replicated. Memoized per mesh — the resident
    feature cache consults it every sharded cycle."""
    node1 = NamedSharding(mesh, P(NODE_AXIS))        # [N]
    node2 = NamedSharding(mesh, P(NODE_AXIS, None))  # [N, R] / [N, W]
    repl = NamedSharding(mesh, P())

    return DeviceSnapshot(
        task_req=repl,
        task_resreq=repl,
        task_job=repl,
        task_prio=repl,
        task_creation=repl,
        task_status=repl,
        task_valid=repl,
        task_pending=repl,
        task_best_effort=repl,
        task_sel_bits=repl,
        task_sel_impossible=repl,
        task_tol_bits=repl,
        task_node=repl,
        task_critical=repl,
        task_needs_host=repl,
        task_aff_idx=repl,
        task_aff_mask=NamedSharding(mesh, P(None, NODE_AXIS)),
        task_pref_idx=repl,
        task_pref_node=NamedSharding(mesh, P(None, NODE_AXIS)),
        task_pref_pod=NamedSharding(mesh, P(None, NODE_AXIS)),
        node_idle=node2,
        node_releasing=node2,
        node_used=node2,
        node_alloc=node2,
        node_valid=node1,
        node_sched=node1,
        node_label_bits=node2,
        node_taint_bits=node2,
        job_min_avail=repl,
        job_ready=repl,
        job_queue=repl,
        job_prio=repl,
        job_creation=repl,
        job_valid=repl,
        job_schedulable=repl,
        job_allocated=repl,
        queue_weight=repl,
        queue_capability=repl,
        queue_alloc=repl,
        queue_request=repl,
        queue_valid=repl,
        total=repl,
        quanta=repl,
    )


# jitted solve per (mesh, config, impl) — a fresh jax.jit wrapper per call
# would retrace and recompile the whole solve every scheduling cycle
_jit_cache: dict = {}


def _impl(impl: Optional[str]) -> str:
    """Resolve the sharded-solve implementation: explicit override, else
    the KB_SHARD_MAP knob (shard_map by default, pjit as the oracle)."""
    if impl is not None:
        return impl
    return "shard_map" if shard_map_enabled() else "pjit"


def allocate_solve_fn(mesh: Mesh, config: AllocateConfig,
                      impl: Optional[str] = None):
    """The memoized jitted allocate solve for (mesh, config, impl) — the
    dispatch below calls it; the jaxpr audit (analysis/jaxpr_audit.py)
    traces BOTH impls abstractly so KBT101-104 cover the sharded variants
    in tier-1."""
    impl = _impl(impl)
    key = (mesh, config, impl)
    fn = _jit_cache.get(key)
    if fn is None:
        if impl == "shard_map":
            from kube_batch_tpu.parallel import shard_solve

            fn = shard_solve.allocate_shard_map(mesh, config)
        else:
            in_shardings = snapshot_shardings(mesh)
            node2 = NamedSharding(mesh, P(NODE_AXIS, None))
            repl = NamedSharding(mesh, P())
            out_shardings = AllocateResult(
                assigned=repl,
                pipelined=repl,
                committed=repl,
                node_idle=node2,
                node_releasing=node2,
                node_used=node2,
                deserved=repl,
                rounds_run=repl,
                topk_exhausted=repl,
                topk_reentries=repl,
            )
            fn = jax.jit(
                partial(_solve, config=config),
                in_shardings=(in_shardings,),
                out_shardings=out_shardings,
            )
        jitstats.register(f"sharded_allocate_solve[{impl}]", fn)
        _jit_cache[key] = fn
    return fn


def sharded_allocate_solve(
    snap: DeviceSnapshot, config: AllocateConfig, mesh: Mesh,
    impl: Optional[str] = None,
) -> AllocateResult:
    """The allocate solve jitted over the mesh. Node-axis inputs/outputs are
    sharded; the assignment vector comes back replicated.  ``impl``
    overrides the KB_SHARD_MAP selection — the guard plane's demotion
    passes ``"pjit"`` here to pin a tripped shard_map path to its oracle."""
    fn = allocate_solve_fn(mesh, config, impl=impl)
    with mesh:
        return fn(snap)


def _solve(snap: DeviceSnapshot, config: AllocateConfig) -> AllocateResult:
    return allocate_solve(snap, config)


def allocate_topk_solve_fn(mesh: Mesh, config: AllocateConfig,
                           impl: Optional[str] = None):
    """The memoized jitted COMPACTED allocate solve for (mesh, config,
    impl) — config.topk > 0 selects the [P, K] candidate-table program
    (ops.assignment.allocate_topk_solve).  The shard_map impl builds
    per-shard candidate lists and merges them with one per-solve gather
    (parallel/shard_solve.allocate_topk_shard_map — zero per-round
    collectives); the pjit impl re-jits the single-device compacted body
    with mesh shardings as the sharded bit-exactness oracle, mirroring the
    full solve's impl split."""
    from kube_batch_tpu.ops.assignment import allocate_topk_solve

    impl = _impl(impl)
    key = (mesh, config, "topk", impl)
    fn = _jit_cache.get(key)
    if fn is None:
        if impl == "shard_map":
            from kube_batch_tpu.parallel import shard_solve

            fn = shard_solve.allocate_topk_shard_map(mesh, config)
        else:
            in_shardings = snapshot_shardings(mesh)
            node2 = NamedSharding(mesh, P(NODE_AXIS, None))
            repl = NamedSharding(mesh, P())
            out_shardings = AllocateResult(
                assigned=repl, pipelined=repl, committed=repl,
                node_idle=node2, node_releasing=node2, node_used=node2,
                deserved=repl, rounds_run=repl,
                topk_exhausted=repl, topk_reentries=repl,
            )
            fn = jax.jit(
                partial(allocate_topk_solve.__wrapped__, config=config),
                in_shardings=(in_shardings, repl),
                out_shardings=out_shardings,
            )
        jitstats.register(f"sharded_allocate_topk_solve[{impl}]", fn)
        _jit_cache[key] = fn
    return fn


def sharded_allocate_topk_solve(
    snap: DeviceSnapshot, pend_rows, config: AllocateConfig, mesh: Mesh,
    impl: Optional[str] = None,
) -> AllocateResult:
    """The compacted allocate solve jitted over the mesh (pending-row
    bucket replicated, node columns sharded, ledgers back node-sharded)."""
    fn = allocate_topk_solve_fn(mesh, config, impl=impl)
    with mesh:
        return fn(snap, pend_rows)


def warm_allocate_solve_fn(mesh: Mesh, config: AllocateConfig, k_min: int,
                           impl: Optional[str] = None):
    """The memoized jitted WARM-STARTED compacted solve for (mesh, config,
    k_min, impl) — the cross-cycle candidate-table carry
    (ops.assignment._warm_allocate_solve).  The shard_map impl contributes
    delta-sized per-shard work (fresh changed-node keys via one psum, the
    invalidated sub-bucket via one all_gather + replicated merge) and
    keeps the round loop collective-free; the pjit impl re-jits the
    single-device warm body with mesh shardings (table + plan replicated)
    as the sharded bit-exactness oracle — the same split as every solve."""
    from kube_batch_tpu.ops.assignment import _warm_allocate_solve

    impl = _impl(impl)
    key = (mesh, config, "warm", k_min, impl)
    fn = _jit_cache.get(key)
    if fn is None:
        if impl == "shard_map":
            from kube_batch_tpu.parallel import shard_solve

            fn = shard_solve.warm_allocate_shard_map(mesh, config, k_min)
        else:
            in_shardings = snapshot_shardings(mesh)
            node2 = NamedSharding(mesh, P(NODE_AXIS, None))
            repl = NamedSharding(mesh, P())
            res_shardings = AllocateResult(
                assigned=repl, pipelined=repl, committed=repl,
                node_idle=node2, node_releasing=node2, node_used=node2,
                deserved=repl, rounds_run=repl,
                topk_exhausted=repl, topk_reentries=repl,
            )
            fn = jax.jit(
                partial(_warm_allocate_solve, config=config, k_min=k_min),
                in_shardings=(in_shardings,) + (repl,) * 9,
                out_shardings=(res_shardings, (repl,) * 4, repl),
            )
        jitstats.register(f"sharded_warm_allocate_solve[{impl}]", fn)
        _jit_cache[key] = fn
    return fn


def sharded_warm_allocate_solve(snap, pend_rows, table, plan,
                                config: AllocateConfig, k_min: int,
                                mesh: Mesh, impl: Optional[str] = None):
    """The warm-started compacted solve over the mesh — same calling
    shape as ops.assignment.warm_allocate_solve, returning
    ``(AllocateResult, table', eroded)``; the refreshed table comes back
    replicated and carries to the next cycle as-is."""
    fn = warm_allocate_solve_fn(mesh, config, k_min, impl=impl)
    t_idx, t_skey, t_hash, t_trunc = table
    row_map, changed, rr, rslots = plan
    with mesh:
        return fn(snap, pend_rows, t_idx, t_skey, t_hash, t_trunc,
                  row_map, changed, rr, rslots)


def sentinel_warm_allocate_solve_fn(mesh: Mesh, config: AllocateConfig,
                                    k_min: int,
                                    impl: Optional[str] = None):
    from kube_batch_tpu.ops.invariants import (
        allocate_invariants,
        eligibility_checksum,
    )

    impl = _impl(impl)
    key = (mesh, config, "sentinel_warm", k_min, impl)
    fn = _jit_cache.get(key)
    if fn is None:
        inner = warm_allocate_solve_fn(mesh, config, k_min, impl=impl)

        def fused(snap, pend_rows, *rest):
            res, table, eroded = inner(snap, pend_rows, *rest)
            verdict, hist = allocate_invariants(snap, res, config)
            return (res, verdict, hist, eligibility_checksum(snap),
                    table, eroded)

        fn = jax.jit(fused)
        jitstats.register(f"sentinel_sharded_warm_allocate_solve[{impl}]",
                          fn)
        _jit_cache[key] = fn
    return fn


def sentinel_sharded_warm_allocate_solve(snap, pend_rows, table, plan,
                                         config, k_min, mesh, impl=None):
    fn = sentinel_warm_allocate_solve_fn(mesh, config, k_min, impl=impl)
    t_idx, t_skey, t_hash, t_trunc = table
    row_map, changed, rr, rslots = plan
    with mesh:
        return fn(snap, pend_rows, t_idx, t_skey, t_hash, t_trunc,
                  row_map, changed, rr, rslots)


def failure_histogram_bucket_fn(mesh: Mesh, impl: Optional[str] = None):
    """Memoized jitted sharded BUCKETED fit-error histogram for `mesh`
    (dispatch + jaxpr-audit entry point) — the [P] pending-bucket variant
    of failure_histogram_fn."""
    from kube_batch_tpu.ops.assignment import failure_histogram_bucket_solve

    impl = _impl(impl)
    key = (mesh, "fail_hist_bucket", impl)
    fn = _jit_cache.get(key)
    if fn is None:
        if impl == "shard_map":
            from kube_batch_tpu.parallel import shard_solve

            fn = shard_solve.failure_histogram_bucket_shard_map(mesh)
        else:
            repl = NamedSharding(mesh, P())
            fn = jax.jit(
                failure_histogram_bucket_solve.__wrapped__,
                in_shardings=(snapshot_shardings(mesh), repl),
                out_shardings=repl,
            )
        jitstats.register(f"sharded_failure_histogram_bucket[{impl}]", fn)
        _jit_cache[key] = fn
    return fn


def sharded_failure_histogram_bucket(snap: DeviceSnapshot, pend_rows,
                                     mesh: Mesh):
    """The lazy fit-error histogram over the mesh, restricted to the [P]
    pending bucket — per-shard [P, N_loc] partials, one psum, scattered
    back to the replicated [T, N_REASONS] result."""
    fn = failure_histogram_bucket_fn(mesh)
    with mesh:
        return fn(snap, pend_rows)


def failure_histogram_fn(mesh: Mesh, impl: Optional[str] = None):
    """Memoized jitted sharded fit-error histogram for `mesh` (dispatch +
    jaxpr-audit entry point)."""
    from kube_batch_tpu.ops.assignment import failure_histogram_solve

    impl = _impl(impl)
    key = (mesh, "fail_hist", impl)
    fn = _jit_cache.get(key)
    if fn is None:
        if impl == "shard_map":
            from kube_batch_tpu.parallel import shard_solve

            fn = shard_solve.failure_histogram_shard_map(mesh)
        else:
            fn = jax.jit(
                failure_histogram_solve.__wrapped__,
                in_shardings=(snapshot_shardings(mesh),),
                out_shardings=NamedSharding(mesh, P()),
            )
        jitstats.register(f"sharded_failure_histogram[{impl}]", fn)
        _jit_cache[key] = fn
    return fn


def sharded_failure_histogram(snap: DeviceSnapshot, mesh: Mesh):
    """The lazy fit-error histogram over the mesh: [T, N]-scale predicate
    masks shard along the node axis, the per-reason node counts reduce
    (an explicit psum on the shard_map path) into the replicated
    [T, N_REASONS] result."""
    fn = failure_histogram_fn(mesh)
    with mesh:
        return fn(snap)


def evict_solve_fn(mesh: Mesh, config: EvictConfig,
                   impl: Optional[str] = None):
    """Memoized jitted sharded eviction solve for (mesh, config, impl)
    (dispatch + jaxpr-audit entry point)."""
    impl = _impl(impl)
    key = (mesh, config, "evict", impl)
    fn = _jit_cache.get(key)
    if fn is None:
        if impl == "shard_map":
            from kube_batch_tpu.parallel import shard_solve

            fn = shard_solve.evict_shard_map(mesh, config)
        else:
            in_shardings = snapshot_shardings(mesh)
            repl = NamedSharding(mesh, P())
            out_shardings = EvictResult(
                claim_node=repl, evicted=repl, victim_claimant=repl
            )
            fn = jax.jit(
                partial(_evict, config=config),
                in_shardings=(in_shardings,),
                out_shardings=out_shardings,
            )
        jitstats.register(f"sharded_evict_solve[{config.mode},{impl}]", fn)
        _jit_cache[key] = fn
    return fn


def sharded_evict_solve(
    snap: DeviceSnapshot, config: EvictConfig, mesh: Mesh,
    impl: Optional[str] = None,
) -> EvictResult:
    """The eviction solve (preempt/reclaim) jitted over the mesh: node-axis
    inputs shard exactly like the allocate solve's; every EvictResult field
    is task-axis, so outputs replicate.  ``impl`` is the guard plane's
    demotion override (``"pjit"`` = the oracle)."""
    fn = evict_solve_fn(mesh, config, impl=impl)
    with mesh:
        return fn(snap)


def _evict(snap: DeviceSnapshot, config: EvictConfig) -> EvictResult:
    return evict_solve(snap, config)


# --------------------------------------------------------------------------
# sentinel-fused sharded solves (guard plane tier 1): the memoized sharded
# solve body plus the ops/invariants tail in ONE jitted program — the
# invariant reductions run on the replicated result vectors and the
# node-sharded ledgers (GSPMD partitions the O(N) cross-checks), and the
# verdict/histogram ride the action's single readback exactly like the
# single-device sentinel programs.
# --------------------------------------------------------------------------


def sentinel_allocate_solve_fn(mesh: Mesh, config: AllocateConfig,
                               impl: Optional[str] = None):
    from kube_batch_tpu.ops.invariants import allocate_invariants

    impl = _impl(impl)
    key = (mesh, config, "sentinel_alloc", impl)
    fn = _jit_cache.get(key)
    if fn is None:
        inner = allocate_solve_fn(mesh, config, impl=impl)

        from kube_batch_tpu.ops.invariants import eligibility_checksum

        def fused(snap):
            res = inner(snap)
            verdict, hist = allocate_invariants(snap, res, config)
            return res, verdict, hist, eligibility_checksum(snap)

        fn = jax.jit(fused)
        jitstats.register(f"sentinel_sharded_allocate_solve[{impl}]", fn)
        _jit_cache[key] = fn
    return fn


def sentinel_sharded_allocate_solve(snap, config, mesh, impl=None):
    fn = sentinel_allocate_solve_fn(mesh, config, impl=impl)
    with mesh:
        return fn(snap)


def sentinel_allocate_topk_solve_fn(mesh: Mesh, config: AllocateConfig,
                                    impl: Optional[str] = None):
    from kube_batch_tpu.ops.invariants import allocate_invariants

    impl = _impl(impl)
    key = (mesh, config, "sentinel_topk", impl)
    fn = _jit_cache.get(key)
    if fn is None:
        inner = allocate_topk_solve_fn(mesh, config, impl=impl)

        from kube_batch_tpu.ops.invariants import eligibility_checksum

        def fused(snap, pend_rows):
            res = inner(snap, pend_rows)
            verdict, hist = allocate_invariants(snap, res, config)
            return res, verdict, hist, eligibility_checksum(snap)

        fn = jax.jit(fused)
        jitstats.register(f"sentinel_sharded_allocate_topk_solve[{impl}]", fn)
        _jit_cache[key] = fn
    return fn


def sentinel_sharded_allocate_topk_solve(snap, pend_rows, config, mesh,
                                         impl=None):
    fn = sentinel_allocate_topk_solve_fn(mesh, config, impl=impl)
    with mesh:
        return fn(snap, pend_rows)


def sentinel_evict_solve_fn(mesh: Mesh, config: EvictConfig,
                            impl: Optional[str] = None):
    from kube_batch_tpu.ops.invariants import evict_invariants

    impl = _impl(impl)
    key = (mesh, config, "sentinel_evict", impl)
    fn = _jit_cache.get(key)
    if fn is None:
        inner = evict_solve_fn(mesh, config, impl=impl)

        from kube_batch_tpu.ops.invariants import eligibility_checksum

        def fused(snap):
            res = inner(snap)
            verdict, hist = evict_invariants(snap, res, config)
            return res, verdict, hist, eligibility_checksum(snap)

        fn = jax.jit(fused)
        jitstats.register(
            f"sentinel_sharded_evict_solve[{config.mode},{impl}]", fn)
        _jit_cache[key] = fn
    return fn


def sentinel_sharded_evict_solve(snap, config, mesh, impl=None):
    fn = sentinel_evict_solve_fn(mesh, config, impl=impl)
    with mesh:
        return fn(snap)


def probe_solve_fn(mesh: Mesh, config: AllocateConfig,
                   evict_config: EvictConfig, with_evictions: bool,
                   impl: Optional[str] = None):
    """Memoized jitted sharded what-if probe (ops/probe.py) for (mesh,
    config, evict_config, with_evictions, impl) — the query plane's
    dispatch on multi-device leases, and a jaxpr-audit entry point.  The
    shard_map impl authors its collectives (parallel/shard_solve.py);
    the pjit impl re-jits the single-device :func:`ops.probe.probe_body`
    with mesh shardings — the bit-exactness oracle, same split as the
    solves."""
    impl = _impl(impl)
    key = (mesh, config, evict_config, with_evictions, "probe", impl)
    fn = _jit_cache.get(key)
    if fn is None:
        if impl == "shard_map":
            from kube_batch_tpu.parallel import shard_solve

            fn = shard_solve.probe_shard_map(
                mesh, config, evict_config, with_evictions
            )
        else:
            from kube_batch_tpu.ops.probe import (
                ProbeBatch,
                ProbeResult,
                probe_body,
            )

            repl = NamedSharding(mesh, P())
            batch_shardings = ProbeBatch(
                *([repl] * len(ProbeBatch._fields)))
            out_shardings = ProbeResult(
                *([repl] * len(ProbeResult._fields)))
            fn = jax.jit(
                partial(probe_body, config=config,
                        evict_config=evict_config,
                        with_evictions=with_evictions),
                in_shardings=(snapshot_shardings(mesh), batch_shardings,
                              repl),
                out_shardings=out_shardings,
            )
        jitstats.register(f"sharded_probe_solve[{impl}]", fn)
        _jit_cache[key] = fn
    return fn


def sharded_probe_solve(snap: DeviceSnapshot, batch, probe_rows, mesh: Mesh,
                        config: AllocateConfig, evict_config: EvictConfig,
                        with_evictions: bool = False):
    """The batched what-if probe over the mesh: node-axis snapshot columns
    stay sharded (the lease's resident placement), the B-gang batch and
    row oracle replicate, every ProbeResult field comes back replicated."""
    fn = probe_solve_fn(mesh, config, evict_config, with_evictions)
    with mesh:
        return fn(snap, batch, probe_rows)


def enqueue_gate_solve_fn(mesh: Mesh):
    """Memoized mesh-replicated enqueue admission scan (the shard_map
    wrapper around ops.admission.gate_scan — zero cross-shard bytes; see
    shard_solve.enqueue_gate_shard_map for why it exists)."""
    key = (mesh, "enqueue_gate")
    fn = _jit_cache.get(key)
    if fn is None:
        from kube_batch_tpu.parallel import shard_solve

        fn = shard_solve.enqueue_gate_shard_map(mesh)
        jitstats.register("sharded_enqueue_gate", fn)
        _jit_cache[key] = fn
    return fn


def dispatch_enqueue_gate(min_res, cand, idle0, quanta, n_nodes_padded: int):
    """The enqueue action's gate dispatch: ride the mesh (replicated
    shard_map) when the cycle's solves shard and the shard_map path is on,
    else the single-device jitted scan.  Verdicts are bit-equal either way
    (both trace ops.admission.gate_scan)."""
    if should_shard(n_nodes_padded) and shard_map_enabled():
        mesh = default_mesh()
        with mesh:
            return enqueue_gate_solve_fn(mesh)(min_res, cand, idle0, quanta)
    from kube_batch_tpu.ops.admission import enqueue_gate_solve

    return enqueue_gate_solve(min_res, cand, idle0, quanta)


def collective_stats(mesh: Mesh, config: Optional[AllocateConfig] = None,
                     snap=None, pend_bucket: Optional[int] = None) -> dict:
    """Traced collective inventory of the shard_map allocate solve on
    `mesh` — the per-round / per-solve cross-shard byte accounting
    (utils/jitstats.collective_inventory) of the program XLA actually
    compiles, at the abstract shapes of ``snap`` (defaults to the audit's
    small shapes).  The bench and the sim report this next to the measured
    round counts, so the O(tasks) comms claim is checked against the real
    traced program, not asserted in a comment.

    With ``config.topk > 0`` and a ``pend_bucket`` size, the COMPACTED
    program is traced instead — its contract is per_round_bytes == 0
    (the candidate merge and the fallback's node-column gathers are all
    per-solve), which the bench and tests assert from these numbers.

    The inventory's nested-loop fields pass through:
    ``per_round_bytes_expanded`` multiplies each per-round site by the
    trip count of any scan nested inside the round loop, and
    ``per_round_has_unbounded_inner_loop`` marks an inner ``while``
    (no static trip count — the expanded total is then a floor).  The
    HBM audit's KBT204 reads the same fields for its byte formulas."""
    import jax.numpy as jnp

    if snap is None:
        from kube_batch_tpu.analysis.jaxpr_audit import abstract_snapshot

        snap = abstract_snapshot()
    config = config or AllocateConfig()
    if config.topk and pend_bucket:
        fn = allocate_topk_solve_fn(mesh, config, impl="shard_map")
        traced = fn.trace(
            snap, jax.ShapeDtypeStruct((pend_bucket,), jnp.int32)
        )
    else:
        fn = allocate_solve_fn(mesh, config, impl="shard_map")
        traced = fn.trace(snap)
    stats = jitstats.collective_inventory(traced.jaxpr)
    stats["mesh"] = {k: int(v) for k, v in dict(mesh.shape).items()}
    stats["task_bucket"] = int(snap.task_req.shape[0])
    stats["node_bucket"] = int(snap.node_idle.shape[0])
    if config.topk and pend_bucket:
        stats["topk"] = int(config.topk)
        stats["pend_bucket"] = int(pend_bucket)
    return stats
