"""Device-mesh sharding of the allocate solve over ICI.

SURVEY.md §5.7/§5.8: the reference scales its per-cycle problem with
16-worker goroutine fan-outs; the TPU-native analog partitions the **node
axis** across a `jax.sharding.Mesh` (the way a sequence axis is partitioned
in sequence parallelism). Every [N, R] budget tensor and the [T, N]
feasibility/score intermediates shard over the 'nodes' axis; task-axis
tensors replicate. XLA/GSPMD then inserts the collectives: the per-task
argmax over nodes becomes a sharded argmax + all-reduce of (value, index)
pairs, and the post-conflict budget updates stay node-local — the only
cross-chip traffic per round is O(T) "who won", never O(T × N) — riding ICI,
with DCN reserved for host↔cluster-API traffic.

This module expresses shardings declaratively via NamedSharding on the
snapshot pytree and jit's in_shardings/out_shardings; no manual collectives —
compiler-inserted, profile-guided (the scaling-book recipe: pick a mesh,
annotate, let XLA insert collectives)."""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kube_batch_tpu.api.snapshot import DeviceSnapshot
from kube_batch_tpu.ops.assignment import AllocateConfig, AllocateResult, allocate_solve
from kube_batch_tpu.ops.eviction import EvictConfig, EvictResult, evict_solve

NODE_AXIS = "nodes"

# below this padded node-axis size a single chip wins: the per-round
# cross-chip argmax reduction costs more than the sharded [T, N] work saves
SHARD_MIN_NODES = 256

_default_mesh = None


def default_mesh() -> Optional[Mesh]:
    """The production mesh over every visible device — None on single-chip
    parts.  Cached: the device list is fixed for the process lifetime."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh() if len(jax.devices()) > 1 else False
    return _default_mesh or None


def should_shard(n_nodes_padded: int) -> bool:
    """The production actions' auto-selection gate: a mesh exists and the
    node axis is big enough that sharding beats one chip (the reference's
    16-worker fan-out is always on, scheduler_helper.go:34-64; here the
    analog turns on with the hardware).  KB_SHARD=0 forces the single-chip
    path (the sharded-vs-single equivalence tests' knob)."""
    if os.environ.get("KB_SHARD", "").strip().lower() in (
        "0", "false", "off", "no"
    ):
        return False
    return n_nodes_padded >= SHARD_MIN_NODES and default_mesh() is not None


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the node axis. Multi-host: pass the global device list
    order; ICI rings form along the axis automatically."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (NODE_AXIS,))


@lru_cache(maxsize=8)
def snapshot_shardings(mesh: Mesh) -> DeviceSnapshot:
    """A DeviceSnapshot-shaped pytree of NamedShardings: node-axis arrays
    sharded, everything else replicated. Memoized per mesh — the resident
    feature cache consults it every sharded cycle."""
    node1 = NamedSharding(mesh, P(NODE_AXIS))        # [N]
    node2 = NamedSharding(mesh, P(NODE_AXIS, None))  # [N, R] / [N, W]
    repl = NamedSharding(mesh, P())

    return DeviceSnapshot(
        task_req=repl,
        task_resreq=repl,
        task_job=repl,
        task_prio=repl,
        task_creation=repl,
        task_status=repl,
        task_valid=repl,
        task_pending=repl,
        task_best_effort=repl,
        task_sel_bits=repl,
        task_sel_impossible=repl,
        task_tol_bits=repl,
        task_node=repl,
        task_critical=repl,
        task_needs_host=repl,
        task_aff_idx=repl,
        task_aff_mask=NamedSharding(mesh, P(None, NODE_AXIS)),
        task_pref_idx=repl,
        task_pref_node=NamedSharding(mesh, P(None, NODE_AXIS)),
        task_pref_pod=NamedSharding(mesh, P(None, NODE_AXIS)),
        node_idle=node2,
        node_releasing=node2,
        node_used=node2,
        node_alloc=node2,
        node_valid=node1,
        node_sched=node1,
        node_label_bits=node2,
        node_taint_bits=node2,
        job_min_avail=repl,
        job_ready=repl,
        job_queue=repl,
        job_prio=repl,
        job_creation=repl,
        job_valid=repl,
        job_schedulable=repl,
        job_allocated=repl,
        queue_weight=repl,
        queue_capability=repl,
        queue_alloc=repl,
        queue_request=repl,
        queue_valid=repl,
        total=repl,
        quanta=repl,
    )


# jitted solve per (mesh, config) — a fresh jax.jit wrapper per call would
# retrace and recompile the whole solve every scheduling cycle
_jit_cache: dict = {}


def allocate_solve_fn(mesh: Mesh, config: AllocateConfig):
    """The memoized jitted allocate solve for (mesh, config) — the dispatch
    below calls it; the jaxpr audit (analysis/jaxpr_audit.py) traces it
    abstractly so KBT101-104 cover the sharded variant in tier-1."""
    key = (mesh, config)
    fn = _jit_cache.get(key)
    if fn is None:
        in_shardings = snapshot_shardings(mesh)
        node2 = NamedSharding(mesh, P(NODE_AXIS, None))
        repl = NamedSharding(mesh, P())
        out_shardings = AllocateResult(
            assigned=repl,
            pipelined=repl,
            committed=repl,
            node_idle=node2,
            node_releasing=node2,
            node_used=node2,
            deserved=repl,
            rounds_run=repl,
        )
        fn = jax.jit(
            partial(_solve, config=config),
            in_shardings=(in_shardings,),
            out_shardings=out_shardings,
        )
        _jit_cache[key] = fn
    return fn


def sharded_allocate_solve(
    snap: DeviceSnapshot, config: AllocateConfig, mesh: Mesh
) -> AllocateResult:
    """The allocate solve jitted over the mesh. Node-axis inputs/outputs are
    sharded; the assignment vector comes back replicated."""
    fn = allocate_solve_fn(mesh, config)
    with mesh:
        return fn(snap)


def _solve(snap: DeviceSnapshot, config: AllocateConfig) -> AllocateResult:
    return allocate_solve(snap, config)


def failure_histogram_fn(mesh: Mesh):
    """Memoized jitted sharded fit-error histogram for `mesh` (dispatch +
    jaxpr-audit entry point)."""
    from kube_batch_tpu.ops.assignment import failure_histogram_solve

    key = (mesh, "fail_hist")
    fn = _jit_cache.get(key)
    if fn is None:
        fn = jax.jit(
            failure_histogram_solve.__wrapped__,
            in_shardings=(snapshot_shardings(mesh),),
            out_shardings=NamedSharding(mesh, P()),
        )
        _jit_cache[key] = fn
    return fn


def sharded_failure_histogram(snap: DeviceSnapshot, mesh: Mesh):
    """The lazy fit-error histogram over the mesh: [T, N]-scale predicate
    masks shard along the node axis, the per-reason node counts all-reduce
    into the replicated [T, N_REASONS] result."""
    fn = failure_histogram_fn(mesh)
    with mesh:
        return fn(snap)


def evict_solve_fn(mesh: Mesh, config: EvictConfig):
    """Memoized jitted sharded eviction solve for (mesh, config) (dispatch
    + jaxpr-audit entry point)."""
    key = (mesh, config, "evict")
    fn = _jit_cache.get(key)
    if fn is None:
        in_shardings = snapshot_shardings(mesh)
        repl = NamedSharding(mesh, P())
        out_shardings = EvictResult(
            claim_node=repl, evicted=repl, victim_claimant=repl
        )
        fn = jax.jit(
            partial(_evict, config=config),
            in_shardings=(in_shardings,),
            out_shardings=out_shardings,
        )
        _jit_cache[key] = fn
    return fn


def sharded_evict_solve(
    snap: DeviceSnapshot, config: EvictConfig, mesh: Mesh
) -> EvictResult:
    """The eviction solve (preempt/reclaim) jitted over the mesh: node-axis
    inputs shard exactly like the allocate solve's; every EvictResult field
    is task-axis, so outputs replicate."""
    fn = evict_solve_fn(mesh, config)
    with mesh:
        return fn(snap)


def _evict(snap: DeviceSnapshot, config: EvictConfig) -> EvictResult:
    return evict_solve(snap, config)
