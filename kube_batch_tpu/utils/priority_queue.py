"""A less-fn parameterized priority queue.

Mirrors the reference's pkg/scheduler/util/priority_queue.go:26-94 (a
container/heap over an api.LessFn). Used by the host-side portions of the
actions (queue/job ordering) exactly like the reference's actions use it.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Callable, Iterable, Optional


class PriorityQueue:
    """Heap ordered by a caller-supplied ``less(a, b)`` function.

    ``less(a, b) == True`` means ``a`` pops before ``b``. Ties break by
    insertion order (stable), matching the deterministic behavior tests rely
    on in the reference's priority_queue_test.go.
    """

    def __init__(self, less: Callable[[Any, Any], bool], items: Iterable[Any] = ()):
        self._less = less
        self._counter = itertools.count()
        self._heap: list = []
        for it in items:
            self.push(it)

    def push(self, item: Any) -> None:
        heapq.heappush(self._heap, _Entry(item, next(self._counter), self._less))

    def pop(self) -> Any:
        return heapq.heappop(self._heap).item

    def peek(self) -> Any:
        return self._heap[0].item

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)

    def __bool__(self) -> bool:  # truthiness = "has items", like Empty() inverted
        return bool(self._heap)


class _Entry:
    __slots__ = ("item", "seq", "less")

    def __init__(self, item: Any, seq: int, less: Callable[[Any, Any], bool]):
        self.item = item
        self.seq = seq
        self.less = less

    def __lt__(self, other: "_Entry") -> bool:
        if self.less(self.item, other.item):
            return True
        if self.less(other.item, self.item):
            return False
        return self.seq < other.seq
