"""Jit compile/retrace accounting for the solver programs.

The cycle-time budget assumes the compiled solves are cache hits after
warmup: the snapshot axes are padded to capacity buckets precisely so a
±10% pod-count wobble maps to the SAME shapes cycle after cycle.  A silent
retrace (shape drift, a fresh lambda in a jit cache key, an axis growing
mid-flight) costs hundreds of ms and hides inside p50s — so the bench and
the tests read these counters instead of guessing.

Every jitted entry point registers itself here; ``total_compiles()`` sums
``_cache_size()`` (the per-function count of distinct traced/compiled
specializations) across them.  A delta of zero between two points proves no
retrace happened in the interval.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

_TRACKED: List[Tuple[str, object]] = []


def register(name: str, fn) -> object:
    """Track a jitted callable (idempotent per (name, fn)); returns fn so it
    can wrap a definition site."""
    for n, f in _TRACKED:
        if n == name and f is fn:
            return fn
    _TRACKED.append((name, fn))
    return fn


def _size(fn) -> int:
    try:
        return int(fn._cache_size())
    except Exception:  # noqa: BLE001 — older jax without the probe
        return 0


def compile_counts() -> Dict[str, int]:
    """{name: compiled-specialization count} for every tracked function."""
    out: Dict[str, int] = {}
    for name, fn in _TRACKED:
        out[name] = out.get(name, 0) + _size(fn)
    return out


def total_compiles() -> int:
    return sum(_size(fn) for _, fn in _TRACKED)
