"""Jit compile/retrace accounting for the solver programs.

The cycle-time budget assumes the compiled solves are cache hits after
warmup: the snapshot axes are padded to capacity buckets precisely so a
±10% pod-count wobble maps to the SAME shapes cycle after cycle.  A silent
retrace (shape drift, a fresh lambda in a jit cache key, an axis growing
mid-flight) costs hundreds of ms and hides inside p50s — so the bench and
the tests read these counters instead of guessing.

Every jitted entry point registers itself here; ``total_compiles()`` sums
``_cache_size()`` (the per-function count of distinct traced/compiled
specializations) across them.  A delta of zero between two points proves no
retrace happened in the interval.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

_TRACKED: List[Tuple[str, object]] = []


def register(name: str, fn) -> object:
    """Track a jitted callable (idempotent per (name, fn)); returns fn so it
    can wrap a definition site."""
    for n, f in _TRACKED:
        if n == name and f is fn:
            return fn
    _TRACKED.append((name, fn))
    return fn


def _size(fn) -> int:
    try:
        return int(fn._cache_size())
    except Exception:  # noqa: BLE001 — older jax without the probe
        return 0


def compile_counts() -> Dict[str, int]:
    """{name: compiled-specialization count} for every tracked function."""
    out: Dict[str, int] = {}
    for name, fn in _TRACKED:
        out[name] = out.get(name, 0) + _size(fn)
    return out


def total_compiles() -> int:
    return sum(_size(fn) for _, fn in _TRACKED)


# --------------------------------------------------------------------------
# collective-bytes inventory (the shard_map comms counter)
# --------------------------------------------------------------------------

#: cross-device communication primitives as they appear in jaxprs
COLLECTIVE_PRIMS = (
    "psum", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
    "reduce_scatter", "psum_scatter",
)
#: jaxpr spellings that alias a canonical collective (jax renamed psum's
#: primitive to ``psum2`` in 0.4.x; report it under the stable name)
_PRIM_ALIASES = {"psum2": "psum"}
_LOOP_PRIMS = ("while", "scan")


def _aval_bytes(var) -> int:
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def collective_inventory(closed_jaxpr, *, detail: bool = False) -> Dict:
    """Walk a traced program (a ClosedJaxpr, e.g. ``fn.trace(...).jaxpr``)
    and account every collective primitive's result bytes, split into
    per-ROUND (inside a while/scan body — paid every bidding round) and
    per-SOLVE (outside the loops — e.g. the one-time node-ledger gather).

    This is the evidence behind the "O(tasks) cross-host bytes per round"
    claim: the numbers come from the program XLA compiles, so a regression
    that smuggles an O(nodes) or O(tasks × nodes) collective into the
    round loop shows up as a bytes jump, not a silent slowdown.  Bytes are
    the collective RESULT sizes — a uniform proxy for payload (an
    all-reduce moves ~result-size per hop; an all_gather's result already
    includes the axis-size factor).

    Nested loops: a collective inside a scan/fori nested WITHIN the round
    loop (the warm refresh's inner merge loops) runs inner-trip-count times
    per round.  ``per_round_bytes`` keeps the historical once-per-site
    count; ``per_round_bytes_expanded`` multiplies each per-round site by
    the product of the scan lengths of the loops strictly inside the
    outermost one.  An inner ``while`` has no static trip count — its sites
    count ×1 in the expanded total and set
    ``per_round_has_unbounded_inner_loop`` so the consumer (KBT204) knows
    the formula is a floor, not a bound.

    With ``detail=True``, each result also carries ``sites``: one record
    per collective equation with its result shape/dtype/bytes, loop depth,
    and inner trip multiplier — the raw material for byte-formula
    extraction."""
    per: Dict[str, Dict[str, Dict[str, int]]] = {
        "per_round": {}, "per_solve": {},
    }
    sites: List[Dict] = []
    expanded = {"per_round": 0}
    unbounded_seen = [False]

    def walk(jaxpr, depth: int, inner_trips: int, unbounded: bool) -> None:
        # depth = enclosing while/scan count; inner_trips = product of the
        # known scan lengths of the enclosing loops EXCLUDING the outermost
        # (per-round means "per iteration of the outermost loop").
        for eqn in jaxpr.eqns:
            prim = _PRIM_ALIASES.get(str(eqn.primitive), str(eqn.primitive))
            if prim in COLLECTIVE_PRIMS:
                in_loop = depth > 0
                bucket = per["per_round" if in_loop else "per_solve"]
                rec = bucket.setdefault(prim, {"count": 0, "bytes": 0})
                rec["count"] += 1
                b = sum(_aval_bytes(v) for v in eqn.outvars)
                rec["bytes"] += b
                if in_loop:
                    expanded["per_round"] += b * inner_trips
                    if unbounded:
                        unbounded_seen[0] = True
                if detail:
                    aval = getattr(eqn.outvars[0], "aval", None)
                    sites.append({
                        "prim": prim,
                        "bytes": b,
                        "shape": tuple(getattr(aval, "shape", ()) or ()),
                        "dtype": str(getattr(aval, "dtype", "?")),
                        "depth": depth,
                        "inner_trips": inner_trips,
                        "unbounded_trips": unbounded,
                    })
            is_loop = prim in _LOOP_PRIMS
            if is_loop and depth >= 1:
                # entering a loop nested inside the round loop: fold its
                # trip count into the per-round multiplier
                length = eqn.params.get("length")
                sub_trips = inner_trips * int(length) if length else inner_trips
                sub_unbounded = unbounded or length is None
            else:
                sub_trips, sub_unbounded = inner_trips, unbounded
            inner_depth = depth + 1 if is_loop else depth
            for param in eqn.params.values():
                vals = param if isinstance(param, (list, tuple)) else [param]
                for sub in vals:
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        walk(inner, inner_depth, sub_trips, sub_unbounded)
                    elif hasattr(sub, "eqns"):
                        walk(sub, inner_depth, sub_trips, sub_unbounded)

    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    walk(jaxpr, 0, 1, False)
    out = {
        "per_round_bytes": sum(
            r["bytes"] for r in per["per_round"].values()
        ),
        "per_solve_bytes": sum(
            r["bytes"] for r in per["per_solve"].values()
        ),
        "per_round_bytes_expanded": expanded["per_round"],
        "per_round_has_unbounded_inner_loop": unbounded_seen[0],
        "ops": per,
    }
    if detail:
        out["sites"] = sites
    return out
