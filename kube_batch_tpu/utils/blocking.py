"""`allow_blocking` — the runtime analog of `# kbt: allow[...]` for the
lockdep blocking-under-lock check (kube_batch_tpu/analysis/lockdep.py).

Lives in utils/ (stdlib-only, no analysis-package imports) because the
RUNTIME core annotates with it — cache/volume.py fences its pv-writes
submit — and pulling the AST lint engine into every scheduler process just
to mark a sound blocking region would be backwards. The lockdep detector
reads the same thread-local, so suppression works whether or not the
detector is installed.
"""

from __future__ import annotations

import contextlib
import threading

# allow_blocking() nesting depth, per thread
_blocking_ok = threading.local()


@contextlib.contextmanager
def allow_blocking(reason: str):
    """Suppress lockdep blocking-under-lock reports for the enclosed region.
    `reason` is mandatory and should say why the block is sound (bounded,
    ordering-fenced, one-time spawn...) — it is what a reviewer greps for,
    exactly like the static `# kbt: allow[...]` annotations."""
    if not reason or not reason.strip():
        raise ValueError("allow_blocking requires a non-empty reason")
    depth = getattr(_blocking_ok, "depth", 0)
    _blocking_ok.depth = depth + 1
    try:
        yield
    finally:
        _blocking_ok.depth = depth


def blocking_allowed() -> bool:
    return getattr(_blocking_ok, "depth", 0) > 0
