"""`allow_blocking` / `allow_nesting` / `allow_unguarded` — the runtime
analogs of `# kbt: allow[...]` for the lockdep checks (kube_batch_tpu/
analysis/lockdep.py): the first fences a sound blocking region, the second
declares a deliberate same-site lock nesting (two instances of one lock
class held at once — per-object locks acquired in a stable aggregate
order), and the third declares a deliberate lock-free access to an
attribute whose tier-D domain lock (analysis/races.py) would otherwise be
enforced by the guarded-access corroborator.

Lives in utils/ (stdlib-only, no analysis-package imports) because the
RUNTIME core annotates with it — cache/volume.py fences its pv-writes
submit — and pulling the AST lint engine into every scheduler process just
to mark a sound blocking region would be backwards. The lockdep detector
reads the same thread-locals, so suppression works whether or not the
detector is installed.
"""

from __future__ import annotations

import contextlib
import threading

# per-thread depth counters: separate switches, one per declaration kind —
# a region sanctioned for same-site nesting is not thereby sanctioned to
# block, and vice versa
_blocking_ok = threading.local()
_nesting_ok = threading.local()
_unguarded_ok = threading.local()


@contextlib.contextmanager
def _declared_region(local: threading.local, kind: str, reason: str):
    """Shared depth-counted region: mandatory reason, reentrant, exception
    safe.  `reason` is what a reviewer greps for, exactly like the static
    `# kbt: allow[...]` annotations."""
    if not reason or not reason.strip():
        raise ValueError(f"{kind} requires a non-empty reason")
    depth = getattr(local, "depth", 0)
    local.depth = depth + 1
    try:
        yield
    finally:
        local.depth = depth


def allow_blocking(reason: str):
    """Suppress lockdep blocking-under-lock reports for the enclosed region.
    The reason should say why the block is sound (bounded, ordering-fenced,
    one-time spawn...)."""
    return _declared_region(_blocking_ok, "allow_blocking", reason)


def blocking_allowed() -> bool:
    return getattr(_blocking_ok, "depth", 0) > 0


def allow_nesting(reason: str):
    """Declare that same-site lock nesting inside this region is deliberate
    — e.g. two per-object locks of one class acquired in a stable aggregate
    order.  Without the declaration the lockdep detector reports same-site
    nesting as an order violation (two instances of one class have no
    defined order, so the nesting IS an undeclared ordering claim).  The
    reason should name the order invariant that makes the nesting sound."""
    return _declared_region(_nesting_ok, "allow_nesting", reason)


def nesting_allowed() -> bool:
    return getattr(_nesting_ok, "depth", 0) > 0


def allow_unguarded(reason: str):
    """Declare that lock-free access to domain-guarded attributes inside
    this region is deliberate — the runtime counterpart of a static
    `# kbt: allow[KBT301]` annotation, consumed by the guarded-access
    corroborator (analysis/lockdep.install_guarded_access).  The reason
    should say why the unlocked access cannot tear (GIL-atomic single op,
    documented stale-tolerant hint, cycle-confined structure...)."""
    return _declared_region(_unguarded_ok, "allow_unguarded", reason)


def unguarded_allowed() -> bool:
    return getattr(_unguarded_ok, "depth", 0) > 0
