"""Invariant assertions with an opt-out env switch.

Mirrors the reference's pkg/scheduler/util/assert/assert.go:11-36: invariant
violations panic by default, but setting PANIC_ON_ERROR=false downgrades them
to logged warnings so a production loop can limp along and self-correct on the
next scheduling cycle.
"""

from __future__ import annotations

import logging
import os
import traceback

logger = logging.getLogger("kube_batch_tpu")

_ENV_KEY = "PANIC_ON_ERROR"


def _panic_enabled() -> bool:
    return os.environ.get(_ENV_KEY, "true").lower() != "false"


class InvariantError(AssertionError):
    """Raised when a scheduler invariant (e.g. resource underflow) is broken."""


def graft_assert(condition: bool, message: str = "invariant violated") -> None:
    """Assert a scheduler invariant (assert.go:25-36).

    Raises InvariantError unless env PANIC_ON_ERROR=false, in which case the
    violation (with stack) is logged and execution continues.
    """
    if condition:
        return
    if _panic_enabled():
        raise InvariantError(message)
    logger.error("invariant violated: %s\n%s", message, "".join(traceback.format_stack()))
