from kube_batch_tpu.utils.assertions import graft_assert
from kube_batch_tpu.utils.priority_queue import PriorityQueue

__all__ = ["graft_assert", "PriorityQueue"]
