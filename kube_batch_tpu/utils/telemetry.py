"""Telemetry clock seam — the ONE sanctioned wall-clock read for latency
metrics inside the clock-seamed paths (scheduler / actions / framework).

KBT001 (kube_batch_tpu/analysis) bans raw `time.*` reads in those paths
because the virtual-time simulator injects its own clock and a stray
wall-clock read silently breaks replay determinism. Latency telemetry is
the deliberate exception: it measures how long the real compute took, never
scenario time, so it must NOT follow the injected clock. Routing every such
read through this module keeps the exception greppable to a single import —
`grep -rn 'telemetry.perf_counter'` is the complete audit of wall-clock
telemetry in the scheduling core. Anything else that needs time goes
through the injected clock (`Scheduler.clock`, sim `VirtualClock`) or
carries a per-line `# kbt: allow[KBT001] reason` annotation.
"""

import time

#: wall-clock monotonic high-resolution counter for latency spans only
perf_counter = time.perf_counter
