"""Flight recorder — the black box for the scheduling cycle.

A bounded ring (``KB_TRACE_RING``, default 256 cycles) of complete
per-cycle trace trees from the span recorder (obs/trace.py).  On an
anomaly — a guard-plane trip, a cycle-budget shed, an arrival→decision
SLO breach, a duplicate bind — the recorder snapshots the N cycles BEFORE
the trigger, arms a capture of the N cycles AFTER it, and publishes the
whole window as a self-contained dump directory:

    <dir>/flight-<reason>-<serial>/
        trace.json   — Chrome trace-event JSON (chrome://tracing/Perfetto
                       render the pipelined overlap directly)
        meta.json    — trigger reason/detail, window bounds, knobs

The write uses the guard-bundle idiom (build in a temp sibling,
``os.replace`` into place) so a crash mid-dump never leaves a half
capture.  Dump directory resolution: ``KB_TRACE_DIR``, else
``<KB_GUARD_DIR>/flight`` when the guard bundle dir is configured (trip
dumps land NEXT to the guard bundle for the same incident), else
``flight-recorder``.  ``KB_TRACE_POST`` (default 8) sets N — how many
post-trigger cycles each dump waits for before publishing.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
from collections import deque
from typing import Dict, List, Optional

from kube_batch_tpu import metrics
from kube_batch_tpu.envutil import env_int

logger = logging.getLogger("kube_batch_tpu")

_KNOBS = (
    "KB_TRACE", "KB_TRACE_RING", "KB_TRACE_POST", "KB_TRACE_SLO_MS",
    "KB_PIPELINE", "KB_TOPK", "KB_SHARD_MAP", "KB_GUARD", "JAX_PLATFORMS",
)

#: in-memory bound on the trigger log (dumps on disk are the durable record)
MAX_TRIGGER_LOG = 64


def flight_dir() -> str:
    explicit = os.environ.get("KB_TRACE_DIR", "").strip()
    if explicit:
        return explicit
    guard = os.environ.get("KB_GUARD_DIR", "").strip()
    if guard:
        return os.path.join(guard, "flight")
    return "flight-recorder"


class FlightRecorder:
    def __init__(self, ring: Optional[int] = None,
                 directory: Optional[str] = None,
                 post_cycles: Optional[int] = None):
        self.ring_cap = ring if ring is not None else max(
            2, env_int("KB_TRACE_RING", 256)
        )
        self.directory = directory  # None → flight_dir() at dump time
        self.post_cycles = (
            post_cycles if post_cycles is not None
            else max(0, env_int("KB_TRACE_POST", 8))
        )
        # set False by a disabled Tracer: with no record_cycle feed, an
        # armed capture could never settle — trigger() then no-ops
        self.enabled = True
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=self.ring_cap)
        # armed captures: trigger fired, waiting out their post window
        self._armed: List[Dict] = []
        self.cycles_recorded = 0
        self.triggers: deque = deque(maxlen=MAX_TRIGGER_LOG)
        self.dumps: List[str] = []
        self._serial = 0

    @classmethod
    def from_env(cls) -> "FlightRecorder":
        return cls()

    # ------------------------------------------------------------------
    def record_cycle(self, record) -> None:
        """Ring-append one finalized cycle record; settle armed captures
        whose post-trigger window completed (file I/O OUTSIDE the lock)."""
        due: List[Dict] = []
        with self._mu:
            self._ring.append(record)
            self.cycles_recorded += 1
            for armed in self._armed:
                armed["post"].append(record)
                if len(armed["post"]) >= self.post_cycles:
                    due.append(armed)
            if due:
                self._armed = [a for a in self._armed if a not in due]
        for armed in due:
            self._publish(armed)

    def trigger(self, reason: str, detail: str = "") -> None:
        """One anomaly: snapshot the pre-trigger ring, arm the
        post-trigger capture.  With ``post_cycles == 0`` (or an idle
        process that never cycles again) the dump publishes immediately.

        No-ops when tracing is disabled (nothing feeds the ring, so a
        capture could never settle), and COALESCES repeat triggers: while
        a capture for ``reason`` is still armed, a new trigger of the same
        reason only logs — a sustained SLO breach or a trip storm must not
        arm one capture (each holding a full ring snapshot) per event."""
        if not self.enabled:
            return
        with self._mu:
            self.triggers.append({
                "reason": reason, "detail": detail,
                "cycle": self.cycles_recorded,
            })
            if any(a["reason"] == reason for a in self._armed):
                return  # coalesced into the already-armed capture
            armed = {
                "reason": reason,
                "detail": detail,
                "pre": list(self._ring),
                "post": [],
                "trigger_cycle": self.cycles_recorded,
            }
            if self.post_cycles > 0:
                self._armed.append(armed)
                armed = None
        if armed is not None:
            self._publish(armed)

    def flush(self) -> List[str]:
        """Publish every still-armed capture with whatever post-trigger
        cycles arrived (shutdown / end-of-run path: the sim and the smoke
        call this so a trigger near the end of a run still dumps)."""
        with self._mu:
            armed, self._armed = self._armed, []
        out = []
        for a in armed:
            path = self._publish(a)
            if path:
                out.append(path)
        return out

    # ------------------------------------------------------------------
    def _publish(self, armed: Dict) -> Optional[str]:
        from kube_batch_tpu.obs.trace import chrome_trace

        records = armed["pre"] + armed["post"]
        if not records:
            logger.warning("flight dump for %s skipped: empty ring",
                           armed["reason"])
            return None
        root = self.directory or flight_dir()
        try:
            os.makedirs(root, exist_ok=True)
            doc = chrome_trace(records)
            meta = {
                "schema": 1,
                "reason": armed["reason"],
                "detail": armed["detail"],
                "trigger_cycle": armed["trigger_cycle"],
                "cycles_before": len(armed["pre"]),
                "cycles_after": len(armed["post"]),
                "cycle_ids": [r.cycle for r in records],
                "knobs": {k: os.environ.get(k, "") for k in _KNOBS},
                "tree": [r.to_dict() for r in records],
            }
            # atomic publish: whole dump in a temp sibling, one rename —
            # the guard-bundle idiom, so a crash never leaves a half dump
            tmp = tempfile.mkdtemp(dir=root, prefix=".tmp-flight-")
            try:
                with open(os.path.join(tmp, "trace.json"), "w") as f:
                    json.dump(doc, f)
                with open(os.path.join(tmp, "meta.json"), "w") as f:
                    json.dump(meta, f, indent=2, sort_keys=True)
                while True:
                    final = os.path.join(
                        root, f"flight-{armed['reason']}-{self._serial:04d}"
                    )
                    if not os.path.exists(final):
                        try:
                            os.replace(tmp, final)
                            break
                        except OSError:
                            pass  # lost a concurrent-dump race — next serial
                    self._serial += 1
                    if self._serial > 9999:
                        raise OSError("flight recorder directory full")
            except BaseException:
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
                raise
        except Exception:  # noqa: BLE001 — diagnostics only, never the cycle
            logger.exception("flight recorder dump failed")
            return None
        with self._mu:
            self.dumps.append(final)
        metrics.register_flight_dump(armed["reason"])
        logger.warning("flight recorder dump written: %s", final)
        return final

    # ------------------------------------------------------------------
    def last_record(self):
        with self._mu:
            return self._ring[-1] if self._ring else None

    def records(self) -> list:
        with self._mu:
            return list(self._ring)

    def stats(self) -> Dict:
        with self._mu:
            return {
                "capacity": self.ring_cap,
                "cycles_recorded": self.cycles_recorded,
                "cycles_resident": len(self._ring),
                "post_cycles": self.post_cycles,
                "armed": len(self._armed),
                "triggers": list(self.triggers),
                "dumps": list(self.dumps),
            }
