"""Cycle tracing plane — structured spans over the pipelined scheduling
cycle (the Dapper span model, Sigelman et al. 2010, sized for one process).

Every stage of the staged cycle — ingest drain, delta session open, solve
dispatch, device wait, host replay, status derive, the overlapped
writeback — runs inside a context-manager :class:`Span`; per-action and
per-plugin child spans nest under them through a per-thread stack.  Wall
time is stamped through the ONE sanctioned seam (``utils.telemetry``;
KBT001's deliberate exception), virtual time through the injected clock
(the sim's ``VirtualClock``), so a traced sim run attributes stages on the
same clock its report uses.  Device work is attributed via
``utils/jitstats``: a :meth:`Tracer.device_span` samples the jit
compile-specialization count and the resident-scatter counters at entry
and exit, so a retrace or an unexpected full re-upload is annotated onto
the exact span that paid it (``compiles``/``retrace``/scatter deltas), and
sharded dispatch spans can carry the traced collective-bytes inventory
(``KB_TRACE_COLLECTIVES=1`` opt-in — the trace itself is a one-off
program lowering, kept off the default path so the zero-retrace counters
benches assert stay untouched).

Complete per-cycle trace trees land in the flight recorder's ring
(:mod:`kube_batch_tpu.obs.recorder`) and export as Chrome trace-event
JSON (``chrome_trace``), so ``chrome://tracing`` / Perfetto render the
pipelined overlap directly — the writeback span rides its own thread
track and visibly overlaps the next cycle's compute.

Tracing is INERT by construction: spans only read clocks and counters,
never scheduling state — trace-on vs trace-off cycle decisions are
bit-identical (tests/test_trace.py pins this over randomized churn).
``KB_TRACE=0`` additionally disables retention (ring, attrs, device
sampling, dumps); spans still stamp their own wall time either way, so
the latency metrics they feed (action/plugin/stage histograms) never
change meaning with the knob.

KBT014 (kube_batch_tpu/analysis) enforces the discipline: in the
clock-seamed paths spans are created only via these context managers, and
span bodies read no raw ``time.*`` and no ad-hoc ``telemetry.perf_counter``
pairs — the span IS the measurement; metrics feed from ``Span.dur_us``.
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Dict, List, Optional

from kube_batch_tpu import metrics
from kube_batch_tpu.envutil import env_flag
from kube_batch_tpu.utils import telemetry

import time as _time  # identity sentinel only: `clock is _time` ⇒ no vt


#: root spans per implicit record before it rolls into the ring — callers
#: that drive open/close directly (bench one_cycle, tests) never call
#: begin_cycle, and an unbounded current record would grow forever
IMPLICIT_ROLL = 512


class Span:
    """One traced region.  Created ONLY via the :class:`Tracer` context
    managers (rule KBT014); re-entrant use of a single instance is not
    supported — every ``span()`` call makes a fresh one."""

    __slots__ = ("name", "t0", "t1", "vt0", "vt1", "tid", "attrs",
                 "children", "_tracer", "_record", "_cols", "_c0", "_sc0")

    def __init__(self, tracer: "Tracer", name: str,
                 record: Optional["CycleRecord"] = None,
                 cols=None, attrs: Optional[Dict] = None):
        self.name = name
        self.t0 = self.t1 = 0.0
        self.vt0 = self.vt1 = None
        self.tid = 0
        self.attrs = attrs
        self.children: List["Span"] = []
        self._tracer = tracer
        self._record = record  # explicit target (the writeback worker)
        self._cols = cols
        self._c0 = self._sc0 = None

    # -- timing -----------------------------------------------------------
    @property
    def dur_ms(self) -> float:
        return (self.t1 - self.t0) * 1e3

    @property
    def dur_us(self) -> float:
        return (self.t1 - self.t0) * 1e6

    def set(self, **attrs) -> None:
        """Annotate the span (no-op when retention is disabled so the
        disabled tracer stays allocation-free on the attr path)."""
        if not self._tracer.enabled:
            return
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)

    # -- context manager --------------------------------------------------
    def __enter__(self) -> "Span":
        tracer = self._tracer
        self.tid = threading.get_ident()
        stack = tracer._stack()
        stack.append(self)
        # device-attribution sampling happens OUTSIDE the stamped window so
        # the counter reads never inflate the span's own duration — and
        # inside a guard: attribution must never hurt a cycle, and a probe
        # that raised AFTER the stack push would leak the entry and corrupt
        # this thread's nesting for good
        if tracer.enabled and self._cols is not None:
            try:
                from kube_batch_tpu.utils import jitstats

                self._c0 = jitstats.total_compiles()
                self._sc0 = _scatter_totals(self._cols)
            except Exception:  # noqa: BLE001
                self._c0 = self._sc0 = None
        clock = tracer.clock
        if clock is not None:
            self.vt0 = clock.monotonic()
        self.t0 = telemetry.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.t1 = telemetry.perf_counter()
        tracer = self._tracer
        try:
            clock = tracer.clock
            if clock is not None:
                self.vt1 = clock.monotonic()
            if tracer.enabled and self._c0 is not None:
                from kube_batch_tpu.utils import jitstats

                compiles = jitstats.total_compiles() - self._c0
                if compiles:
                    # a retrace annotated onto the OWNING span — the signal
                    # the flat jit counters could never localize
                    self.set(compiles=compiles, retrace=True)
                sc = _scatter_totals(self._cols)
                delta = {k: sc[k] - self._sc0.get(k, 0)
                         for k in sc if sc[k] != self._sc0.get(k, 0)}
                if delta:
                    self.set(resident=delta)
            if exc_type is not None:
                self.set(error=exc_type.__name__)
        except Exception:  # noqa: BLE001 — attribution only; the stack
            pass           # unwind below must ALWAYS run
        finally:
            stack = tracer._stack()
            stack.pop()
            if stack and self._record is None:
                if tracer.enabled:
                    stack[-1].children.append(self)
                    tracer._count_span(self)
            else:
                tracer._close_root(self)
        return False

    # -- export -----------------------------------------------------------
    def to_dict(self) -> Dict:
        d: Dict = {"name": self.name, "dur_ms": round(self.dur_ms, 4)}
        if self.vt0 is not None:
            d["vt0"] = round(self.vt0, 6)
            if self.vt1 is not None:
                d["vt_dur"] = round(self.vt1 - self.vt0, 6)
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


def _scatter_totals(cols) -> Dict[str, int]:
    """Flattened per-path resident-cache counters ({path.counter: n}) —
    the delta between a device span's entry and exit attributes scatter /
    full-upload traffic to the owning dispatch."""
    out: Dict[str, int] = {}
    try:
        for path, c in cols.resident_counters().items():
            for k, v in c.items():
                out[f"{path}.{k}"] = int(v)
    except Exception:  # noqa: BLE001 — attribution must never hurt a cycle
        pass
    return out


class CycleRecord:
    """One cycle's complete trace tree.  Root spans are appended by the
    cycle thread; the overlapped writeback span arrives from its worker
    thread AFTER the record was finalized into the ring — appends are
    guarded by the tracer's lock."""

    __slots__ = ("cycle", "reason", "t0", "t1", "vt0", "vt1", "spans",
                 "attrs", "closed")

    def __init__(self, cycle: int, reason: str, t0: float,
                 vt0: Optional[float]):
        self.cycle = cycle
        self.reason = reason
        self.t0 = t0
        self.t1: Optional[float] = None
        self.vt0 = vt0
        self.vt1: Optional[float] = None
        self.spans: List[Span] = []
        self.attrs: Dict = {}
        self.closed = False

    def to_dict(self) -> Dict:
        d = {
            "cycle": self.cycle,
            "reason": self.reason,
            "dur_ms": (round((self.t1 - self.t0) * 1e3, 4)
                       if self.t1 is not None else None),
            "spans": [s.to_dict() for s in self.spans],
        }
        if self.vt0 is not None:
            d["vt0"] = round(self.vt0, 6)
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class Tracer:
    """The per-cache span recorder.  One instance per SchedulerCache
    (``tracer_of``); the Scheduler re-points ``clock`` at its injected
    clock so virtual-time stamps follow the sim."""

    def __init__(self, clock=None, recorder=None, enabled: Optional[bool] = None):
        self.enabled = (
            enabled if enabled is not None else env_flag("KB_TRACE", True)
        )
        # vt stamps only for a real injected clock — the wall-clock default
        # would duplicate t0/t1 into the vt fields
        self.clock = None if clock is None or clock is _time else clock
        self.recorder = recorder
        if recorder is not None:
            # a disabled tracer never feeds the ring, so the recorder must
            # not ARM captures either — an armed window that can never
            # settle (record_cycle is the settle path) would accumulate
            # forever on a long-running KB_TRACE=0 server
            recorder.enabled = self.enabled
        self.collectives = env_flag("KB_TRACE_COLLECTIVES", False)
        # arrival→decision SLO (ms) that arms a flight dump; 0 = off
        try:
            self.slo_ms = float(os.environ.get("KB_TRACE_SLO_MS", "0") or 0)
        except ValueError:
            self.slo_ms = 0.0
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._seq = itertools.count()
        self.current: Optional[CycleRecord] = None
        # seed-stable longitudinal stats (the sim report's section)
        self.cycles_total = 0
        self.spans_total = 0
        self.span_counts: Dict[str, int] = {}
        self.retraces_attributed = 0
        self._collective_cache: Dict = {}

    # -- thread-local span stack -----------------------------------------
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    # -- cycle bracket ----------------------------------------------------
    def begin_cycle(self, reason: str = "tick") -> CycleRecord:
        """Open a new cycle record (finalizing any implicit predecessor);
        returns the record so the pipelined caller can hand it to the
        writeback worker."""
        vt0 = self.clock.monotonic() if self.clock is not None else None
        rec = CycleRecord(next(self._seq), reason,
                          telemetry.perf_counter(), vt0)
        with self._mu:
            prev, self.current = self.current, rec
        if prev is not None:
            self._finalize(prev)
        return rec

    def end_cycle(self) -> None:
        with self._mu:
            rec, self.current = self.current, None
        if rec is not None:
            self._finalize(rec)

    def _finalize(self, rec: CycleRecord) -> None:
        rec.t1 = telemetry.perf_counter()
        if self.clock is not None:
            rec.vt1 = self.clock.monotonic()
        rec.closed = True
        with self._mu:
            self.cycles_total += 1
        recorder = self.recorder
        if recorder is not None and self.enabled:
            recorder.record_cycle(rec)

    def _count_span(self, span: Span) -> None:
        with self._mu:
            self.spans_total += 1
            self.span_counts[span.name] = (
                self.span_counts.get(span.name, 0) + 1
            )
            if span.attrs and span.attrs.get("retrace"):
                self.retraces_attributed += span.attrs.get("compiles", 1)

    def _close_root(self, span: Span) -> None:
        """A span finished with no parent on its thread: attach it to its
        record (explicit for writeback spans, else the current cycle) and
        feed the per-stage latency surface.  The histogram observes even
        with KB_TRACE=0 — the knob disables RETENTION (ring, dumps, device
        attribution), never the latency metrics spans feed (the same
        contract as the action/plugin histograms reading sp.dur_us)."""
        metrics.observe_stage_latency(span.name, span.dur_ms)
        if self.enabled:
            self._count_span(span)
            with self._mu:
                rec = span._record
                if rec is None:
                    rec = self.current
                    if rec is None:
                        # direct-driven flows (bench one_cycle, tests) never
                        # bracket cycles — collect under an implicit record
                        rec = self.current = CycleRecord(
                            next(self._seq), "implicit", span.t0, span.vt0
                        )
                rec.spans.append(span)
                roll = (rec is self.current
                        and rec.reason == "implicit"
                        and len(rec.spans) >= IMPLICIT_ROLL)
                if roll:
                    self.current = None
            if roll:
                self._finalize(rec)

    # -- span factories (rule KBT014: THE sanctioned constructors) --------
    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs=attrs or None)

    def device_span(self, name: str, cols=None, **attrs) -> Span:
        """A span that attributes device work: jit compile delta (retraces
        land on the owning span) and resident scatter/upload deltas; the
        dispatching action additionally calls :meth:`annotate_collectives`
        on sharded dispatches."""
        return Span(self, name, cols=cols if self.enabled else None,
                    attrs=attrs or None)

    def cycle_span(self, name: str, record: Optional[CycleRecord],
                   **attrs) -> Span:
        """A root span explicitly targeted at ``record`` — the overlapped
        writeback stage runs on its own worker thread after its cycle's
        record was already finalized into the ring."""
        return Span(self, name, record=record, attrs=attrs or None)

    # -- cycle annotations -------------------------------------------------
    def note_cycle_attr(self, key: str, value) -> None:
        if not self.enabled:
            return
        with self._mu:
            rec = self.current
            if rec is not None:
                rec.attrs[key] = value

    def note_decision_latencies(self, ms_values) -> None:
        """Stamp this cycle's arrival→decision samples onto the trace tree
        (the exact values the histogram/sink observe — test_trace pins the
        equality) and arm a flight dump on an SLO breach."""
        if not ms_values or not self.enabled:
            return
        with self._mu:
            rec = self.current
            if rec is not None:
                rec.attrs.setdefault("decision_lat_ms", []).extend(
                    round(v, 3) for v in ms_values
                )
        if self.slo_ms > 0 and self.recorder is not None:
            worst = max(ms_values)
            if worst > self.slo_ms:
                self.recorder.trigger(
                    "slo_breach",
                    detail=f"arrival→decision {worst:.1f}ms > "
                           f"KB_TRACE_SLO_MS={self.slo_ms:g}",
                )

    def anomaly(self, reason: str, detail: str = "") -> None:
        """Route a non-guard anomaly (budget shed, duplicate bind) to the
        flight recorder."""
        if self.recorder is not None and self.enabled:
            self.recorder.trigger(reason, detail=detail)

    # -- surfaces ---------------------------------------------------------
    def last_cycle(self) -> Optional[Dict]:
        recorder = self.recorder
        if recorder is None:
            return None
        with self._mu:
            rec = recorder.last_record()
        return rec.to_dict() if rec is not None else None

    def state(self) -> Dict:
        with self._mu:
            out = {
                "enabled": self.enabled,
                "cycles_traced": self.cycles_total,
                "spans_total": self.spans_total,
                "span_counts": dict(self.span_counts),
                "retraces_attributed": self.retraces_attributed,
            }
        if self.recorder is not None:
            out["ring"] = self.recorder.stats()
        out["last_cycle"] = self.last_cycle()
        return out

    def stage_attribution(self) -> Dict:
        """The seed-stable longitudinal summary for the sim report: span
        counts per stage plus the attributed retrace total — everything
        here is a function of the event stream, not the host's wall
        clock."""
        with self._mu:
            return {
                "cycles_traced": self.cycles_total,
                "spans_total": self.spans_total,
                "stages": dict(sorted(self.span_counts.items())),
                "retraces_attributed": self.retraces_attributed,
            }

    # -- sharded collective attribution (opt-in, memoized) ----------------
    def annotate_collectives(self, span: Span, config, snap,
                             pend_rows=None) -> None:
        """Attach the traced per-round/per-solve collective result bytes
        (``utils/jitstats.collective_inventory``) to a sharded dispatch
        span.  Opt-in (``KB_TRACE_COLLECTIVES=1``) and memoized per (mesh,
        config, shapes): the one-off program trace this needs must not run
        on the default path, where the benches' zero-retrace counters are
        part of the acceptance evidence."""
        if not (self.enabled and self.collectives):
            return
        try:
            from kube_batch_tpu.parallel.mesh import (
                default_mesh,
                shard_map_enabled,
            )

            if not shard_map_enabled():
                return
            mesh = default_mesh()
            if mesh is None:
                return
            T = int(snap.task_req.shape[0])
            N = int(snap.node_idle.shape[0])
            pend = int(pend_rows.shape[0]) if pend_rows is not None else None
            key = (id(mesh), config, T, N, pend)
            hash(key)
            if key not in self._collective_cache:
                from kube_batch_tpu.analysis.jaxpr_audit import (
                    abstract_snapshot,
                )
                from kube_batch_tpu.parallel.mesh import collective_stats

                stats = collective_stats(
                    mesh, config=config, snap=abstract_snapshot(T=T, N=N),
                    pend_bucket=pend,
                )
                self._collective_cache[key] = {
                    "per_round_bytes": stats["per_round_bytes"],
                    "per_solve_bytes": stats["per_solve_bytes"],
                }
            out = self._collective_cache[key]
        except Exception:  # noqa: BLE001 — attribution only
            return
        if out:
            span.set(collective_bytes=out)


# --------------------------------------------------------------------------
# per-cache attach (the guard_of idiom)
# --------------------------------------------------------------------------

_ATTACH_LOCK = threading.Lock()


def tracer_of(cache, clock=None) -> Tracer:
    """THE per-cache tracer accessor: the scheduler, the actions, and the
    framework all reach tracing through here, so one cache has exactly one
    span plane and one flight-recorder ring.  ``clock`` (the Scheduler's
    injected clock) re-points virtual-time stamping on first attach."""
    tr = getattr(cache, "tracer", None)
    if tr is None:
        with _ATTACH_LOCK:
            tr = getattr(cache, "tracer", None)
            if tr is None:
                from kube_batch_tpu.obs.recorder import FlightRecorder

                rec = FlightRecorder.from_env()
                tr = Tracer(clock=clock, recorder=rec)
                cache.flight_recorder = rec
                cache.tracer = tr
    if clock is not None and clock is not _time and tr.clock is None:
        tr.clock = clock
    return tr


# --------------------------------------------------------------------------
# Chrome trace-event export + structural validation
# --------------------------------------------------------------------------


def chrome_trace(records) -> Dict:
    """Render cycle records as a Chrome trace-event document (`ph: "X"`
    complete events, µs timestamps) — load in ``chrome://tracing`` or
    Perfetto.  Thread ids are preserved, so the writeback stage rides its
    own track and the pipelined overlap is visible as spans of cycle N's
    writeback under cycle N+1's compute."""
    events: List[Dict] = []
    tid_names: Dict[int, str] = {}

    def emit(span: Span, cycle: int, depth: int) -> None:
        events.append({
            "name": span.name,
            "ph": "X",
            "ts": span.t0 * 1e6,
            "dur": max(span.t1 - span.t0, 0.0) * 1e6,
            "pid": 1,
            "tid": span.tid,
            "args": dict(span.attrs or {}, cycle=cycle, depth=depth),
        })
        if "writeback" in span.name:
            tid_names.setdefault(span.tid, "writeback")
        else:
            tid_names.setdefault(span.tid, "cycle")
        for child in span.children:
            emit(child, cycle, depth + 1)

    for rec in records:
        for span in rec.spans:
            emit(span, rec.cycle, 0)
    meta = [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
         "args": {"name": name}}
        for tid, name in sorted(tid_names.items())
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def validate_chrome_trace(doc: Dict) -> List[str]:
    """Structural validation of an exported trace: every complete event
    carries a non-negative duration, per-thread events are properly nested
    (a deeper span lies inside its ancestor's bounds — balanced brackets),
    and timestamps are finite/monotonic per (thread, depth) stream.
    Returns the violations (empty = valid); the trace smoke and the tests
    gate on it."""
    errs: List[str] = []
    events = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    if not events:
        return ["no complete (ph=X) events"]
    by_tid: Dict[int, List[Dict]] = {}
    for e in events:
        if not isinstance(e.get("ts"), (int, float)) or e["ts"] != e["ts"]:
            errs.append(f"non-numeric ts on {e.get('name')}")
            continue
        if e.get("dur", -1) < 0:
            errs.append(f"negative dur on {e.get('name')}")
        by_tid.setdefault(e["tid"], []).append(e)
    for tid, evs in by_tid.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[Dict] = []  # enclosing spans
        for e in evs:
            while stack and e["ts"] >= stack[-1]["ts"] + stack[-1]["dur"] - 1e-3:
                stack.pop()
            if stack:
                outer = stack[-1]
                if e["ts"] + e["dur"] > outer["ts"] + outer["dur"] + 1e-3:
                    errs.append(
                        f"unbalanced nesting on tid {tid}: "
                        f"{e['name']} ends after its enclosing "
                        f"{outer['name']}"
                    )
            stack.append(e)
    return errs
