"""Guard trip-rate SLO alerting (the ROADMAP standing item).

The guard plane (kube_batch_tpu/guard) counts integrity trips per fast
path and per action; this evaluator turns those series into ALERTS: a
path (or the aggregate) whose trip count within the last
``KB_ALERT_WINDOW`` cycles reaches ``KB_ALERT_GUARD_TRIPS`` is FIRING.
One trip is an incident the breaker already handled; a trip RATE is a
systemic signal (flapping hardware, a persistently divergent fast path)
that demands an operator — exactly the distinction a gauge on raw
``volcano_guard_trips_total`` cannot make without server-side rate rules.

Evaluation runs on the guard plane's own cycle clock (the Scheduler calls
it right after ``GuardPlane.end_cycle``), so firing decisions are
deterministic under the sim's virtual time; the corruption chaos preset
asserts the aggregate alert fires.  Surfaces: ``GET /v1/alerts`` and the
``volcano_alerts_firing`` gauge.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from kube_batch_tpu import metrics
from kube_batch_tpu.envutil import env_int

logger = logging.getLogger("kube_batch_tpu")


#: the aggregate series (any action, any path)
AGGREGATE = "guard_trips"


class AlertEvaluator:
    """Sliding-window trip-rate thresholds over the guard plane's trip
    log.  Alert names: ``guard_trips`` (aggregate) and
    ``guard_trips:<path>`` per demoted fast path."""

    def __init__(self, threshold: Optional[int] = None,
                 window: Optional[int] = None):
        self.threshold = (
            threshold if threshold is not None
            else max(1, env_int("KB_ALERT_GUARD_TRIPS", 1))
        )
        self.window = (
            window if window is not None
            else max(1, env_int("KB_ALERT_WINDOW", 64))
        )
        self._mu = threading.Lock()
        self._seen_trips = 0  # trip_log prefix already ingested
        # alert name → trip cycle numbers still inside the window
        self._recent: Dict[str, List[int]] = {}
        self.firing: Dict[str, bool] = {}
        self.fired_total: Dict[str, int] = {}
        self.last_cycle = -1

    def evaluate(self, guard) -> Dict[str, bool]:
        """Ingest new trips from ``guard.trip_log`` and re-derive every
        alert's firing state at the guard's current cycle clock."""
        with self._mu:
            cycle, new, self._seen_trips = guard.trip_series(self._seen_trips)
            self.last_cycle = cycle
            for trip in new:
                t_cycle = int(trip.get("cycle", cycle))
                names = [AGGREGATE] + [
                    f"{AGGREGATE}:{p}" for p in trip.get("demoted", ())
                ]
                for name in names:
                    self._recent.setdefault(name, []).append(t_cycle)
            lo = cycle - self.window
            out: Dict[str, bool] = {}
            for name, cycles in list(self._recent.items()):
                cycles[:] = [c for c in cycles if c >= lo]
                firing = len(cycles) >= self.threshold
                was = self.firing.get(name, False)
                if firing and not was:
                    self.fired_total[name] = self.fired_total.get(name, 0) + 1
                    logger.error(
                        "ALERT firing: %s — %d guard trips within %d cycles "
                        "(threshold %d)", name, len(cycles), self.window,
                        self.threshold,
                    )
                elif was and not firing:
                    logger.info("ALERT resolved: %s", name)
                self.firing[name] = firing
                out[name] = firing
                metrics.set_alert_firing(name, int(firing))
        return out

    def state(self) -> Dict:
        with self._mu:
            return {
                "threshold_trips": self.threshold,
                "window_cycles": self.window,
                "evaluated_at_cycle": self.last_cycle,
                "alerts": {
                    name: {
                        "firing": self.firing.get(name, False),
                        "trips_in_window": len(self._recent.get(name, ())),
                        "fired_total": self.fired_total.get(name, 0),
                    }
                    for name in sorted(
                        set(self.firing) | set(self._recent)
                    )
                },
            }


_ATTACH_LOCK = threading.Lock()


def alerts_of(cache) -> AlertEvaluator:
    """THE per-cache alert evaluator (the guard_of idiom)."""
    ev = getattr(cache, "alert_evaluator", None)
    if ev is None:
        with _ATTACH_LOCK:
            ev = getattr(cache, "alert_evaluator", None)
            if ev is None:
                ev = AlertEvaluator()
                cache.alert_evaluator = ev
    return ev
