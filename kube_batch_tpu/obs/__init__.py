"""Observability plane — structured cycle tracing, the anomaly-triggered
flight recorder, and guard-trip SLO alerting.

Three modules:

- :mod:`kube_batch_tpu.obs.trace` — the span recorder: context-manager
  spans with nesting over every stage of the (pipelined) scheduling cycle,
  wall time through the ``telemetry`` seam, virtual time through the
  injected clock, device-time attribution via ``utils/jitstats``.
- :mod:`kube_batch_tpu.obs.recorder` — the flight recorder: a bounded
  ring of complete per-cycle trace trees that dumps the cycles AROUND an
  anomaly (guard trip, budget shed, arrival→decision SLO breach,
  duplicate bind) as Chrome trace-event JSON.
- :mod:`kube_batch_tpu.obs.alerts` — the guard trip-rate SLO evaluator
  feeding ``GET /v1/alerts`` and the ``volcano_alerts_firing`` gauge.

Everything attaches lazily per cache (the ``guard_of`` idiom) so multiple
scheduler instances in one process never cross wires.
"""

from kube_batch_tpu.obs.trace import Tracer, tracer_of  # noqa: F401
from kube_batch_tpu.obs.recorder import FlightRecorder  # noqa: F401
from kube_batch_tpu.obs.alerts import AlertEvaluator, alerts_of  # noqa: F401
