"""pytest plugin: run the whole suite under the lockdep runtime validator.

Wired via ``pytest_plugins`` in tests/conftest.py, so the ordinary tier-1
run doubles as a lock-order regression test (the `go test -race` analog).
Violations accumulated over the session print a report and fail the run.

Opt out with ``KBT_LOCKDEP=0`` (e.g. when bisecting an unrelated failure).
Tests that deliberately provoke violations (tests/test_lockdep.py) run
against their own private ``LockdepState`` and never touch the
session-global one.
"""

from __future__ import annotations

import os

from kube_batch_tpu.analysis import lockdep


def _enabled() -> bool:
    return os.environ.get("KBT_LOCKDEP", "1").lower() not in ("0", "false", "no")


def pytest_configure(config):
    if _enabled():
        config._kbt_lockdep_state = lockdep.install()


def pytest_unconfigure(config):
    if getattr(config, "_kbt_lockdep_state", None) is not None:
        lockdep.uninstall()
        config._kbt_lockdep_state = None


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    state = getattr(config, "_kbt_lockdep_state", None)
    if state is None:
        return
    if state.violations:
        terminalreporter.section("kbt lockdep violations")
        terminalreporter.write_line(state.report())
    else:
        terminalreporter.write_line(
            f"kbt lockdep: clean ({len(state.edges)} lock-order edges observed)"
        )


def pytest_sessionfinish(session, exitstatus):
    state = getattr(session.config, "_kbt_lockdep_state", None)
    if state is not None and state.violations and session.exitstatus == 0:
        session.exitstatus = 1
