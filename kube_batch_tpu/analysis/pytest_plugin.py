"""pytest plugin: run the whole suite under the lockdep runtime validator.

Wired via ``pytest_plugins`` in tests/conftest.py, so the ordinary tier-1
run doubles as a lock-order regression test (the `go test -race` analog).
Violations accumulated over the session print a report and fail the run.

Opt out with ``KBT_LOCKDEP=0`` (e.g. when bisecting an unrelated failure).
Tests that deliberately provoke violations (tests/test_lockdep.py) run
against their own private ``LockdepState`` and never touch the
session-global one.

The tier-D guarded-access corroborator (analysis/races.py lock domains)
rides along: the hot shared structures below are instrumented so every
access the suite executes asserts the statically inferred domain lock is
held.  ``KBT_GUARDED_ACCESS=0`` opts out independently of lockdep;
``KBT_GUARDED_SAMPLE=N`` checks only every Nth access on a shared
instance (default 1 = every access — the suite is small enough).
"""

from __future__ import annotations

import os

from kube_batch_tpu.analysis import lockdep

#: the instrumented hot shared structures: (module, class, attr).  The
#: domain lock is NOT written here — it is resolved from the static tier-D
#: inference at session start (races.runtime_domain_specs), so this table
#: can never silently disagree with the map it corroborates.  The resync
#: queue and the warm-table state are deliberately absent: neither owns a
#: lock (the cache's big lock serializes the former; the latter is
#: cycle-confined), so they have no domain to corroborate.
HOT_STRUCTURES = (
    ("kube_batch_tpu.cache.cache", "SchedulerCache", "_ingest_staged"),
    ("kube_batch_tpu.serve.lease", "LeaseBroker", "_lease"),
    ("kube_batch_tpu.replicate.publisher", "ReplicationPublisher", "_ring"),
    ("kube_batch_tpu.replicate.publisher", "ReplicationPublisher", "_mirror"),
)


def _env_on(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default).lower() not in ("0", "false", "no")


def _enabled() -> bool:
    return _env_on("KBT_LOCKDEP")


def pytest_configure(config):
    if not _enabled():
        return
    config._kbt_lockdep_state = lockdep.install()
    if _env_on("KBT_GUARDED_ACCESS"):
        from kube_batch_tpu.analysis import races

        specs = races.runtime_domain_specs(HOT_STRUCTURES)
        config._kbt_guarded = lockdep.install_guarded_access(
            specs,
            state=config._kbt_lockdep_state,
            sample=int(os.environ.get("KBT_GUARDED_SAMPLE", "1")),
        )


def pytest_unconfigure(config):
    if getattr(config, "_kbt_guarded", None) is not None:
        config._kbt_guarded.uninstall()
        config._kbt_guarded = None
    if getattr(config, "_kbt_lockdep_state", None) is not None:
        lockdep.uninstall()
        config._kbt_lockdep_state = None


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    state = getattr(config, "_kbt_lockdep_state", None)
    if state is None:
        return
    if state.violations:
        terminalreporter.section("kbt lockdep violations")
        terminalreporter.write_line(state.report())
    else:
        guarded = getattr(config, "_kbt_guarded", None)
        extra = (
            f", {len(guarded._patched)} guarded structures corroborated"
            if guarded is not None else ""
        )
        terminalreporter.write_line(
            f"kbt lockdep: clean ({len(state.edges)} lock-order edges "
            f"observed{extra})"
        )


def pytest_sessionfinish(session, exitstatus):
    state = getattr(session.config, "_kbt_lockdep_state", None)
    if state is not None and state.violations and session.exitstatus == 0:
        session.exitstatus = 1
