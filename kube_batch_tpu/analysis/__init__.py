"""kbt-check — project-specific static analysis + runtime lock-order checks.

The reference kube-batch is Go: `go vet` and `go test -race` catch whole bug
classes for free. This Python/JAX port has no such net, and every advisor
finding to date (sleep-under-lock in TokenBucket, the process-global
allocate→backfill discard signal, the fail-open PV nodeAffinity translation,
PR 1's writer-executor race) was an instance of a mechanically detectable
pattern. This package builds the checks once so the class stops recurring:

- `engine` / `rules` / `flowrules` / `dataflow`: an AST lint engine
  (stdlib `ast`, no new deps) with rules KBT001–KBT010, each grounded in
  a real past bug. KBT001–005 are line-local; KBT006–010 are flow-aware —
  the engine builds a per-module symbol table with resolved imports and
  the rules run intra-procedural def-use tracking (aliasing, taint,
  may-merge joins), the sized-for-us analog of `go vet`'s SSA passes.
  Run with `python -m kube_batch_tpu.analysis` (add `--jsonl` for CI).
- `jaxpr_audit`: tier B — the registered jitted entry points traced with
  abstract shapes and their closed jaxprs linted for f64 upcasts, in-graph
  transfers, host callbacks, and donation drift (KBT101–104). Run with
  `--jaxpr` / `--jaxpr-only`, or both tiers via `scripts/check.sh`.
- `races`: tier D — the static thread/lock-domain race analyzer
  (KBT301–304): a thread-root graph (spawn sites, worker bodies, HTTP
  handlers), per-class lock-domain inference over the def-use walker's
  with-block regions, and rules for off-domain access, publish-then-
  mutate handoffs (the generalized KBT012, whose id survives as a
  `--select` alias), lock-free check-then-act, and racy lazy init.  Run
  with `--races` / `--races-only`; `--domains` prints the inferred map.
- `lockdep`: a runtime lock-order validator in the spirit of the Linux
  kernel's lockdep — instrumented Lock/RLock factories record per-thread
  held-lock sets, build the acquisition-order graph, and flag A→B/B→A
  inversions (transitive cycles included), blocking calls made while a
  lock is held, and same-site nesting not declared via
  utils.blocking.allow_nesting.  Also hosts the tier-D guarded-access
  corroborator: hot shared structures are instrumented so every access
  the suite executes asserts the statically inferred domain lock is held.
- `pytest_plugin`: enables lockdep + the guarded-access corroborator for
  the whole test suite and fails the run on violations (wired into
  tests/conftest.py, so tier-1 enforces it).

Suppressions: `# kbt: allow[KBT00X] reason` on the flagged line (or the
line directly above). The reason is mandatory — an allow without one does
not suppress. See ANALYSIS.md for the rule catalog.
"""

from kube_batch_tpu.analysis.engine import (  # noqa: F401
    Finding,
    check_source,
    run_paths,
)
from kube_batch_tpu.analysis.rules import ALL_RULES  # noqa: F401
