"""Lint engine: file walking, suppression parsing, rule dispatch.

A rule sees one parsed module at a time plus its package-relative path
(e.g. ``actions/allocate.py``) — scoping is by path prefix, so the same
rule objects run identically over the installed package and over the
fixture snippets in tests.

Since PR 4 the engine is flow-aware: for each module it builds ONE
:class:`~kube_batch_tpu.analysis.dataflow.ModuleContext` — resolved
imports, module symbol table, function index — and hands it to every rule
through ``check_ctx``.  Line-local rules keep their ``check(tree, relpath)``
signature (the base class adapts); flow rules (flowrules.py) override
``check_ctx`` and additionally get intra-procedural def-use tracking from
``dataflow.walk_function``.

Suppression contract (see ANALYSIS.md): ``# kbt: allow[KBT001] reason``
on the finding's line or the line directly above suppresses that rule
there. The reason text is mandatory; an allow with no reason suppresses
nothing and instead raises a KBT000 finding, so unexplained escapes can't
accumulate.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: package whose source tree is the default analysis target
PACKAGE_NAME = "kube_batch_tpu"

_ALLOW_RE = re.compile(r"kbt:\s*allow\[([A-Za-z0-9_,\s]+)\]\s*(.*)")

#: rule-id aliases, honored by `--select` AND by allow comments: when a
#: rule migrates tiers its old id keeps meaning (KBT012 — the pipelined
#: writeback handoff check — is a tier-D KBT302 instance since PR 18).
#: Lives here (not races.py) so Suppressions can resolve without an
#: engine→races import cycle; races re-exports it for the CLI.
RULE_ALIASES = {"KBT012": "KBT302"}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # display path (as passed to the checker)
    line: int
    col: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Suppressions:
    """Per-line ``kbt: allow[...]`` map for one source file."""

    def __init__(self) -> None:
        self.by_line: Dict[int, Set[str]] = {}
        # allow comments missing the mandatory reason: (line, rules)
        self.missing_reason: List[Tuple[int, str]] = []

    @classmethod
    def parse(cls, source: str) -> "Suppressions":
        """An allow comment covers its own line (inline trailing form) and —
        when it's a comment-only line — the next code line, with any
        intervening comment/blank lines bridged (so a multi-line annotation
        block covers the statement it introduces)."""
        sup = cls()
        lines = source.splitlines()

        def _code_line_after(ln: int) -> int:
            i = ln  # 1-based comment line; scan forward
            while i < len(lines):
                stripped = lines[i].strip()
                if stripped and not stripped.startswith("#"):
                    return i + 1
                i += 1
            return ln

        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _ALLOW_RE.search(tok.string)
                if m is None:
                    continue
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                if not m.group(2).strip():
                    sup.missing_reason.append((tok.start[0], ",".join(sorted(rules))))
                    continue
                ln = tok.start[0]
                sup.by_line.setdefault(ln, set()).update(rules)
                comment_only = lines[ln - 1].strip().startswith("#")
                if comment_only:
                    sup.by_line.setdefault(_code_line_after(ln), set()).update(rules)
        except tokenize.TokenError:
            pass  # a finding-bearing parse already failed upstream
        return sup

    def covers(self, rule: str, line: int) -> bool:
        allowed = self.by_line.get(line, set())
        if rule in allowed:
            return True
        # honor aliased ids: allow[KBT012] keeps suppressing the rule it
        # migrated into (KBT302)
        return any(RULE_ALIASES.get(a) == rule for a in allowed)


class Rule:
    """Base rule: subclasses set ``id``/``title``/``scope`` and implement
    ``check``. ``scope`` is a tuple of package-relative path prefixes; empty
    means package-wide."""

    id: str = "KBT000"
    title: str = ""
    scope: Tuple[str, ...] = ()

    def applies_to(self, relpath: str) -> bool:
        if not self.scope:
            return True
        # prefix match for package-relative paths; segment match so files
        # addressed by absolute/external paths (CLI on a checkout, test
        # fixtures) still land in the right scope
        return any(
            relpath.startswith(p) or f"/{p}" in f"/{relpath}"
            for p in self.scope
        )

    def check(self, tree: ast.Module, relpath: str) -> Iterable[Tuple[int, int, str]]:
        raise NotImplementedError

    def check_ctx(self, ctx) -> Iterable[Tuple[int, int, str]]:
        """Flow-aware entry point: receives the shared ModuleContext.  The
        default adapts line-local rules; flow rules override this."""
        return self.check(ctx.tree, ctx.relpath)


def check_source(
    source: str,
    relpath: str,
    rules: Optional[Sequence[Rule]] = None,
    display_path: Optional[str] = None,
) -> List[Finding]:
    """Run ``rules`` over one module's source. ``relpath`` is the
    package-relative posix path used for rule scoping; ``display_path`` is
    what findings print (defaults to ``relpath``)."""
    from kube_batch_tpu.analysis.rules import ALL_RULES

    if rules is None:
        rules = ALL_RULES
    display = display_path or relpath
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("KBT000", display, e.lineno or 0, e.offset or 0,
                        f"syntax error: {e.msg}")]
    from kube_batch_tpu.analysis.dataflow import ModuleContext

    ctx = ModuleContext(tree, relpath)  # built once, shared by every rule
    sup = Suppressions.parse(source)
    findings: List[Finding] = []
    for line, rules_txt in sup.missing_reason:
        findings.append(Finding(
            "KBT000", display, line, 0,
            f"allow[{rules_txt}] has no reason — suppression ignored; "
            "write `# kbt: allow[RULE] why it is safe`",
        ))
    for rule in rules:
        if not rule.applies_to(relpath):
            continue
        for line, col, message in rule.check_ctx(ctx):
            if sup.covers(rule.id, line):
                continue
            findings.append(Finding(rule.id, display, line, col, message))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _package_relpath(path: Path) -> str:
    """Path → package-relative posix path for scoping: everything after the
    last ``kube_batch_tpu`` component, else the filename."""
    parts = path.as_posix().split("/")
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == PACKAGE_NAME:
            return "/".join(parts[i + 1:])
    # outside the package: keep the full path so directory-segment scoping
    # (applies_to) still sees ops/, actions/, ... components
    return path.as_posix().lstrip("/")


def iter_python_files(paths: Sequence[Path]) -> Iterable[Path]:
    for p in paths:
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def run_paths(
    paths: Optional[Sequence[str]] = None,
    rules: Optional[Sequence[Rule]] = None,
) -> List[Finding]:
    """Analyze files/directories (default: the installed package tree)."""
    if not paths:
        roots = [Path(__file__).resolve().parent.parent]
    else:
        roots = [Path(p) for p in paths]
    findings: List[Finding] = []
    for r in roots:
        # a missing path must NOT read as "clean": a typo'd/renamed CI
        # argument would silently stop checking anything while staying green
        if not r.exists():
            findings.append(Finding(
                "KBT000", str(r), 0, 0, "path does not exist"))
    for f in iter_python_files(roots):
        try:
            source = f.read_text()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("KBT000", str(f), 0, 0, f"unreadable: {e}"))
            continue
        findings.extend(check_source(
            source, _package_relpath(f), rules=rules, display_path=str(f)
        ))
    return findings
