"""Flow-aware KBT rules (KBT006–KBT010), grounded in the PR 3 device-resident
hot path.  Line-local matching (rules.py, KBT001–005) cannot see these bug
shapes: each rule here consumes the per-module :class:`ModuleContext` the
engine builds (import resolution + symbol table) and, where the bug is a
*sequence* of statements, the intra-procedural def-use walk in dataflow.py.

Rules report (line, col, message) triples; scoping and suppression live in
the engine, exactly like the line-local rules.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from kube_batch_tpu.analysis.dataflow import (
    FlowEvent,
    FlowVisitor,
    ModuleContext,
    call_keyword,
    const_int_tuple,
    walk_function,
)
from kube_batch_tpu.analysis.engine import Rule

# --------------------------------------------------------------------------
# shared jit-detection helpers
# --------------------------------------------------------------------------

_JIT_PATHS = {"jax.jit", "jax.api.jit"}
_PARTIAL_PATHS = {"functools.partial", "functools.partial.partial"}


def _is_jit_expr(node: ast.AST, ctx: ModuleContext) -> Optional[ast.Call]:
    """The ``jax.jit(...)`` call inside ``node``, unwrapping one registry
    wrapper layer (``jitstats.register("n", jax.jit(...))``) and the
    ``functools.partial(jax.jit, ...)`` form.  None when node builds no jit
    wrapper."""
    if not isinstance(node, ast.Call):
        return None
    dotted = ctx.resolve_call(node)
    if dotted in _JIT_PATHS:
        return node
    if dotted in _PARTIAL_PATHS and node.args:
        if ctx.imports.dotted(node.args[0]) in _JIT_PATHS:
            return node
    # one wrapper layer: any call carrying a jax.jit call among its args
    for arg in list(node.args) + [kw.value for kw in node.keywords]:
        if isinstance(arg, ast.Call) and ctx.resolve_call(arg) in _JIT_PATHS:
            return arg
    return None


def _donate_positions(jit_call: ast.Call, ctx: ModuleContext,
                      tree: ast.Module) -> Tuple[int, ...]:
    """donate_argnums of a jax.jit call, resolving a Name argument through
    any single assignment in the module (the resident scatter binds its
    backend-conditional tuple to a local first).  Conditional tuples fold
    may-style — a position that CAN be donated is tracked."""
    kw = call_keyword(jit_call, "donate_argnums")
    if kw is None:
        return ()
    got = const_int_tuple(kw)
    if got is not None:
        return got
    if isinstance(kw, ast.Name):
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == kw.id
                for t in node.targets
            ):
                got = const_int_tuple(node.value)
                if got is not None:
                    return got
    return ()


class _DonationTable:
    """Module symbol table slice for KBT006: which local names are donating
    jitted callables, which zero-arg functions return one, and — ONE call
    level deep through the module's symbol table — which same-module
    helpers donate their own parameters.

    The interprocedural level closes the ROADMAP-standing escape: a helper
    like ``def refresh(dev): return _scatter_fn()(dev, rows, vals)``
    donates its caller's buffer, but only the helper's body carries the
    donating call — a caller reading ``dev`` after ``refresh(dev)`` walked
    clean.  The ``param_donors`` scan marks such helpers so their call
    sites taint arguments exactly like a direct donating call.  One level
    only (a helper calling a helper is out of scope), matching the
    deliberately-bounded depth of the rest of the flow engine."""

    def __init__(self, ctx: ModuleContext):
        self.by_name: Dict[str, Tuple[int, ...]] = {}
        self.factories: Dict[str, Tuple[int, ...]] = {}
        #: helper function name → parameter positions it donates
        self.param_donors: Dict[str, Tuple[int, ...]] = {}
        tree = ctx.tree
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                jit = _is_jit_expr(node.value, ctx)
                if jit is None:
                    continue
                pos = _donate_positions(jit, ctx, tree)
                if not pos:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.by_name[t.id] = pos
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    jit = _is_jit_expr(dec, ctx) if isinstance(dec, ast.Call) else None
                    if jit is not None:
                        pos = _donate_positions(jit, ctx, tree)
                        if pos:
                            self.by_name[node.name] = pos
        # factories: functions whose return value is a donating name
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Return)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id in self.by_name):
                    self.factories[node.name] = self.by_name[sub.value.id]
        # one-level interprocedural: a function passing its OWN parameter
        # into a donating call at a donated position donates that
        # parameter — including through the factory ``_scatter_fn()(...)``
        # form, which _direct_positions already resolves
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in node.args.args]
            donated: set = set()
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                for p in self._direct_positions(sub):
                    if (p < len(sub.args)
                            and isinstance(sub.args[p], ast.Name)
                            and sub.args[p].id in params):
                        donated.add(params.index(sub.args[p].id))
            if donated:
                self.param_donors[node.name] = tuple(sorted(donated))

    def _direct_positions(self, call: ast.Call) -> Tuple[int, ...]:
        """Donated positions from the module-level table only (no
        interprocedural step — this is what the one-level scan itself
        consumes, keeping the closure bounded)."""
        f = call.func
        if isinstance(f, ast.Name):
            return self.by_name.get(f.id, ())
        if (isinstance(f, ast.Call) and isinstance(f.func, ast.Name)
                and not f.args):
            return self.factories.get(f.func.id, ())
        return ()

    def call_positions(self, call: ast.Call) -> Tuple[int, ...]:
        """Donated positions of this call site, or () — the direct
        ``scatter(...)`` form, the factory ``_scatter_fn()(...)`` form,
        and same-module helpers that donate their parameters."""
        direct = self._direct_positions(call)
        if direct:
            return direct
        f = call.func
        if isinstance(f, ast.Name):
            return self.param_donors.get(f.id, ())
        return ()


# --------------------------------------------------------------------------
# KBT006 — donated-buffer use after donation
# --------------------------------------------------------------------------


class UseAfterDonationRule(Rule):
    """PR 3 hazard: the resident scatter donates its stale device buffer
    (``donate_argnums``) so XLA writes in place — after the donating call
    the Python binding still *looks* alive, but the buffer is deleted; a
    later read raises (or worse, silently reads garbage on backends that
    alias).  Nothing fails until a real accelerator run.  Tracks
    donate_argnums call sites through the module symbol table (direct
    names, registry-wrapped assigns, factory functions) and flags any read
    of a donated binding that was not rebound first — rebinding to the
    call's result (``dev = scatter(dev, ...)``) is the sanctioned shape."""

    id = "KBT006"
    title = "read of a donated buffer after the donating call"
    scope = ()  # donation is rare; check everywhere it appears

    def check_ctx(self, ctx: ModuleContext):
        table = _DonationTable(ctx)
        if not table.by_name:
            return
        findings: List[Tuple[int, int, str]] = []
        seen: Set[Tuple[int, str]] = set()

        class V(FlowVisitor):
            def on_call(self, ev: FlowEvent, env) -> None:
                call = ev.node
                pos = table.call_positions(call)
                for p in pos:
                    if p < len(call.args) and isinstance(call.args[p], ast.Name):
                        cell = env.get(call.args[p].id)
                        if cell is not None:
                            cell["donated"] = (call.lineno, call.args[p].id)

            def on_load(self, ev: FlowEvent, env) -> None:
                if ev.cell is None or "donated" not in ev.cell:
                    return
                dline, dname = ev.cell["donated"]  # type: ignore[misc]
                key = (ev.node.lineno, ev.name)
                if key in seen:
                    return
                seen.add(key)
                findings.append((
                    ev.node.lineno, ev.node.col_offset,
                    f"`{ev.name}` was donated to the jitted call on line "
                    f"{dline} (donate_argnums) — its buffer no longer "
                    "exists; rebind the name to the call's result before "
                    "any further use",
                ))

        for func in ctx.functions:
            walk_function(func, V())
        yield from findings


# --------------------------------------------------------------------------
# KBT007 — jit retrace hazards
# --------------------------------------------------------------------------


class RetraceHazardRule(Rule):
    """Guards the zero-steady-state-retrace invariant the PR 3 bench proves
    (utils/jitstats counters): a ``jax.jit`` wrapper constructed inside a
    function body gets a fresh cache per call — every cycle recompiles the
    whole solve (the bug parallel/mesh.py's ``_jit_cache`` exists to
    prevent).  Also flags unhashable literals passed in static positions of
    module-known jitted callables (TypeError at runtime, or a per-value
    cache key), shape-derived static args (``len(...)``/``.shape[...]`` —
    per-size specializations; route sizes through the snapshot buckets /
    ``ColumnStore.reserve()``), and jitted functions closing over mutable
    module state (the value is baked at trace time; mutation never
    reaches the compiled code)."""

    id = "KBT007"
    title = "jit retrace hazard"
    scope = ("ops/", "api/", "actions/", "parallel/", "framework/", "cache/")

    MUTABLE_FACTORIES = {"dict", "list", "set", "defaultdict", "deque",
                         "Counter", "OrderedDict"}

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _body_without_nested_defs(func: ast.AST) -> Iterable[ast.AST]:
        stack = list(func.body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _memo_names(self, func: ast.AST) -> Set[str]:
        """Names that escape into a memo within this function: stored to a
        subscript/attribute (``_jit_cache[key] = fn``) or declared global
        (the module-global memo the resident scatter uses)."""
        out: Set[str] = set()
        for node in self._body_without_nested_defs(func):
            if isinstance(node, ast.Assign):
                if any(isinstance(t, (ast.Subscript, ast.Attribute))
                       for t in node.targets):
                    if isinstance(node.value, ast.Name):
                        out.add(node.value.id)
            elif isinstance(node, ast.Global):
                out.update(node.names)
        return out

    def _static_positions(self, jit_call: ast.Call) -> Tuple[Tuple[int, ...],
                                                             Tuple[str, ...]]:
        nums = const_int_tuple(call_keyword(jit_call, "static_argnums") or
                               ast.Constant(value=None)) or ()
        names: Tuple[str, ...] = ()
        kw = call_keyword(jit_call, "static_argnames")
        if isinstance(kw, (ast.Tuple, ast.List)):
            names = tuple(e.value for e in kw.elts
                          if isinstance(e, ast.Constant) and isinstance(e.value, str))
        elif isinstance(kw, ast.Constant) and isinstance(kw.value, str):
            names = (kw.value,)
        return nums, names

    @staticmethod
    def _is_lru_cached(func: ast.AST, ctx: ModuleContext) -> bool:
        for dec in func.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if ctx.imports.dotted(target) in (
                "functools.lru_cache", "functools.cache",
            ):
                return True
        return False

    @staticmethod
    def _unhashable(node: ast.AST) -> str:
        if isinstance(node, (ast.List, ast.ListComp)):
            return "list"
        if isinstance(node, (ast.Dict, ast.DictComp)):
            return "dict"
        if isinstance(node, (ast.Set, ast.SetComp)):
            return "set"
        return ""

    @staticmethod
    def _shape_derived(node: ast.AST) -> bool:
        """len(x) or anything.shape[...] — a per-cycle size reaching a
        static position means one compile per distinct size."""
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "len"):
            return True
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr == "shape":
                return True
        return False

    # -- the check ---------------------------------------------------------
    def check_ctx(self, ctx: ModuleContext):
        # (a) jit wrappers built per call inside function bodies
        for func in ctx.functions:
            if self._is_lru_cached(func, ctx):
                continue
            memo = self._memo_names(func)
            for node in self._body_without_nested_defs(func):
                jit: Optional[ast.Call] = None
                bound: Optional[str] = None
                if isinstance(node, ast.Assign):
                    jit = _is_jit_expr(node.value, ctx)
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            bound = t.id
                elif isinstance(node, ast.Expr):
                    jit = _is_jit_expr(node.value, ctx)
                if jit is None:
                    continue
                if bound is not None and bound in memo:
                    continue  # memoized (the mesh _jit_cache pattern)
                yield (jit.lineno, jit.col_offset,
                       "jax.jit wrapper constructed inside a function body "
                       "gets a fresh compile cache per call — every "
                       "invocation retraces; hoist to module level or memo "
                       "it (the parallel/mesh.py _jit_cache pattern)")

        # (b) static-position hazards at call sites of module-known jitted
        # callables
        jitted: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        jit = _is_jit_expr(dec, ctx)
                        if jit is not None:
                            jitted[node.name] = self._static_positions(jit)
                    elif ctx.imports.dotted(dec) in _JIT_PATHS:
                        jitted[node.name] = ((), ())  # bare @jax.jit
            elif isinstance(node, ast.Assign):
                jit = _is_jit_expr(node.value, ctx)
                if jit is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jitted[t.id] = self._static_positions(jit)
        for node in ast.walk(ctx.tree):
            if not jitted:
                break
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in jitted):
                continue
            nums, names = jitted[node.func.id]
            static_args = [
                (node.args[p], f"position {p}") for p in nums
                if p < len(node.args)
            ] + [
                (kw.value, f"`{kw.arg}`") for kw in node.keywords
                if kw.arg in names
            ]
            for arg, where in static_args:
                kind = self._unhashable(arg)
                if kind:
                    yield (arg.lineno, arg.col_offset,
                           f"unhashable {kind} literal passed in static "
                           f"{where} of jitted `{node.func.id}` — jit cache "
                           "keys must hash; pass a tuple/NamedTuple")
                elif self._shape_derived(arg):
                    yield (arg.lineno, arg.col_offset,
                           f"shape-derived value in static {where} of "
                           f"jitted `{node.func.id}` compiles once per "
                           "distinct size; route sizes through the "
                           "snapshot shape buckets (ColumnStore.reserve)")

        # (c) jitted functions closing over mutable module state
        mutable_globals = {
            name for name, value in ctx.module_assigns.items()
            if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                  ast.DictComp, ast.SetComp))
            or (isinstance(value, ast.Call) and isinstance(value.func, ast.Name)
                and value.func.id in self.MUTABLE_FACTORIES)
        }
        if not mutable_globals:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(
                (isinstance(dec, ast.Call) and _is_jit_expr(dec, ctx))
                or ctx.imports.dotted(dec) in _JIT_PATHS
                for dec in node.decorator_list
            ):
                continue
            params = {a.arg for a in node.args.args}
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in mutable_globals
                        and sub.id not in params):
                    yield (sub.lineno, sub.col_offset,
                           f"jitted `{node.name}` reads mutable module "
                           f"state `{sub.id}` — the value is baked in at "
                           "trace time and later mutation never reaches "
                           "the compiled code (silent staleness, not a "
                           "retrace)")


# --------------------------------------------------------------------------
# KBT008 — fail-open seam probes in the k8s layer
# --------------------------------------------------------------------------


class FailOpenSeamProbeRule(Rule):
    """ROADMAP follow-on to KBT004: the translate/watch layer probed its
    volume-binder seam with 3-arg ``getattr(binder, "add_pv", lambda..)`` —
    a binder missing the method silently dropped every PV event, the exact
    shape of the round-5 PV fail-open but one layer up.  Now that the seam
    surface is stable (cache/interface.py Protocols + explicit no-op
    fakes), a defaulted getattr probe in k8s/ is a policy decision to fail
    open and must be written down or replaced with a declared method.
    Dispatch-table ``.get()`` probes whose miss silently drops an event are
    the same bug through a dict."""

    id = "KBT008"
    title = "fail-open seam probe (defaulted getattr / dispatch-table get)"
    scope = ("k8s/",)

    DISPATCH_NAMES = ("handlers", "registry", "builders", "dispatch", "hooks")

    def check_ctx(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Name) and f.id == "getattr"
                    and len(node.args) == 3):
                default = node.args[2]
                attr = node.args[1]
                attr_txt = (
                    repr(attr.value) if isinstance(attr, ast.Constant) else "?"
                )
                if (isinstance(default, ast.Constant) and default.value is None) \
                        or isinstance(default, ast.Lambda):
                    yield (node.lineno, node.col_offset,
                           f"3-arg getattr probe of {attr_txt} fails open "
                           "when the seam object lacks it (events silently "
                           "dropped); declare the method on the interface "
                           "Protocol with an explicit no-op on fakes, or "
                           "annotate why silent absence is sound")
            elif (isinstance(f, ast.Attribute) and f.attr == "get"
                    and isinstance(f.value, ast.Name)
                    and f.value.id.lower() in self.DISPATCH_NAMES):
                default = node.args[1] if len(node.args) > 1 else None
                if default is None or (
                    isinstance(default, ast.Constant) and default.value is None
                ):
                    yield (node.lineno, node.col_offset,
                           f"dispatch-table `{f.value.id}.get(...)` miss "
                           "returns None and silently drops the event; "
                           "fail closed (raise/log at the seam) or "
                           "annotate the open default")


# --------------------------------------------------------------------------
# KBT009 — telemetry clock outside metrics-feeding expressions
# --------------------------------------------------------------------------

_TELEMETRY_PATHS = {
    "kube_batch_tpu.utils.telemetry.perf_counter",
    "kube_batch_tpu.utils.telemetry",
}


class TelemetryMisuseRule(Rule):
    """ROADMAP follow-on to KBT001: ``telemetry.perf_counter`` is the ONE
    sanctioned wall-clock read in the clock-seamed paths, sanctioned
    precisely because it only feeds latency metrics.  A telemetry value
    reaching *control flow* (a comparison, a loop/if test, a sleep arg)
    smuggles real wall-clock back into scheduling decisions — the exact
    determinism break KBT001 exists to stop, laundered through the
    telemetry seam.  Flow-tracked: bindings are tainted, aliases follow,
    and a binding that is never read at all is a dead wall-clock read."""

    id = "KBT009"
    title = "telemetry clock value outside metrics-feeding expressions"
    scope = ("scheduler.py", "actions/", "cache/", "sim/", "framework/")

    @staticmethod
    def _is_perf_counter(call: ast.Call, ctx: ModuleContext) -> bool:
        dotted = ctx.resolve_call(call)
        if dotted in _TELEMETRY_PATHS or dotted.endswith(
            ".telemetry.perf_counter"
        ):
            return True
        # `from ..utils.telemetry import perf_counter` form
        return dotted.endswith("utils.telemetry.perf_counter")

    def check_ctx(self, ctx: ModuleContext):
        rule = self
        findings: List[Tuple[int, int, str]] = []
        seen: Set[int] = set()

        def flag(node: ast.AST, msg: str) -> None:
            if node.lineno in seen:
                return
            seen.add(node.lineno)
            findings.append((node.lineno, node.col_offset, msg))

        class V(FlowVisitor):
            def __init__(self) -> None:
                # dead-read tracking is keyed by BIND SITE and marked by
                # NAME, not by cell identity: branch joins replace cells
                # with union copies and the two-pass loop walk rebinds, so
                # a cell-held counter misses legitimate post-join /
                # loop-carried reads (review finding, PR 4)
                self.bind_nodes: Dict[int, ast.AST] = {}   # id(node) → node
                self.bind_used: Dict[int, bool] = {}
                self.binds_by_name: Dict[str, List[int]] = {}

            def on_call(self, ev: FlowEvent, env) -> None:
                call = ev.node
                if not rule._is_perf_counter(call, ctx):
                    return
                if "compare" in ev.where or "test" in ev.where:
                    flag(call,
                         "telemetry.perf_counter() used directly in control "
                         "flow — pacing/timeout decisions belong to the "
                         "injected clock (Scheduler.clock / sim "
                         "VirtualClock); the telemetry seam is for latency "
                         "metrics only")

            def on_bind(self, ev: FlowEvent, env, value) -> None:
                if (isinstance(value, ast.Call)
                        and rule._is_perf_counter(value, ctx)
                        and ev.cell is not None):
                    ev.cell["telemetry"] = value.lineno
                    key = id(ev.node)
                    self.bind_nodes[key] = ev.node
                    self.bind_used.setdefault(key, False)
                    self.binds_by_name.setdefault(ev.name, []).append(key)

            def on_load(self, ev: FlowEvent, env) -> None:
                for key in self.binds_by_name.get(ev.name, ()):
                    self.bind_used[key] = True
                cell = ev.cell
                if cell is None or "telemetry" not in cell:
                    return
                if "compare" in ev.where or "test" in ev.where:
                    flag(ev.node,
                         f"telemetry clock value `{ev.name}` reaches a "
                         "comparison/branch — wall clock is steering "
                         "scheduling control flow; use the injected clock "
                         "for pacing, telemetry for metrics spans only")

        for func in ctx.functions:
            v = V()
            walk_function(func, v)
            for key, used in v.bind_used.items():
                if not used:
                    flag(v.bind_nodes[key],
                         "telemetry.perf_counter() bound but never read — "
                         "a dead wall-clock read in a clock-seamed path; "
                         "delete it or feed it to a metrics expression")
        yield from findings


# --------------------------------------------------------------------------
# KBT010 — host-device sync on resident values in the action layer
# --------------------------------------------------------------------------

#: calls whose results live on device (the PR 3 resident/solve surface,
#: extended for the PR 5 sharded scatters + enqueue gate dispatch shapes,
#: the PR 8 what-if probe — the query plane's outputs are device arrays
#: until its one sanctioned batch readback — and the KB_TOPK compacted
#: solves, whose candidate-table intermediates and exhaustion counters are
#: device values until the allocate action's single choke-point readback)
_DEVICE_SOURCES = {
    "kube_batch_tpu.ops.assignment.allocate_solve",
    "kube_batch_tpu.ops.assignment.allocate_topk_solve",
    "kube_batch_tpu.ops.assignment.warm_allocate_solve",
    "kube_batch_tpu.ops.assignment.failure_histogram_solve",
    "kube_batch_tpu.ops.assignment.failure_histogram_bucket_solve",
    "kube_batch_tpu.ops.eviction.evict_solve",
    "kube_batch_tpu.ops.probe.probe_solve",
    "kube_batch_tpu.parallel.mesh.sharded_allocate_solve",
    "kube_batch_tpu.parallel.mesh.sharded_allocate_topk_solve",
    "kube_batch_tpu.parallel.mesh.sharded_warm_allocate_solve",
    "kube_batch_tpu.parallel.mesh.sharded_failure_histogram",
    "kube_batch_tpu.parallel.mesh.sharded_failure_histogram_bucket",
    "kube_batch_tpu.parallel.mesh.sharded_evict_solve",
    "kube_batch_tpu.parallel.mesh.sharded_probe_solve",
    "kube_batch_tpu.api.columns.resident_snap",
    "kube_batch_tpu.ops.admission.enqueue_gate_solve",
    "jax.device_put",
}
#: local-name fallbacks for intra-module dispatch helpers: direct calls
#: (`..._solve(...)`) and the jitted-fn factory form the resident scatters
#: use (`_scatter_fn()(dev, ...)`, `_mesh_shard_scatter_fn(mesh)(dev, ...)`)
_DEVICE_SOURCE_SUFFIXES = ("_solve", "solve_dispatch")
_DEVICE_FACTORY_SUFFIXES = ("_scatter_fn", "_gate_fn")


class ResidentSyncRule(Rule):
    """Guards the PR 3 cycle budget at its weakest point: the action layer
    holds BOTH host-backed snapshots (cheap numpy reads) and device-resident
    solve results (each read = a blocking transfer).  KBT005 can't tell
    them apart — this rule can: solve dispatches and resident swaps taint
    their results "device", aliases follow, and a ``np.asarray``/
    ``.item()``/``jax.device_get``/``float()`` on a tainted value is a
    host-device sync.  The sanctioned choke points (the allocate action's
    ONE blocking ``device_get`` and the post-replay histogram readback)
    carry ``# kbt: allow[KBT010]`` annotations — everything else is a new
    stall on the <1s/50k-pod path."""

    id = "KBT010"
    title = "host-device sync on a device-resident value"
    # serve/ joined the scope with the query plane (PR 8): probe results
    # are device-resident until the micro-batcher's one sanctioned
    # per-window readback (serve/plane.py carries the allow annotation)
    scope = ("actions/", "api/resident.py", "serve/")

    SYNC_ATTRS = {"item", "tolist", "block_until_ready"}

    @staticmethod
    def _is_device_source(call: ast.Call, ctx: ModuleContext) -> bool:
        dotted = ctx.resolve_call(call)
        if dotted in _DEVICE_SOURCES:
            return True
        f = call.func
        if isinstance(f, ast.Name):
            return f.id.endswith(_DEVICE_SOURCE_SUFFIXES) or f.id == "resident_snap"
        # the factory form: `_scatter_fn()(dev, ...)` / `_mesh_shard_
        # scatter_fn(mesh)(dev, ...)` — the inner call returns a jitted
        # device fn, so the outer call's result is device-resident
        if (isinstance(f, ast.Call) and isinstance(f.func, ast.Name)
                and f.func.id.endswith(_DEVICE_FACTORY_SUFFIXES)):
            return True
        return False

    @staticmethod
    def _base_name(node: ast.AST) -> str:
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        if isinstance(node, ast.Name):
            return node.id
        return ""

    def check_ctx(self, ctx: ModuleContext):
        rule = self
        findings: List[Tuple[int, int, str]] = []
        seen: Set[int] = set()

        def flag(node: ast.AST, msg: str) -> None:
            if node.lineno in seen:
                return
            seen.add(node.lineno)
            findings.append((node.lineno, node.col_offset, msg))

        def tainted(env, expr: ast.AST) -> bool:
            for sub in ast.walk(expr):
                name = rule._base_name(sub) if isinstance(
                    sub, (ast.Name, ast.Attribute, ast.Subscript)) else ""
                if name:
                    cell = env.get(name)
                    if cell is not None and "device" in cell:
                        return True
            return False

        class V(FlowVisitor):
            def on_call(self, ev: FlowEvent, env) -> None:
                call = ev.node
                dotted = ctx.resolve_call(call)
                f = call.func
                # syncs ------------------------------------------------
                if dotted == "jax.device_get":
                    flag(call,
                         "jax.device_get blocks on the device pipeline; "
                         "the action layer gets ONE sanctioned readback "
                         "per cycle — annotate the choke point or batch "
                         "this into it")
                    return
                if dotted in ("numpy.asarray", "numpy.array") and call.args:
                    if tainted(env, call.args[0]):
                        flag(call,
                             "np.asarray on a device-resident value forces "
                             "a blocking transfer outside the sanctioned "
                             "readback; keep it on device or fold it into "
                             "the cycle's choke point")
                    return
                if (isinstance(f, ast.Attribute)
                        and f.attr in rule.SYNC_ATTRS
                        and tainted(env, f.value)):
                    flag(call,
                         f"`.{f.attr}()` on a device-resident value is a "
                         "blocking host-device sync in the action layer; "
                         "batch it into the sanctioned readback")
                    return
                if (isinstance(f, ast.Name) and f.id in ("float", "int")
                        and call.args and tainted(env, call.args[0])):
                    flag(call,
                         f"`{f.id}()` on a device-resident value "
                         "materializes it on host; read it back through "
                         "the sanctioned choke point")

            def on_bind(self, ev: FlowEvent, env, value) -> None:
                if (isinstance(value, ast.Call)
                        and rule._is_device_source(value, ctx)
                        and ev.cell is not None):
                    # device_get results are host values — never a source
                    if ctx.resolve_call(value) != "jax.device_get":
                        ev.cell["device"] = value.lineno

        for func in ctx.functions:
            walk_function(func, V())
        yield from findings


FLOW_RULES = (
    UseAfterDonationRule(),
    RetraceHazardRule(),
    FailOpenSeamProbeRule(),
    TelemetryMisuseRule(),
    ResidentSyncRule(),
)
