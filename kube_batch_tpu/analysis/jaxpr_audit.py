"""Tier B: jaxpr-level audit of the jitted entry points.

Tier A (the AST rules) sees source text; XLA sees the traced computation —
and the gap between them is where the PR 3 hot path's silent bugs live: a
float64 upcast that doubles every buffer, a `device_put` smuggled into the
middle of a compiled program, a host callback stalling the pipeline, a
donation that quietly stopped happening.  None of those fail a test on CPU;
all of them cost the <1s/50k-pod target on a real accelerator.  This
module is the JaxPruner-style answer (PAPERS.md): audit what actually gets
compiled, not what the source looks like.

Mechanism: a REGISTRY of the package's jitted entry points (ops/ solves,
the resident scatter, the Pallas round head).  Each entry is traced with
ABSTRACT inputs (jax.ShapeDtypeStruct — no device work, no compile) under
``jax.experimental.enable_x64`` so dtype promotion is visible instead of
silently canonicalized away, then the closed jaxpr is walked recursively
(while/cond/scan/pjit sub-jaxprs included) and linted:

- **KBT101 float64 upcast** — any f64 aval anywhere in the jaxpr when the
  declared inputs are f32/i32.  Integer widening under the x64 probe is
  canonicalization noise and ignored.
- **KBT102 in-graph transfer** — a `device_put` targeting a concrete
  device or performing a real copy (alias placements with device=None are
  how jnp constants materialize and are benign).
- **KBT103 host callback** — `pure_callback`/`io_callback`/`debug_callback`
  inside a hot-path program: a host round-trip per invocation.
- **KBT104 donation mismatch** — the wrapper's traced donate_argnums
  differ from what the registry entry declares for the current backend
  (e.g. someone drops donate_argnums from the resident scatter: CPU tests
  stay green, every TPU cycle silently double-allocates).

Suppression: registry entries carry ``allow={"KBT10x": "reason"}`` — the
reason is mandatory, mirroring the `# kbt: allow` contract.

Run via ``python -m kube_batch_tpu.analysis --jaxpr`` (adds this tier to
the static run; ``--jaxpr-only`` skips tier A) or the tier-1
self-enforcement test.  Tracing is abstract, so the whole audit is
sub-second after the jax import.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from kube_batch_tpu.analysis.engine import Finding

AUDIT_RULES = {
    "KBT101": "float64 upcast in a traced entry point",
    "KBT102": "in-graph device transfer in a traced entry point",
    "KBT103": "host callback in a traced entry point",
    "KBT104": "donation mismatch between wrapper and registry declaration",
}

_CALLBACK_PRIMS = {"pure_callback", "io_callback", "callback", "debug_callback"}


@dataclasses.dataclass
class EntryPoint:
    """One jitted entry point the audit traces.

    ``build`` returns ``(jitted_fn, args)`` with abstract (ShapeDtypeStruct)
    array arguments — static arguments go in baked into ``args`` as real
    values.  ``build`` accepts an optional ShapePoint: ``build()`` traces at
    the tier-B audit extents, ``build(sp)`` at a tier-C shape-ladder point.
    ``donate`` maps backend name → expected donate_argnums, with ``"*"`` as
    the fallback (the resident scatter donates everywhere except CPU).
    ``allow`` suppresses one audit rule for this entry, reason mandatory.
    ``steady`` declares the program steady-path/sparse: dispatched every
    cycle at scale, so tier C's KBT202 asserts it materializes no
    task-axis × node-axis plane (the full-matrix oracle and the pallas tile
    kernels are NOT steady — the first is the cold reference, the second
    are fixed-tile building blocks)."""

    name: str
    build: Callable[..., Tuple[Callable, Tuple]]
    donate: Dict[str, Tuple[int, ...]] = dataclasses.field(
        default_factory=lambda: {"*": ()})
    allow: Dict[str, str] = dataclasses.field(default_factory=dict)
    steady: bool = False


# --------------------------------------------------------------------------
# abstract input builders
# --------------------------------------------------------------------------

# small-but-representative axis sizes: which primitives appear in the trace
# does not depend on extents, and small shapes keep tracing fast.  W/Wt=1
# matches a fresh ColumnStore; K/Kp=1 is the padded sparse-row floor.
_T, _N, _J, _Q, _R, _W, _K = 16, 8, 4, 2, 3, 1, 1


@dataclasses.dataclass(frozen=True)
class ShapePoint:
    """One rung of the tier-C shape ladder: the abstract axis extents every
    entry point is traced at when the HBM audit asks "does this program fit
    at THIS scale".  Tier B traces at `_AUDIT_POINT` (the tiny historical
    extents — primitive coverage only); tier C re-traces the same builders
    at the bench shapes, the 50k×5k headline, and the 1M×100k north star,
    where peak live bytes are the production numbers.

    ``T``/``N``/``J`` are the padded capacity buckets (api.snapshot.bucket)
    for ``tasks``/``nodes`` pods/nodes; ``P``/``topk`` are the compacted
    [P, K] dispatch extents the production sizing would pick at this scale,
    and ``warm_*`` mirror api.resident's warm-carry plan for the same."""

    name: str
    tasks: int           # nominal pod count (pre-bucketing)
    nodes: int           # nominal node count (pre-bucketing)
    T: int               # task capacity bucket
    N: int               # node capacity bucket
    J: int               # job capacity bucket
    Q: int               # queue count
    R: int               # resource kinds
    W: int               # label/selector bitset words
    K_aff: int           # padded affinity rows
    P: int               # compacted pending bucket
    topk: int            # candidate width K of the [P, K] table
    warm_w: int          # warm carried-table stored width
    warm_c: int          # warm changed-node slots
    warm_pi: int         # warm rerank rung (re-ranked rows per refresh)
    probe_b: int = 2     # what-if probe batch
    probe_g: int = 4     # what-if gang width
    scatter_rows: int = 64  # resident scatter's device-ledger rows


#: tier B's extents as a ShapePoint — `build()` with no argument traces here
_AUDIT_POINT = ShapePoint(
    name="audit", tasks=_T, nodes=_N, T=_T, N=_N, J=_J, Q=_Q, R=_R, W=_W,
    K_aff=_K, P=8, topk=2, warm_w=4, warm_c=4, warm_pi=4,
    probe_b=2, probe_g=4, scatter_rows=64,
)


def shape_point(name: str, tasks: int, nodes: int, R: int = 8,
                W: int = 4) -> ShapePoint:
    """Derive a ladder point from nominal pod/node counts using the SAME
    sizing the production path uses: capacity buckets from
    api.snapshot.bucket, the pending bucket from actions.allocate's
    ``fit ≤ T // 4`` rule (largest fitting bucket = the worst case the
    audit must cover), and the warm plan's width/changed/rung arithmetic
    from api.resident.  Keeping these derivations shared — not copied —
    is the point: if the sizing rules move, the audit moves with them."""
    from kube_batch_tpu.actions.allocate import TOPK_DEFAULT, TOPK_PEND_BUCKETS
    from kube_batch_tpu.api.resident import (
        WARM_CHANGED_BUCKETS,
        WARM_WIDTH_MARGIN,
        warm_rerank_rungs,
    )
    from kube_batch_tpu.api.snapshot import bucket

    T, N = bucket(tasks), bucket(nodes)
    J = bucket(max(8, tasks // 4))
    fit = [b for b in TOPK_PEND_BUCKETS if b <= T // 4]
    P = fit[-1] if fit else TOPK_PEND_BUCKETS[0]
    k = TOPK_DEFAULT
    changed = [c for c in WARM_CHANGED_BUCKETS if c < N]
    warm_c = changed[-1] if changed else WARM_CHANGED_BUCKETS[0]
    return ShapePoint(
        name=name, tasks=tasks, nodes=nodes, T=T, N=N, J=J, Q=8, R=R, W=W,
        K_aff=4, P=P, topk=k, warm_w=k + WARM_WIDTH_MARGIN, warm_c=warm_c,
        warm_pi=warm_rerank_rungs(P)[-1], probe_b=2, probe_g=4,
        scatter_rows=N,
    )


def abstract_snapshot(T=_T, N=_N, J=_J, Q=_Q, R=_R, W=_W, K=_K):
    """A DeviceSnapshot of ShapeDtypeStructs — the audit's default small
    shapes, or caller-supplied bucket sizes (the bench traces the
    collective inventory at its REAL padded shapes so the byte counts are
    the production program's)."""
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from kube_batch_tpu.api.snapshot import DeviceSnapshot

    f32, i32, b, u32 = jnp.float32, jnp.int32, jnp.bool_, jnp.uint32
    return DeviceSnapshot(
        task_req=S((T, R), f32), task_resreq=S((T, R), f32),
        task_job=S((T,), i32), task_prio=S((T,), i32),
        task_creation=S((T,), i32), task_status=S((T,), i32),
        task_valid=S((T,), b), task_pending=S((T,), b),
        task_best_effort=S((T,), b), task_sel_bits=S((T, W), u32),
        task_sel_impossible=S((T,), b), task_tol_bits=S((T, W), u32),
        task_node=S((T,), i32), task_critical=S((T,), b),
        task_needs_host=S((T,), b), task_aff_idx=S((K,), i32),
        task_aff_mask=S((K, N), b), task_pref_idx=S((K,), i32),
        task_pref_node=S((K, N), f32), task_pref_pod=S((K, N), f32),
        node_idle=S((N, R), f32), node_releasing=S((N, R), f32),
        node_used=S((N, R), f32), node_alloc=S((N, R), f32),
        node_valid=S((N,), b), node_sched=S((N,), b),
        node_label_bits=S((N, W), u32), node_taint_bits=S((N, W), u32),
        job_min_avail=S((J,), i32), job_ready=S((J,), i32),
        job_queue=S((J,), i32), job_prio=S((J,), i32),
        job_creation=S((J,), i32), job_valid=S((J,), b),
        job_schedulable=S((J,), b), job_allocated=S((J, R), f32),
        queue_weight=S((Q,), f32), queue_capability=S((Q, R), f32),
        queue_alloc=S((Q, R), f32), queue_request=S((Q, R), f32),
        queue_valid=S((Q,), b), total=S((R,), f32), quanta=S((R,), f32),
    )


def _snap(ax: ShapePoint):
    return abstract_snapshot(
        T=ax.T, N=ax.N, J=ax.J, Q=ax.Q, R=ax.R, W=ax.W, K=ax.K_aff)


def _build_allocate(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig, allocate_solve

    ax = sp or _AUDIT_POINT
    return allocate_solve, (_snap(ax), AllocateConfig())


def _build_failure_histogram(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import failure_histogram_solve

    ax = sp or _AUDIT_POINT
    return failure_histogram_solve, (_snap(ax),)


#: audit-scale pending bucket + candidate width for the compacted solve
_P, _TOPK = 8, 2


def _abstract_pend_rows(P=_P):
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    return S((P,), jnp.int32)


def _build_topk_allocate(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig, allocate_topk_solve

    ax = sp or _AUDIT_POINT
    return allocate_topk_solve, (
        _snap(ax), _abstract_pend_rows(ax.P),
        AllocateConfig(topk=ax.topk),
    )


#: warm-carry audit shapes: stored width W, changed-node slots, rerank
#: rung — small audit extents like _T/_N, NOT the dispatch's real sizing
#: (W = K + WARM_WIDTH_MARGIN there); the traced primitives don't depend
#: on the extents
_WARM_W, _WARM_C, _WARM_PI = 2 * _TOPK, 4, 4


def _abstract_warm_args(P=_P, W=_WARM_W, C=_WARM_C, Pi=_WARM_PI):
    """(pend_rows, table×4, plan×4) ShapeDtypeStructs of the warm solve."""
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    return (
        S((P,), jnp.int32),
        S((P, W), jnp.int32), S((P, W), jnp.int32), S((P, W), jnp.int32),
        S((P,), jnp.bool_),
        S((P,), jnp.int32), S((C,), jnp.int32),
        S((Pi,), jnp.int32), S((Pi,), jnp.int32),
    )


def _warm_donation() -> Dict[str, Tuple[int, ...]]:
    # the warm solve donates the stale carried-table buffers into the
    # refresh everywhere donation is supported; CPU skips it.  Literal
    # positions (no ops.assignment import — the registry is built before
    # jax loads): must match ops.assignment.WARM_TABLE_ARGNUMS, which the
    # warm entry's KBT104 check pins per backend.
    return {"cpu": (), "*": (2, 3, 4, 5)}


def _warm_args_at(ax: ShapePoint):
    return _abstract_warm_args(P=ax.P, W=ax.warm_w, C=ax.warm_c, Pi=ax.warm_pi)


def _build_warm_allocate(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig, warm_solve_fn

    ax = sp or _AUDIT_POINT
    return warm_solve_fn(), (
        _snap(ax), *_warm_args_at(ax),
        AllocateConfig(topk=ax.warm_w), ax.topk,
    )


def _build_warm_sentinel(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.ops.invariants import warm_sentinel_solve_fn

    ax = sp or _AUDIT_POINT
    return warm_sentinel_solve_fn(), (
        _snap(ax), *_warm_args_at(ax),
        AllocateConfig(topk=ax.warm_w), ax.topk,
    )


def _build_bucket_histogram(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import failure_histogram_bucket_solve

    ax = sp or _AUDIT_POINT
    return failure_histogram_bucket_solve, (
        _snap(ax), _abstract_pend_rows(ax.P),
    )


def _build_topk_probe(sp: Optional[ShapePoint] = None):
    """The probe traced with a topk>0 config: the query plane reuses the
    session's AllocateConfig, and the probe's [G, N] head ignores the
    compaction knob by design (a gang's task axis is already tiny) — this
    entry pins that the knob stays inert on the probe program."""
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.ops.eviction import EvictConfig
    from kube_batch_tpu.ops.probe import probe_solve

    ax = sp or _AUDIT_POINT
    batch, rows = _abstract_probe_batch(
        B=ax.probe_b, G=ax.probe_g, R=ax.R, W=ax.W)
    return probe_solve, (
        _snap(ax), batch, rows, AllocateConfig(topk=ax.topk),
        EvictConfig(mode="preempt"), True,
    )


def _build_evict_reclaim(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.eviction import EvictConfig, evict_solve

    ax = sp or _AUDIT_POINT
    return evict_solve, (_snap(ax), EvictConfig(mode="reclaim"))


def _build_evict_preempt(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.eviction import EvictConfig, evict_solve

    ax = sp or _AUDIT_POINT
    return evict_solve, (_snap(ax), EvictConfig(mode="preempt"))


def _build_resident_scatter(sp: Optional[ShapePoint] = None):
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from kube_batch_tpu.api.resident import SCATTER_SLOTS, _scatter_fn

    ax = sp or _AUDIT_POINT
    return _scatter_fn(), (
        S((ax.scatter_rows, ax.R), jnp.float32),
        S((SCATTER_SLOTS,), jnp.int32),
        S((SCATTER_SLOTS, ax.R), jnp.float32),
    )


def _build_enqueue_gate(sp: Optional[ShapePoint] = None):
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from kube_batch_tpu.ops.admission import enqueue_gate_fn

    ax = sp or _AUDIT_POINT
    return enqueue_gate_fn(), (
        S((ax.J, ax.R), jnp.float32), S((ax.J,), jnp.bool_),
        S((ax.R,), jnp.float32), S((ax.R,), jnp.float32),
    )


def _build_pallas_round_head(sp: Optional[ShapePoint] = None):
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from kube_batch_tpu.ops.pallas_kernels import NODE_TILE, TASK_TILE, masked_best_node

    ax = sp or _AUDIT_POINT
    T, N = TASK_TILE, NODE_TILE  # one tile — grid multiples are guaranteed
    return masked_best_node, (
        S((T, N), jnp.float32), S((T, N), jnp.bool_), S((T, ax.R), jnp.float32),
        S((N, ax.R), jnp.float32), S((N, ax.R), jnp.float32), S((T,), jnp.bool_),
        S((ax.R,), jnp.float32), True,  # interpret=True: auditable off-TPU
    )


def _build_pallas_topk_blocks(sp: Optional[ShapePoint] = None):
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from kube_batch_tpu.ops.pallas_kernels import (
        NODE_TILE,
        TASK_TILE,
        masked_topk_blocks,
    )

    ax = sp or _AUDIT_POINT
    P, N = TASK_TILE, NODE_TILE
    return masked_topk_blocks, (
        S((P, N), jnp.float32), S((P, ax.R), jnp.float32),
        S((N, ax.R), jnp.float32), S((N, ax.R), jnp.float32),
        S((P,), jnp.int32), S((ax.R,), jnp.float32),
        0, True,  # n0=0, interpret=True: auditable off-TPU
    )


def _abstract_probe_batch(B=2, G=4, R=_R, W=_W):
    """A ProbeBatch of ShapeDtypeStructs + the [G] row oracle — the query
    plane's serving shapes at audit scale."""
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from kube_batch_tpu.ops.probe import ProbeBatch

    f32, i32, b, u32 = jnp.float32, jnp.int32, jnp.bool_, jnp.uint32
    batch = ProbeBatch(
        req=S((B, G, R), f32), valid=S((B, G), b),
        min_avail=S((B,), i32), queue=S((B,), i32), prio=S((B,), i32),
        sel_bits=S((B, W), u32), sel_impossible=S((B,), b),
        tol_bits=S((B, W), u32), min_res=S((B, R), f32),
        has_min_res=S((B,), b),
    )
    return batch, S((G,), i32)


def _build_probe(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.ops.eviction import EvictConfig
    from kube_batch_tpu.ops.probe import probe_solve

    ax = sp or _AUDIT_POINT
    batch, rows = _abstract_probe_batch(
        B=ax.probe_b, G=ax.probe_g, R=ax.R, W=ax.W)
    # with_evictions=True traces the superset program (head + admission +
    # histogram + the eviction probe's while_loop)
    return probe_solve, (
        _snap(ax), batch, rows, AllocateConfig(),
        EvictConfig(mode="preempt"), True,
    )


def _scatter_donation() -> Dict[str, Tuple[int, ...]]:
    # the resident scatter donates the stale device buffer everywhere
    # donation is supported; CPU skips it (api/resident.py's own gate)
    return {"cpu": (), "*": (0,)}


# ---- sentinel-fused solve variants (guard plane tier 1): the dispatch-
# facing programs are solve body + ops/invariants tail in ONE jaxpr — they
# must pass KBT101-104 like the bare solves (a sentinel that smuggled an
# f64 upcast or a host callback into every production dispatch would tax
# exactly the path it guards)


def _build_sentinel_allocate(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.ops.invariants import allocate_sentinel_solve

    ax = sp or _AUDIT_POINT
    return allocate_sentinel_solve, (_snap(ax), AllocateConfig())


def _build_sentinel_topk(sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.ops.invariants import allocate_topk_sentinel_solve

    ax = sp or _AUDIT_POINT
    return allocate_topk_sentinel_solve, (
        _snap(ax), _abstract_pend_rows(ax.P),
        AllocateConfig(topk=ax.topk),
    )


def _build_sentinel_evict(mode, sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.eviction import EvictConfig
    from kube_batch_tpu.ops.invariants import evict_sentinel_solve

    ax = sp or _AUDIT_POINT
    return evict_sentinel_solve, (
        _snap(ax), EvictConfig(mode=mode))


def _build_sentinel_gate(sp: Optional[ShapePoint] = None):
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from kube_batch_tpu.ops.invariants import enqueue_gate_sentinel_fn

    ax = sp or _AUDIT_POINT
    return enqueue_gate_sentinel_fn(), (
        S((ax.J, ax.R), jnp.float32), S((ax.J,), jnp.bool_),
        S((ax.R,), jnp.float32), S((ax.R,), jnp.float32),
    )


REGISTRY: Tuple[EntryPoint, ...] = (
    # the full-matrix allocate is the COLD oracle — steady=False by design;
    # the compacted topk/warm programs are what dispatches at scale
    EntryPoint("ops.assignment.allocate_solve", _build_allocate),
    EntryPoint("ops.assignment.allocate_topk_solve", _build_topk_allocate,
               steady=True),
    EntryPoint("ops.assignment.warm_allocate_solve", _build_warm_allocate,
               donate=_warm_donation(), steady=True),
    EntryPoint("ops.assignment.failure_histogram_solve",
               _build_failure_histogram),
    EntryPoint("ops.assignment.failure_histogram_bucket_solve",
               _build_bucket_histogram),
    # eviction runs inside production cycles — steady, so KBT202 pins the
    # known full-matrix bid planes (ROADMAP 1.(1)) via the allowlist
    EntryPoint("ops.eviction.evict_solve[reclaim]", _build_evict_reclaim,
               steady=True),
    EntryPoint("ops.eviction.evict_solve[preempt]", _build_evict_preempt,
               steady=True),
    EntryPoint("api.resident.scatter", _build_resident_scatter,
               donate=_scatter_donation(), steady=True),
    EntryPoint("ops.admission.enqueue_gate", _build_enqueue_gate,
               steady=True),
    EntryPoint("ops.pallas_kernels.masked_best_node",
               _build_pallas_round_head),
    EntryPoint("ops.pallas_kernels.masked_topk_blocks",
               _build_pallas_topk_blocks),
    EntryPoint("ops.probe.probe_solve", _build_probe, steady=True),
    EntryPoint("ops.probe.probe_solve[topk-inert]", _build_topk_probe,
               steady=True),
    EntryPoint("ops.invariants.allocate_sentinel_solve",
               _build_sentinel_allocate),
    EntryPoint("ops.invariants.allocate_topk_sentinel_solve",
               _build_sentinel_topk, steady=True),
    EntryPoint("ops.invariants.warm_allocate_sentinel_solve",
               _build_warm_sentinel, donate=_warm_donation(), steady=True),
    EntryPoint("ops.invariants.evict_sentinel_solve[reclaim]",
               lambda sp=None: _build_sentinel_evict("reclaim", sp),
               steady=True),
    EntryPoint("ops.invariants.evict_sentinel_solve[preempt]",
               lambda sp=None: _build_sentinel_evict("preempt", sp),
               steady=True),
    EntryPoint("ops.invariants.enqueue_gate_sentinel", _build_sentinel_gate,
               steady=True),
)


# --------------------------------------------------------------------------
# the mesh-sharded solve variants (ROADMAP follow-on): traced whenever the
# backend exposes ≥2 devices — on CPU a forced host-platform device count
# (XLA_FLAGS=--xla_force_host_platform_device_count=N; tier-1's conftest
# forces 8) stands in for a multi-device CI mesh, so KBT101-104 cover the
# sharded entry points without real hardware.  Single-device runs skip them
# (the registry is empty there, never silently "clean" — the CLI exit code
# reflects only what was actually traced).
# --------------------------------------------------------------------------


def _build_sharded_allocate(mesh, impl, sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.parallel.mesh import allocate_solve_fn

    ax = sp or _AUDIT_POINT
    return allocate_solve_fn(mesh, AllocateConfig(), impl=impl), (
        _snap(ax),)


def _build_sharded_topk(mesh, impl, sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.parallel.mesh import allocate_topk_solve_fn

    ax = sp or _AUDIT_POINT
    fn = allocate_topk_solve_fn(mesh, AllocateConfig(topk=ax.topk), impl=impl)
    return fn, (_snap(ax), _abstract_pend_rows(ax.P))


def _build_sharded_warm(mesh, impl, sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.parallel.mesh import warm_allocate_solve_fn

    ax = sp or _AUDIT_POINT
    fn = warm_allocate_solve_fn(
        mesh, AllocateConfig(topk=ax.warm_w), ax.topk, impl=impl)
    return fn, (_snap(ax), *_warm_args_at(ax))


def _build_sharded_sentinel_warm(mesh, impl, sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.parallel.mesh import (
        sentinel_warm_allocate_solve_fn,
    )

    ax = sp or _AUDIT_POINT
    fn = sentinel_warm_allocate_solve_fn(
        mesh, AllocateConfig(topk=ax.warm_w), ax.topk, impl=impl)
    return fn, (_snap(ax), *_warm_args_at(ax))


def _build_sharded_bucket_histogram(mesh, impl,
                                    sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.parallel.mesh import failure_histogram_bucket_fn

    ax = sp or _AUDIT_POINT
    fn = failure_histogram_bucket_fn(mesh, impl=impl)
    return fn, (_snap(ax), _abstract_pend_rows(ax.P))


def _build_sharded_histogram(mesh, impl, sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.parallel.mesh import failure_histogram_fn

    ax = sp or _AUDIT_POINT
    return failure_histogram_fn(mesh, impl=impl), (_snap(ax),)


def _build_sharded_evict(mesh, mode, impl, sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.eviction import EvictConfig
    from kube_batch_tpu.parallel.mesh import evict_solve_fn

    ax = sp or _AUDIT_POINT
    return evict_solve_fn(mesh, EvictConfig(mode=mode), impl=impl), (
        _snap(ax),)


def _build_sharded_sentinel_allocate(mesh, impl,
                                     sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.parallel.mesh import sentinel_allocate_solve_fn

    ax = sp or _AUDIT_POINT
    fn = sentinel_allocate_solve_fn(mesh, AllocateConfig(), impl=impl)
    return fn, (_snap(ax),)


def _build_sharded_sentinel_topk(mesh, impl,
                                 sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.parallel.mesh import sentinel_allocate_topk_solve_fn

    ax = sp or _AUDIT_POINT
    fn = sentinel_allocate_topk_solve_fn(
        mesh, AllocateConfig(topk=ax.topk), impl=impl)
    return fn, (_snap(ax), _abstract_pend_rows(ax.P))


def _build_sharded_sentinel_evict(mesh, mode, impl,
                                  sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.eviction import EvictConfig
    from kube_batch_tpu.parallel.mesh import sentinel_evict_solve_fn

    ax = sp or _AUDIT_POINT
    fn = sentinel_evict_solve_fn(mesh, EvictConfig(mode=mode), impl=impl)
    return fn, (_snap(ax),)


def _build_sharded_probe(mesh, impl, sp: Optional[ShapePoint] = None):
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.ops.eviction import EvictConfig
    from kube_batch_tpu.parallel.mesh import probe_solve_fn

    ax = sp or _AUDIT_POINT
    batch, rows = _abstract_probe_batch(
        B=ax.probe_b, G=ax.probe_g, R=ax.R, W=ax.W)
    fn = probe_solve_fn(
        mesh, AllocateConfig(), EvictConfig(mode="preempt"), True, impl=impl
    )
    return fn, (_snap(ax), batch, rows)


def _build_sharded_gate(mesh, sp: Optional[ShapePoint] = None):
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from kube_batch_tpu.parallel.mesh import enqueue_gate_solve_fn

    ax = sp or _AUDIT_POINT
    return enqueue_gate_solve_fn(mesh), (
        S((ax.J, ax.R), jnp.float32), S((ax.J,), jnp.bool_),
        S((ax.R,), jnp.float32), S((ax.R,), jnp.float32),
    )


def _build_shard_scatter(mesh, sp: Optional[ShapePoint] = None):
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from kube_batch_tpu.api.resident import (
        SHARD_SCATTER_SLOTS,
        _mesh_shard_scatter_fn,
    )
    from kube_batch_tpu.parallel.mesh import NODE_AXIS

    ax = sp or _AUDIT_POINT
    d = int(dict(mesh.shape)[NODE_AXIS])  # node-axis extent, not device count
    return _mesh_shard_scatter_fn(mesh), (
        S((ax.N, ax.R), jnp.float32),
        S((d, SHARD_SCATTER_SLOTS), jnp.int32),
        S((d, SHARD_SCATTER_SLOTS, ax.R), jnp.float32),
    )


def _build_repl_scatter(mesh, sp: Optional[ShapePoint] = None):
    import jax.numpy as jnp
    from jax import ShapeDtypeStruct as S

    from kube_batch_tpu.api.resident import SCATTER_SLOTS, _mesh_repl_scatter_fn

    ax = sp or _AUDIT_POINT
    return _mesh_repl_scatter_fn(mesh), (
        S((ax.T,), jnp.int32),
        S((SCATTER_SLOTS,), jnp.int32),
        S((SCATTER_SLOTS,), jnp.int32),
    )


def sharded_registry() -> Tuple[EntryPoint, ...]:
    """Entry points for the mesh-sharded solve path — empty on single-device
    backends (no mesh to shard over).  BOTH implementations are traced:
    the shard_map bodies (the production path — KBT101-104 must cover the
    authored-collective programs) and the pjit oracle (KB_SHARD_MAP=0), so
    neither can silently regress.  On ≥4-device backends a 2-D
    (tasks × nodes) mesh variant of the shard_map allocate body is traced
    too — the task-axis-sharded program is a distinct jaxpr (block
    slicing + task-axis all_gathers) and needs its own audit."""
    import functools

    import jax

    if len(jax.devices()) < 2:
        return ()
    from kube_batch_tpu.parallel.mesh import make_mesh

    # _N (8) must divide the mesh for the per-shard scatter's local indexing
    n_dev = len(jax.devices())
    while n_dev > 1 and _N % n_dev:
        n_dev -= 1
    mesh = make_mesh(n_dev)
    p = functools.partial
    entries = []
    for impl in ("shard_map", "pjit"):
        tag = f"[{impl}]"
        entries += [
            EntryPoint(f"parallel.mesh.sharded_allocate_solve{tag}",
                       p(_build_sharded_allocate, mesh, impl)),
            EntryPoint(f"parallel.mesh.sharded_allocate_topk_solve{tag}",
                       p(_build_sharded_topk, mesh, impl), steady=True),
            EntryPoint(f"parallel.mesh.sharded_warm_allocate_solve{tag}",
                       p(_build_sharded_warm, mesh, impl), steady=True),
            EntryPoint(
                f"parallel.mesh.sentinel_sharded_warm_allocate_solve{tag}",
                p(_build_sharded_sentinel_warm, mesh, impl), steady=True),
            EntryPoint(f"parallel.mesh.sharded_failure_histogram{tag}",
                       p(_build_sharded_histogram, mesh, impl)),
            EntryPoint(
                f"parallel.mesh.sharded_failure_histogram_bucket{tag}",
                p(_build_sharded_bucket_histogram, mesh, impl)),
            EntryPoint(f"parallel.mesh.sharded_evict_solve[reclaim]{tag}",
                       p(_build_sharded_evict, mesh, "reclaim", impl),
                       steady=True),
            EntryPoint(f"parallel.mesh.sharded_evict_solve[preempt]{tag}",
                       p(_build_sharded_evict, mesh, "preempt", impl),
                       steady=True),
            EntryPoint(f"parallel.mesh.sharded_probe_solve{tag}",
                       p(_build_sharded_probe, mesh, impl), steady=True),
            EntryPoint(f"parallel.mesh.sentinel_sharded_allocate_solve{tag}",
                       p(_build_sharded_sentinel_allocate, mesh, impl)),
            EntryPoint(
                f"parallel.mesh.sentinel_sharded_allocate_topk_solve{tag}",
                p(_build_sharded_sentinel_topk, mesh, impl), steady=True),
            EntryPoint(
                f"parallel.mesh.sentinel_sharded_evict_solve[reclaim]{tag}",
                p(_build_sharded_sentinel_evict, mesh, "reclaim", impl),
                steady=True),
            EntryPoint(
                f"parallel.mesh.sentinel_sharded_evict_solve[preempt]{tag}",
                p(_build_sharded_sentinel_evict, mesh, "preempt", impl),
                steady=True),
        ]
    entries += [
        EntryPoint("parallel.mesh.sharded_enqueue_gate",
                   p(_build_sharded_gate, mesh), steady=True),
        EntryPoint("api.resident.scatter_sharded",
                   p(_build_shard_scatter, mesh),
                   donate=_scatter_donation(), steady=True),
        EntryPoint("api.resident.scatter_repl",
                   p(_build_repl_scatter, mesh),
                   donate=_scatter_donation(), steady=True),
    ]
    if n_dev >= 4 and n_dev % 2 == 0 and _T % 2 == 0:
        mesh2 = make_mesh(n_dev, task_shards=2)
        entries.append(EntryPoint(
            "parallel.mesh.sharded_allocate_solve[shard_map,2d]",
            p(_build_sharded_allocate, mesh2, "shard_map"),
        ))
    return tuple(entries)


# --------------------------------------------------------------------------
# jaxpr walking
# --------------------------------------------------------------------------


def _iter_jaxprs(jaxpr) -> Iterable:
    """The jaxpr and every sub-jaxpr reachable through eqn params
    (pjit/while/cond/scan/pallas bodies)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for param in eqn.params.values():
            vals = param if isinstance(param, (list, tuple)) else [param]
            for sub in vals:
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    yield from _iter_jaxprs(inner)
                elif hasattr(sub, "eqns"):
                    yield from _iter_jaxprs(sub)


def _eqn_dtypes(eqn) -> Iterable[str]:
    for v in eqn.outvars:
        aval = getattr(v, "aval", None)
        dtype = getattr(aval, "dtype", None)
        if dtype is not None:
            yield str(dtype)


def _real_transfer(eqn) -> bool:
    """True when a device_put eqn moves data for real: a concrete target
    device/src, or copy semantics beyond the benign alias placement that
    jnp constant materialization emits."""
    devices = eqn.params.get("devices", [])
    srcs = eqn.params.get("srcs", [])
    if any(d is not None for d in devices) or any(s is not None for s in srcs):
        return True
    semantics = eqn.params.get("copy_semantics", [])
    return any(getattr(s, "name", str(s)) not in ("ALIAS",) for s in semantics)


def audit_entry(entry: EntryPoint) -> List[Finding]:
    """Trace one entry point and lint its closed jaxpr.  Returns findings
    (suppressed ones dropped; an allow with no reason is itself a KBT000,
    mirroring the static tier's contract)."""
    import jax
    from jax.experimental import enable_x64

    path = f"<jaxpr:{entry.name}>"
    findings: List[Finding] = []
    raw: List[Tuple[str, str]] = []  # (rule, message)

    try:
        fn, args = entry.build()
        with enable_x64():
            traced = fn.trace(*args)
        closed = traced.jaxpr
    except Exception as e:  # noqa: BLE001 — a broken entry must not read as clean
        return [Finding("KBT000", path, 0, 0,
                        f"entry point failed to trace: {type(e).__name__}: {e}")]

    f64_prims: List[str] = []
    transfers: List[str] = []
    callbacks: List[str] = []
    for jaxpr in _iter_jaxprs(closed.jaxpr):
        for eqn in jaxpr.eqns:
            prim = str(eqn.primitive)
            if prim == "device_put":
                if _real_transfer(eqn):
                    transfers.append(prim)
                continue
            if prim in _CALLBACK_PRIMS:
                callbacks.append(prim)
                continue
            if any(dt == "float64" for dt in _eqn_dtypes(eqn)):
                f64_prims.append(prim)
    if f64_prims:
        uniq = sorted(set(f64_prims))
        raw.append((
            "KBT101",
            f"float64 values produced by {', '.join(uniq)} "
            f"({len(f64_prims)} eqn(s)) — the snapshot contract is f32; an "
            "f64 upcast doubles buffer traffic and flips TPU matmuls to "
            "the slow path",
        ))
    if transfers:
        raw.append((
            "KBT102",
            f"{len(transfers)} in-graph device transfer(s) — a device_put "
            "with a concrete placement inside a compiled program is a "
            "mid-solve copy; inputs should arrive placed (resident cache)",
        ))
    if callbacks:
        raw.append((
            "KBT103",
            f"host callback(s) {sorted(set(callbacks))} inside a compiled "
            "hot-path program — one host round-trip per invocation",
        ))

    expected = entry.donate.get(
        jax.default_backend(), entry.donate.get("*", ()))
    actual = tuple(sorted(traced.donate_argnums or ()))
    if tuple(sorted(expected)) != actual:
        raw.append((
            "KBT104",
            f"wrapper donates argnums {actual}, registry declares "
            f"{tuple(sorted(expected))} for backend "
            f"'{jax.default_backend()}' — donation silently changed "
            "(double-allocation on device, or a read of a buffer the "
            "caller thinks it still owns)",
        ))

    for rule, message in raw:
        reason = entry.allow.get(rule)
        if reason is not None:
            if not reason.strip():
                findings.append(Finding(
                    "KBT000", path, 0, 0,
                    f"allow[{rule}] has no reason — suppression ignored",
                ))
            continue
        findings.append(Finding(rule, path, 0, 0, message))
    return findings


def run_audit(
    registry: Optional[Sequence[EntryPoint]] = None,
    select: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Audit every registered entry point — the single-device REGISTRY plus,
    on multi-device backends, the mesh-sharded variants.  ``select``
    restricts to a rule subset (CLI --select parity with the static
    tier)."""
    if registry is None:
        registry = tuple(REGISTRY) + sharded_registry()
    findings: List[Finding] = []
    for entry in registry:
        findings.extend(audit_entry(entry))
    if select is not None:
        wanted = set(select) | {"KBT000"}
        findings = [f for f in findings if f.rule in wanted]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
