"""kbt-check tier D: static thread/lock-domain race analysis (KBT301-304).

The runtime is now a deliberately threaded system — the pipelined cycle
(PR 9) overlaps a writeback worker with the next cycle's ingest drain and
solve, watch threads feed the cache, the what-if batcher and the
replication publisher/follower run their own workers, and every
AdminServer request gets its own thread.  The paper's Go scheduler guarded
all of this with one big mutex; the rebuild splits that into a lock
hierarchy (cache big lock, leaf ingest/dispatch locks, the broker and
batcher condition variables), and the load-bearing invariant underneath is
simple to state and easy to silently break: *every shared mutable
attribute is consistently guarded by the same lock on every thread root
that touches it*.  Lockdep (tier runtime, PR 2/4) catches lock-ORDER
mistakes but says nothing about a field some path forgot to lock at all —
the bug class ``go test -race`` exists for.  Tier D is the static
equivalent, built on the tier-A engine and dataflow walker:

1. **Thread-root graph** — enumerate the code paths that run on distinct
   threads: functions handed to ``threading.Thread``/``Timer``, pool
   ``submit``/``map`` targets, HTTP handler methods (``do_*`` — the
   ThreadingHTTPServer gives every request its own thread, so handlers are
   additionally concurrent with THEMSELVES), and public methods of
   lock-owning classes (a class that created a lock has declared itself
   multi-threaded; its public surface can be entered from any thread —
   this is how cross-module roots like the watch callbacks and admin
   handlers reach a class without whole-program analysis).  Membership
   propagates through same-module calls; everything else is the "main"
   (cycle) root.  ``testing/`` is excluded — its threads are pytest-only
   harness roots.

2. **Lock-domain inference** — per class, a with-block region walk over
   every method records each ``self.<attr>`` access together with the set
   of lock attributes (``threading.Lock``/``RLock``/``Condition``
   instances assigned to ``self``) lexically held around it.  Private
   helpers whose every in-module call site holds lock L are credited with
   L (the ``_locked``-helper idiom — without this, every ``*_locked``
   body would be a false positive).  The lock that dominates an
   attribute's guarded accesses is its *domain*; the full per-class map is
   a reviewable report (``--domains``).

3. **Rules** (each grounded in a bug class this codebase has actually
   carried — see ANALYSIS.md):

   - KBT301: an attribute guarded by its domain lock on one thread root
     but accessed lock-free (or under a different lock) on another.
   - KBT302: live mutable containers (dict/list/set/deque attributes)
     handed to another thread (pool submit/map args, Thread args) without
     a value-snapshot (``dict(x)``/``list(x)``/``.copy()``) — the
     generalized StatusFlush double-buffer contract.  Subsumes KBT012
     (the writeback-stage instance), whose id stays as a ``--select``
     alias.
   - KBT303: check-then-act on a shared attribute outside its domain lock
     (test and act both lock-free — the lost-update window).
   - KBT304: the lazy-init special case of 303 (``if self.x is None:
     self.x = ...`` without the lock).  The sanctioned double-checked
     idiom — lock-free peek, then re-check and assign UNDER the lock —
     does not fire: only a lock-free *assignment* reports.

Suppression is the established ``# kbt: allow[KBT30x] reason`` contract.
The runtime corroborator (analysis/lockdep.py ``install_guarded_access``)
consumes this module's inferred domains to assert, at access time in the
test suite, that the domain lock is actually held on hot shared
structures — the static map and the runtime behavior cross-validate the
way tier B's jaxpr audit corroborates tier A.  The runtime-side escape
hatch is ``kube_batch_tpu.utils.blocking.allow_unguarded`` so product
code never imports this engine.

Known approximation directions (deliberate, like the tier-A walker):
- UNDER: cross-module calls (a bound method stored as a callback and
  invoked from another module's thread) are invisible unless the callee's
  class owns a lock; ``lock.acquire()``/``release()`` outside a ``with``
  is not credited; attributes never accessed under ANY lock have no
  domain and are skipped (KBT003 owns module globals; wholly unguarded
  classes are a design smell this tier cannot rank).
- OVER: "public method of a lock-owning class" assumes any-thread entry,
  and construction-time calls into helpers count as main-root calls —
  both can flag code that is dynamically single-threaded; that is what
  the annotation contract (with a mandatory reason) is for.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import Counter
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from kube_batch_tpu.analysis.dataflow import (
    FlowEvent, FlowVisitor, ModuleContext, call_keyword, walk_function,
)
from kube_batch_tpu.analysis.engine import Rule

#: tier-D path exclusions: testing/ spawns threads only under pytest (the
#: benchmark/e2e harness) — those are pytest-only roots per the tier spec
EXCLUDED_PREFIXES = ("testing/",)

#: select alias: the old writeback-handoff rule is a KBT302 instance now.
#: Defined in engine.py (so allow-comment resolution sees it too) and
#: re-exported here for the CLI and tests.
from kube_batch_tpu.analysis.engine import RULE_ALIASES  # noqa: F401,E402

MAIN_ROOT = "main"
#: the any-thread root: HTTP handlers and the public surface of
#: lock-owning classes; concurrent with every root INCLUDING itself
EXT_ROOT = "ext"

LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "kube_batch_tpu.analysis.lockdep.TrackedLock",
}
#: attributes bound to these are internally synchronized (or per-thread)
#: by construction — excluded from the domain map and the rules
SAFE_FACTORIES = {
    "threading.local", "threading.Event", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue",
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
    "logging.getLogger",
}
CONTAINER_FACTORIES = {
    "collections.deque", "collections.defaultdict",
    "collections.OrderedDict", "collections.Counter",
}
CONTAINER_BUILTINS = {"dict", "list", "set"}
#: sanctioned snapshot constructors for a cross-thread handoff (KBT302)
SNAPSHOT_CALLS = {"dict", "list", "set", "tuple", "frozenset", "sorted"}
SNAPSHOT_METHODS = {"copy"}
#: method calls that mutate a container in place (count as writes)
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "add", "update",
    "insert", "remove", "discard", "pop", "popitem", "popleft", "clear",
    "setdefault", "sort", "reverse",
}
HTTP_HANDLER_METHODS = {
    "do_GET", "do_POST", "do_PUT", "do_DELETE", "do_PATCH", "do_HEAD",
}
INIT_METHODS = {"__init__", "__new__", "__post_init__"}
#: submit-shaped pool entry points: first arg runs on a worker thread
POOL_SPAWN_ATTRS = {"submit", "map"}


# --------------------------------------------------------------------------
# module scan: function index, spawn seeds, class lock/access regions
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _FuncInfo:
    qual: str
    node: ast.AST
    cls: Optional[str]          # immediate enclosing class name
    name: str                   # bare name


@dataclasses.dataclass
class Access:
    attr: str
    line: int
    col: int
    write: bool
    held: FrozenSet[str]        # lexically held lock attrs
    qual: str                   # function the access executes in
    extra_key: Optional[str]    # method name for caller-held credit
    in_init: bool


@dataclasses.dataclass
class CheckAct:
    attr: str
    test_line: int
    test_col: int
    test_held: FrozenSet[str]
    write_line: int
    write_held: FrozenSet[str]
    lazy: bool                  # `is None` test → KBT304, else KBT303
    qual: str
    extra_key: Optional[str]


@dataclasses.dataclass
class Handoff:                  # KBT302: live container crossing threads
    attr: str
    line: int
    col: int
    qual: str
    via: str                    # "submit" | "thread"


@dataclasses.dataclass
class _CallSite:
    callee: str                 # bare method name (same class)
    held: FrozenSet[str]
    caller_key: Optional[str]   # caller method name (None inside closures)
    from_init: bool


class ClassScan:
    def __init__(self, name: str):
        self.name = name
        self.lock_attrs: Dict[str, int] = {}      # attr -> def line
        self.safe_attrs: Set[str] = set()
        self.container_attrs: Set[str] = set()
        self.accesses: List[Access] = []
        self.check_acts: List[CheckAct] = []
        self.handoffs: List[Handoff] = []
        self.call_sites: List[_CallSite] = []
        self.methods: Dict[str, ast.AST] = {}     # bare name -> def node
        self.seed_methods: Set[str] = set()       # spawn targets
        #: caller-held credit for private helpers (the `_locked` idiom)
        self.extra_held: Dict[str, FrozenSet[str]] = {}


def _self_attr(node: ast.AST) -> Optional[str]:
    """`self.X` → 'X' (direct attribute on the literal name `self`)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _getattr_self_attr(call: ast.Call) -> Optional[str]:
    """`getattr(self, "X", ...)` / `setattr(self, "X", v)` → 'X'."""
    if (isinstance(call.func, ast.Name)
            and call.func.id in ("getattr", "setattr")
            and len(call.args) >= 2
            and isinstance(call.args[0], ast.Name)
            and call.args[0].id == "self"):
        return _const_str(call.args[1])
    return None


class _RaceModule:
    """Everything tier D derives from one module, built once per file and
    shared by the four rules (memoized on the engine's ModuleContext)."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.funcs: Dict[str, _FuncInfo] = {}
        self.classes: Dict[str, ClassScan] = {}
        self.edges: Dict[str, Set[str]] = {}      # same-module call graph
        self.seeds: Dict[str, Set[str]] = {}      # qual -> base roots
        self.roots: Dict[str, FrozenSet[str]] = {}
        self._index(ctx.tree)
        self._scan_classes()
        self._collect_spawns_and_edges()
        self._propagate_roots()
        self._credit_caller_held()

    # -- function index ----------------------------------------------------
    def _index(self, tree: ast.Module) -> None:
        def visit(node: ast.AST, owner: str, cls: Optional[str],
                  owner_is_func: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if owner_is_func:
                        qual = f"{owner}.<locals>.{child.name}"
                    elif cls:
                        qual = f"{cls}.{child.name}"
                    else:
                        qual = child.name
                    self.funcs[qual] = _FuncInfo(qual, child, cls, child.name)
                    visit(child, qual, cls, True)
                elif isinstance(child, ast.ClassDef):
                    # innermost class wins (nested handler classes)
                    visit(child, child.name, child.name, False)
                else:
                    visit(child, owner, cls, owner_is_func)

        visit(tree, "", None, False)

    # -- per-class region scan ---------------------------------------------
    def _scan_classes(self) -> None:
        for info in self.funcs.values():
            if info.cls is None:
                continue
            scan = self.classes.setdefault(info.cls, ClassScan(info.cls))
            if "<locals>" not in info.qual:
                scan.methods[info.name] = info.node
        # pass 1: lock / safe / container attribute classification —
        # needed before the access walk can compute held sets
        for cls, scan in self.classes.items():
            for name, node in scan.methods.items():
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    for t in sub.targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        self._classify(scan, attr, sub.value, t.lineno)
        # pass 2: the held-region access walk over every top-level method
        for cls, scan in self.classes.items():
            for name, node in sorted(scan.methods.items()):
                _MethodScan(self, scan, name, node).run()

    def _classify(self, scan: ClassScan, attr: str, value: ast.expr,
                  line: int) -> None:
        if isinstance(value, ast.Call):
            dotted = self.ctx.imports.dotted(value.func)
            if dotted in LOCK_FACTORIES:
                scan.lock_attrs.setdefault(attr, line)
                return
            if dotted in SAFE_FACTORIES:
                scan.safe_attrs.add(attr)
                return
            if dotted in CONTAINER_FACTORIES or (
                    isinstance(value.func, ast.Name)
                    and value.func.id in CONTAINER_BUILTINS):
                scan.container_attrs.add(attr)
                return
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                              ast.ListComp, ast.SetComp)):
            scan.container_attrs.add(attr)

    # -- spawn seeds + same-module call edges -------------------------------
    def _resolve_target(self, node: ast.AST, caller: _FuncInfo
                        ) -> Optional[str]:
        """A callable expression → the qual of the function it names."""
        attr = _self_attr(node)
        if attr is not None and caller.cls is not None:
            qual = f"{caller.cls}.{attr}"
            return qual if qual in self.funcs else None
        if isinstance(node, ast.Name):
            nested = f"{caller.qual}.<locals>.{node.id}"
            if nested in self.funcs:
                return nested
            if node.id in self.funcs:
                return node.id
        return None

    def _spawn_target(self, call: ast.Call, caller: _FuncInfo
                      ) -> Optional[Tuple[str, str]]:
        """(target qual, kind) when `call` starts a thread on `target`."""
        dotted = self.ctx.imports.dotted(call.func)
        cand: Optional[ast.AST] = None
        kind = "thread"
        if dotted == "threading.Thread":
            cand = call_keyword(call, "target")
        elif dotted == "threading.Timer":
            cand = call_keyword(call, "function") or (
                call.args[1] if len(call.args) > 1 else None)
        elif (isinstance(call.func, ast.Attribute)
                and call.func.attr in POOL_SPAWN_ATTRS and call.args):
            cand, kind = call.args[0], "submit"
        if cand is None:
            return None
        qual = self._resolve_target(cand, caller)
        return (qual, kind) if qual is not None else None

    def _collect_spawns_and_edges(self) -> None:
        for info in self.funcs.values():
            callees: Set[str] = set()
            for sub in self._own_nodes(info.node):
                if not isinstance(sub, ast.Call):
                    continue
                spawn = self._spawn_target(sub, info)
                if spawn is not None:
                    qual, _ = spawn
                    self.seeds.setdefault(qual, set()).add(f"worker:{qual}")
                    target = self.funcs[qual]
                    if target.cls is not None:
                        self.classes[target.cls].seed_methods.add(target.name)
                    continue  # registration is not a same-thread call
                callee = self._resolve_target(sub.func, info)
                if callee is not None:
                    callees.add(callee)
            self.edges[info.qual] = callees
            if info.name in HTTP_HANDLER_METHODS and info.cls is not None:
                self.seeds.setdefault(info.qual, set()).add(EXT_ROOT)
            elif (info.cls is not None and "<locals>" not in info.qual
                    and not info.name.startswith("_")
                    and self.classes[info.cls].lock_attrs):
                # public surface of a lock-owning class: any-thread entry
                self.seeds.setdefault(info.qual, set()).add(EXT_ROOT)
            # dunders other than __init__ are public surface too
            elif (info.cls is not None and "<locals>" not in info.qual
                    and info.name.startswith("__")
                    and info.name not in INIT_METHODS
                    and self.classes[info.cls].lock_attrs):
                self.seeds.setdefault(info.qual, set()).add(EXT_ROOT)

    def _own_nodes(self, func: ast.AST) -> Iterable[ast.AST]:
        """Walk a function body, excluding nested function scopes (they are
        indexed as their own functions)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(func))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- root propagation ---------------------------------------------------
    def _propagate_roots(self) -> None:
        member: Dict[str, Set[str]] = {
            q: set(self.seeds.get(q, ())) for q in self.funcs}

        def flow() -> None:
            changed = True
            while changed:
                changed = False
                for caller, callees in self.edges.items():
                    for callee in callees:
                        if callee not in member:
                            continue
                        before = len(member[callee])
                        member[callee] |= member[caller]
                        changed = changed or len(member[callee]) != before

        flow()
        # nested closures inherit their definer's roots unless they are
        # spawn seeds themselves (a worker body defined inline)
        for qual in self.funcs:
            if "<locals>" in qual and not member[qual]:
                definer = qual.split(".<locals>.")[0]
                member[qual] |= member.get(definer, set())
        # whatever nothing reaches runs on the caller's thread: the cycle
        # body, module entry points, plain-class public methods
        for qual, roots in member.items():
            if not roots:
                roots.add(MAIN_ROOT)
        flow()
        self.roots = {q: frozenset(r) for q, r in member.items()}

    # -- caller-held credit for private helpers -----------------------------
    def _credit_caller_held(self) -> None:
        """A private method whose EVERY non-__init__ in-module call site
        holds lock L is analyzed as holding L — the `*_locked` helper
        idiom.  Spawn seeds are excluded: the registration site's locks
        are NOT held when the worker later runs.  A ``*_locked``-SUFFIXED
        method is additionally credited by its name: the suffix is this
        codebase's documented "caller holds the lock" contract, and such
        methods are routinely passed around as callbacks (the resync
        apply), where no in-module call site exists to intersect over —
        the runtime corroborator is what checks the name keeps its
        promise."""
        for scan in self.classes.values():
            all_locks = frozenset(scan.lock_attrs)
            for name in scan.methods:
                if name.endswith("_locked") and name not in scan.seed_methods:
                    scan.extra_held[name] = all_locks
            for _ in range(4):  # propagate helper→helper chains
                for name in scan.methods:
                    if (not name.startswith("_") or name in INIT_METHODS
                            or name in scan.seed_methods
                            or name.endswith("_locked")):
                        continue
                    sites = [s for s in scan.call_sites
                             if s.callee == name and not s.from_init]
                    if not sites:
                        continue
                    held = None
                    for s in sites:
                        eff = s.held | scan.extra_held.get(
                            s.caller_key or "", frozenset())
                        held = eff if held is None else (held & eff)
                    scan.extra_held[name] = frozenset(held or ())

    # -- effective held / concurrency helpers ------------------------------
    def held_of(self, scan: ClassScan, held: FrozenSet[str],
                extra_key: Optional[str]) -> FrozenSet[str]:
        if extra_key is None:
            return held
        return held | scan.extra_held.get(extra_key, frozenset())

    def roots_of(self, qual: str) -> FrozenSet[str]:
        return self.roots.get(qual, frozenset((MAIN_ROOT,)))


def _concurrent(a: FrozenSet[str], b: FrozenSet[str]) -> bool:
    """Can code on roots `a` run concurrently with code on roots `b`?
    The ext root is concurrent with everything, itself included (many
    handler threads); otherwise two DISTINCT roots are required."""
    if EXT_ROOT in a or EXT_ROOT in b:
        return True
    return any(r1 != r2 for r1 in a for r2 in b)


# --------------------------------------------------------------------------
# per-method held-region walk
# --------------------------------------------------------------------------


class _MethodScan:
    """Walk one method recording every `self.<attr>` access with the set
    of lock attributes lexically held around it, plus check-then-act
    shapes (If tests reading an attr whose body writes it)."""

    def __init__(self, mod: _RaceModule, scan: ClassScan, name: str,
                 node: ast.AST):
        self.mod = mod
        self.scan = scan
        self.method = name
        self.node = node
        self.in_init = name in INIT_METHODS

    def run(self) -> None:
        self._stmts(self.node.body, frozenset(), f"{self.scan.name}."
                    f"{self.method}", self.method, [])

    # -- access recording ---------------------------------------------------
    def _record(self, attr: str, node: ast.AST, write: bool,
                held: FrozenSet[str], qual: str, key: Optional[str],
                if_stack: List[Tuple[Dict[str, Tuple[int, int, bool]],
                                     FrozenSet[str]]]) -> None:
        if attr in self.scan.lock_attrs or attr in self.scan.methods:
            return  # lock handles and bound-method references are not data
        self.scan.accesses.append(Access(
            attr, node.lineno, node.col_offset, write, held, qual, key,
            self.in_init))
        if write and not self.in_init:
            # pair the act with EVERY enclosing frame that tested the attr,
            # not just the nearest: the double-checked idiom's outer peek
            # (lock-free test, locked re-check + write) is only recognized
            # as sanctioned if the outer frame also yields a CheckAct
            for tests, test_held in reversed(if_stack):
                if attr in tests:
                    line, col, lazy = tests[attr]
                    self.scan.check_acts.append(CheckAct(
                        attr, line, col, test_held, node.lineno, held,
                        lazy, qual, key))

    def _expr(self, node: Optional[ast.AST], held, qual, key, if_stack,
              store: bool = False) -> None:
        if node is None or isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.Lambda, ast.ClassDef)):
            return
        attr = _self_attr(node)
        if attr is not None:
            write = store or isinstance(node.ctx, (ast.Store, ast.Del))
            self._record(attr, node, write, held, qual, key, if_stack)
            return
        if isinstance(node, ast.Call):
            # same-class `self.m(...)`: a call site for caller-held credit
            callee = _self_attr(node.func)
            if callee is not None and callee in self.scan.methods:
                self.scan.call_sites.append(_CallSite(
                    callee, held, key, self.in_init))
            # self.X.append(...) — in-place container mutation is a write
            if isinstance(node.func, ast.Attribute):
                recv = _self_attr(node.func.value)
                if recv is not None and node.func.attr in MUTATOR_METHODS:
                    self._record(recv, node.func.value, True, held, qual,
                                 key, if_stack)
                    for a in node.args:
                        self._expr(a, held, qual, key, if_stack)
                    for kw in node.keywords:
                        self._expr(kw.value, held, qual, key, if_stack)
                    return
            ga = _getattr_self_attr(node)
            if ga is not None:
                write = (isinstance(node.func, ast.Name)
                         and node.func.id == "setattr")
                self._record(ga, node, write, held, qual, key, if_stack)
        if store and isinstance(node, ast.Subscript):
            recv = _self_attr(node.value)
            if recv is not None:
                # self.X[k] = v mutates the container bound at X
                self._record(recv, node.value, True, held, qual, key,
                             if_stack)
                self._expr(node.slice, held, qual, key, if_stack)
                return
        if store and isinstance(node, ast.Attribute):
            recv = _self_attr(node.value)
            if recv is not None:
                # self.X.field = v mutates the OBJECT bound at X in place
                self._record(recv, node.value, True, held, qual, key,
                             if_stack)
                return
        for child in ast.iter_child_nodes(node):
            self._expr(child, held, qual, key, if_stack)

    def _assign_target(self, t: ast.AST, held, qual, key, if_stack) -> None:
        attr = _self_attr(t)
        if attr is not None:
            self._record(attr, t, True, held, qual, key, if_stack)
            return
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._assign_target(e, held, qual, key, if_stack)
            return
        if isinstance(t, ast.Starred):
            self._assign_target(t.value, held, qual, key, if_stack)
            return
        self._expr(t, held, qual, key, if_stack, store=True)

    # -- statements ---------------------------------------------------------
    def _stmts(self, stmts, held, qual, key, if_stack) -> None:
        for s in stmts:
            self._stmt(s, held, qual, key, if_stack)

    def _stmt(self, s: ast.stmt, held, qual, key, if_stack) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a closure runs later, usually on another thread, with NO
            # lexically captured lock held; roots come from the spawn graph
            nested = f"{qual}.<locals>.{s.name}"
            self._stmts(s.body, frozenset(), nested, None, [])
            return
        if isinstance(s, ast.ClassDef):
            return
        if isinstance(s, (ast.With, ast.AsyncWith)):
            acquired: Set[str] = set()
            for item in s.items:
                attr = _self_attr(item.context_expr)
                if attr is not None and attr in self.scan.lock_attrs:
                    acquired.add(attr)
                else:
                    self._expr(item.context_expr, held, qual, key, if_stack)
                if item.optional_vars is not None:
                    self._assign_target(item.optional_vars, held, qual,
                                        key, if_stack)
            self._stmts(s.body, held | acquired, qual, key, if_stack)
            return
        if isinstance(s, ast.If):
            tests: Dict[str, Tuple[int, int, bool]] = {}
            self._collect_test_attrs(s.test, tests)
            self._expr(s.test, held, qual, key, if_stack)
            self._stmts(s.body, held, qual, key, if_stack + [(tests, held)])
            self._stmts(s.orelse, held, qual, key, if_stack)
            return
        if isinstance(s, ast.While):
            self._expr(s.test, held, qual, key, if_stack)
            self._stmts(s.body, held, qual, key, if_stack)
            self._stmts(s.orelse, held, qual, key, if_stack)
            return
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._expr(s.iter, held, qual, key, if_stack)
            self._assign_target(s.target, held, qual, key, if_stack)
            self._stmts(s.body, held, qual, key, if_stack)
            self._stmts(s.orelse, held, qual, key, if_stack)
            return
        if isinstance(s, ast.Try):
            self._stmts(s.body, held, qual, key, if_stack)
            for h in s.handlers:
                self._stmts(h.body, held, qual, key, if_stack)
            self._stmts(s.orelse, held, qual, key, if_stack)
            self._stmts(s.finalbody, held, qual, key, if_stack)
            return
        if isinstance(s, ast.Assign):
            self._expr(s.value, held, qual, key, if_stack)
            for t in s.targets:
                self._assign_target(t, held, qual, key, if_stack)
            return
        if isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._expr(s.value, held, qual, key, if_stack)
            self._assign_target(s.target, held, qual, key, if_stack)
            return
        if isinstance(s, ast.AugAssign):
            self._expr(s.value, held, qual, key, if_stack)
            self._assign_target(s.target, held, qual, key, if_stack)
            return
        if isinstance(s, (ast.Expr, ast.Return)):
            self._expr(s.value, held, qual, key, if_stack)
            return
        if isinstance(s, ast.Match):
            self._expr(s.subject, held, qual, key, if_stack)
            for case in s.cases:
                if case.guard is not None:
                    self._expr(case.guard, held, qual, key, if_stack)
                self._stmts(case.body, held, qual, key, if_stack)
            return
        for child in ast.iter_child_nodes(s):
            if isinstance(child, ast.expr):
                self._expr(child, held, qual, key, if_stack)

    def _collect_test_attrs(self, test: ast.AST,
                            out: Dict[str, Tuple[int, int, bool]]) -> None:
        lazy_attr = None
        if (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.ops[0], ast.Is)
                and isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value is None):
            lazy_attr = _self_attr(test.left)
            if lazy_attr is None and isinstance(test.left, ast.Call):
                lazy_attr = _getattr_self_attr(test.left)
        for sub in ast.walk(test):
            attr = _self_attr(sub)
            if attr is None and isinstance(sub, ast.Call):
                attr = _getattr_self_attr(sub)
            if attr is not None and attr not in self.scan.lock_attrs:
                out.setdefault(attr, (test.lineno, test.col_offset,
                                      attr == lazy_attr))


# --------------------------------------------------------------------------
# KBT302 handoff detection (dataflow walk: aliases launder nothing)
# --------------------------------------------------------------------------


class _HandoffVisitor(FlowVisitor):
    def __init__(self, mod: _RaceModule, scan: ClassScan, info: _FuncInfo,
                 mutated: Set[str]):
        self.mod = mod
        self.scan = scan
        self.info = info
        self.mutated = mutated

    def on_bind(self, ev: FlowEvent, env, value) -> None:
        attr = _self_attr(value) if value is not None else None
        if attr is not None and attr in self.scan.container_attrs:
            ev.cell["kbt_container"] = attr

    def _payload_attr(self, node: ast.AST, env) -> Optional[str]:
        attr = _self_attr(node)
        if attr is not None and attr in self.scan.container_attrs:
            return attr
        if isinstance(node, ast.Name):
            cell = env.get(node.id)
            if cell is not None:
                tainted = cell.get("kbt_container")
                if isinstance(tainted, str):
                    return tainted
        return None

    def on_call(self, ev: FlowEvent, env) -> None:
        call = ev.node
        dotted = self.mod.ctx.imports.dotted(call.func)
        payload: List[ast.AST] = []
        via = "submit"
        if dotted == "threading.Thread":
            args_t = call_keyword(call, "args")
            if isinstance(args_t, (ast.Tuple, ast.List)):
                payload = list(args_t.elts)
            via = "thread"
        elif (isinstance(call.func, ast.Attribute)
                and call.func.attr in POOL_SPAWN_ATTRS
                and len(call.args) > 1):
            payload = list(call.args[1:])
        for p in payload:
            attr = self._payload_attr(p, env)
            if attr is not None and attr in self.mutated:
                self.scan.handoffs.append(Handoff(
                    attr, p.lineno, p.col_offset, self.info.qual, via))


# --------------------------------------------------------------------------
# domain inference + rule evaluation
# --------------------------------------------------------------------------


@dataclasses.dataclass
class Domain:
    cls: str
    attr: str
    lock: str
    guarded: int
    unguarded: int
    written: bool
    roots: FrozenSet[str]


def _excluded(relpath: str) -> bool:
    return any(relpath.startswith(p) or f"/{p}" in f"/{relpath}"
               for p in EXCLUDED_PREFIXES)


def race_context(ctx: ModuleContext) -> Optional["_RaceAnalysis"]:
    cached = getattr(ctx, "_kbt_race", None)
    if cached is not None:
        return cached
    if _excluded(ctx.relpath):
        return None
    analysis = _RaceAnalysis(ctx)
    ctx._kbt_race = analysis
    return analysis


class _RaceAnalysis:
    """Domains + the four rules' findings for one module, computed once."""

    def __init__(self, ctx: ModuleContext):
        self.mod = _RaceModule(ctx)
        self.domains: List[Domain] = []
        self.findings: Dict[str, List[Tuple[int, int, str]]] = {
            "KBT301": [], "KBT302": [], "KBT303": [], "KBT304": [],
        }
        self._evaluate()

    def _attr_state(self, scan: ClassScan):
        """Per attribute: effective accesses outside __init__."""
        mod = self.mod
        by_attr: Dict[str, List[Tuple[Access, FrozenSet[str],
                                      FrozenSet[str]]]] = {}
        for a in scan.accesses:
            if a.in_init or a.attr in scan.safe_attrs:
                continue
            eff = mod.held_of(scan, a.held, a.extra_key)
            by_attr.setdefault(a.attr, []).append(
                (a, eff & frozenset(scan.lock_attrs),
                 mod.roots_of(a.qual)))
        return by_attr

    def _evaluate(self) -> None:
        mod = self.mod
        self._claimed: Set[Tuple[str, str, int]] = set()
        for cls in sorted(mod.classes):
            scan = mod.classes[cls]
            if not scan.lock_attrs:
                self._evaluate_handoffs(scan)
                continue
            by_attr = self._attr_state(scan)
            domain_by_attr: Dict[str, Domain] = {}
            for attr in sorted(by_attr):
                accs = by_attr[attr]
                guarded = [(a, h, r) for a, h, r in accs if h]
                if not guarded:
                    continue  # never locked anywhere: no inferable domain
                counts: Counter = Counter()
                for a, h, r in guarded:
                    for lock in h:
                        # writes are stronger domain evidence than reads
                        counts[lock] += 2 if a.write else 1
                lock = min(counts, key=lambda k: (-counts[k], k))
                written = any(a.write for a, _, _ in accs)
                dom = Domain(
                    cls, attr, lock,
                    guarded=sum(1 for _, h, _ in accs if lock in h),
                    unguarded=sum(1 for _, h, _ in accs if lock not in h),
                    written=written,
                    roots=frozenset().union(*(r for _, _, r in accs)),
                )
                self.domains.append(dom)
                domain_by_attr[attr] = dom
            # check-then-act and handoffs first: their findings claim
            # their lines so KBT301 does not double-report the access
            self._evaluate_check_acts(scan, domain_by_attr)
            self._evaluate_handoffs(scan)
            for attr, dom in domain_by_attr.items():
                if dom.written:
                    self._evaluate_attr(scan, dom, by_attr[attr])

    def _evaluate_check_acts(self, scan: ClassScan,
                             domain_by_attr: Dict[str, Domain]) -> None:
        mod = self.mod
        for ca in scan.check_acts:
            dom = domain_by_attr.get(ca.attr)
            if dom is None:
                continue
            test_held = mod.held_of(scan, ca.test_held, ca.extra_key)
            write_held = mod.held_of(scan, ca.write_held, ca.extra_key)
            if dom.lock in test_held or dom.lock in write_held:
                # guarded act.  The LAZY variant with a lock-free test and
                # a guarded write is the double-checked idiom — one torn-
                # proof reference peek, re-verified under the lock before
                # the write — so the peek line is sanctioned: claim it so
                # KBT301 doesn't re-report the read the idiom depends on.
                if (ca.lazy and dom.lock not in test_held
                        and dom.lock in write_held):
                    self._claimed.add((scan.name, ca.attr, ca.test_line))
                continue
            roots = mod.roots_of(ca.qual)
            others = [r for a in scan.accesses if a.attr == ca.attr
                      and not a.in_init
                      for r in (mod.roots_of(a.qual),)]
            if not any(_concurrent(roots, r) for r in others):
                continue
            rule = "KBT304" if ca.lazy else "KBT303"
            what = ("lazy init of" if ca.lazy else "check-then-act on")
            self.findings[rule].append((
                ca.test_line, ca.test_col,
                f"{what} shared `.{ca.attr}` outside its inferred domain "
                f"lock `self.{dom.lock}` — the test at line {ca.test_line} "
                f"and the write at line {ca.write_line} are both lock-free, "
                f"so two threads can interleave between them; hold "
                f"`self.{dom.lock}` around the check AND the act (or "
                f"annotate why this window is benign)",
            ))
            self._claimed.add((scan.name, ca.attr, ca.test_line))
            self._claimed.add((scan.name, ca.attr, ca.write_line))

    def _evaluate_attr(self, scan: ClassScan, dom: Domain, accs) -> None:
        claimed = self._claimed
        guarded = [(a, h, r) for a, h, r in accs if dom.lock in h]
        for a, h, roots in accs:
            if dom.lock in h:
                continue
            if (scan.name, a.attr, a.line) in claimed:
                continue  # a check-then-act finding owns this line
            witness = next(
                (g for g, _, gr in guarded if _concurrent(roots, gr)), None)
            if witness is None:
                continue  # same single root as every guarded access
            verb = "written" if a.write else "read"
            under = (f" (holds `self.{min(h)}` instead)" if h else
                     " without a lock")
            self.findings["KBT301"].append((
                a.line, a.col,
                f"`.{a.attr}` is guarded by `self.{dom.lock}` on another "
                f"thread root (e.g. line {witness.line}) but {verb} here"
                f"{under} — hold `self.{dom.lock}` or annotate why this "
                f"access cannot race",
            ))

    def _evaluate_handoffs(self, scan: ClassScan) -> None:
        mod = self.mod
        mutated = {a.attr for a in scan.accesses
                   if a.write and not a.in_init}
        if scan.container_attrs & mutated:
            for name, node in sorted(scan.methods.items()):
                info = mod.funcs.get(f"{scan.name}.{name}")
                if info is None:
                    continue
                walk_function(node, _HandoffVisitor(
                    mod, scan, info, scan.container_attrs & mutated))
        for h in scan.handoffs:
            self.findings["KBT302"].append((
                h.line, h.col,
                f"live container `.{h.attr}` handed to another thread by "
                f"reference (via {h.via}) while this class keeps mutating "
                f"it — snapshot the value at the handoff "
                f"(`dict(...)`/`list(...)`/`.copy()`) like the StatusFlush "
                f"double buffer, or annotate the ownership transfer",
            ))
            self._claimed.add((scan.name, h.attr, h.line))


# --------------------------------------------------------------------------
# the tier-D rules (engine plumbing: suppression, scoping, --select)
# --------------------------------------------------------------------------


class _TierDRule(Rule):
    rule_key = ""

    def check_ctx(self, ctx) -> Iterable[Tuple[int, int, str]]:
        analysis = race_context(ctx)
        if analysis is None:
            return ()
        return analysis.findings[self.rule_key]

    def check(self, tree, relpath):  # tier D is flow-only
        return ()


class LockDomainRule(_TierDRule):
    """The tier's core invariant — the paper's Go scheduler guarded the
    whole cache under one mutex; the JAX rebuild split that into per-plane
    locks, and each split is a chance for one access site to drift off its
    domain.  Grounded in this PR's own dogfood catch: the replication
    publisher's ``encode_errors`` counter and the guard plane's
    ``bundles`` list were written by worker threads lock-free while
    readers held the owning lock — exactly the torn-read/lost-update class
    ``go test -race`` reports for the reference."""

    id = "KBT301"
    rule_key = "KBT301"
    title = "shared attribute accessed off its inferred lock domain"


class PublishHandoffRule(_TierDRule):
    """KBT302 also carries the original KBT012 contract (the pipelined
    writeback stage must only touch the value-snapshotted StatusFlush):
    same stage-function walk, now one rule owning every cross-thread
    publish.  KBT012 remains a ``--select`` alias."""

    id = "KBT302"
    rule_key = "KBT302"
    title = ("live mutable state published across threads without a "
             "value-snapshot handoff")

    #: the one structurally-known overlapped stage (the KBT012 instance)
    STAGE_FNS = {"run_status_flush", "_writeback"}
    STAGE_SCOPE = ("cache/cache.py", "scheduler.py")
    FORBIDDEN = {
        "jobs", "nodes", "pods", "queues", "pod_groups", "columns",
        "open_cache", "dirty", "fit_state_jobs",
    }
    ROOTS = {"self", "cache", "ssn", "session"}

    def check_ctx(self, ctx):
        yield from super().check_ctx(ctx)
        in_scope = any(ctx.relpath.startswith(p)
                       or f"/{p}" in f"/{ctx.relpath}"
                       for p in self.STAGE_SCOPE)
        if not in_scope:
            return
        from kube_batch_tpu.analysis.rules import (
            _leftmost_name, _walk_skipping_defs,
        )
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in self.STAGE_FNS:
                continue
            for sub in _walk_skipping_defs(node.body):
                if not isinstance(sub, ast.Attribute):
                    continue
                if sub.attr not in self.FORBIDDEN:
                    continue
                if _leftmost_name(sub) not in self.ROOTS:
                    continue
                yield (sub.lineno, sub.col_offset,
                       f"writeback stage `{node.name}` reads live "
                       f"`.{sub.attr}` — the overlapped stage may only "
                       "touch the value-snapshotted StatusFlush handoff "
                       "(stage the read in stage_status_flush instead)")


class CheckThenActRule(_TierDRule):
    """A guarded attribute tested lock-free and then acted on lock-free is
    a TOCTOU window even when each individual access is atomic — the bug
    class behind the cache's historical arrival-timestamp stamp-then-apply
    race (now a documented GIL-atomic ``setdefault``): two threads both
    pass the test, both act, one update is lost.  Holding the domain lock
    across the test AND the act closes the window."""

    id = "KBT303"
    rule_key = "KBT303"
    title = "check-then-act on a shared attribute outside its guarding lock"


class LazyInitRule(_TierDRule):
    """Racy lazy init (``if self.x is None: self.x = build()``) without
    the domain lock builds the resource twice under contention — for this
    codebase that means two writeback pools or two compiled-executable
    tables, where the loser's copy leaks its worker thread.  The lazy
    ``is None`` shape is split out from KBT303 because its sanctioned
    repair differs: the double-checked idiom (lock-free peek, locked
    re-check + write) passes, where a generic check-then-act must move
    wholly under the lock."""

    id = "KBT304"
    rule_key = "KBT304"
    title = "unguarded lazy initialization of a shared attribute"


RACE_RULES: Tuple[Rule, ...] = (
    LockDomainRule(), PublishHandoffRule(), CheckThenActRule(),
    LazyInitRule(),
)
RACE_RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in RACE_RULES}


# --------------------------------------------------------------------------
# the --domains report + the corroborator's domain feed
# --------------------------------------------------------------------------


def module_domains(source: str, relpath: str) -> List[Domain]:
    """Inferred lock domains for one module's source ([] on syntax error —
    tier A owns reporting that)."""
    if _excluded(relpath):
        return []
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    return _RaceAnalysis(ModuleContext(tree, relpath)).domains


def domains_report(paths=None) -> str:
    """The reviewable per-class guarded-field map, package-wide."""
    from kube_batch_tpu.analysis.engine import (
        _package_relpath, iter_python_files,
    )
    from pathlib import Path

    if not paths:
        roots = [Path(__file__).resolve().parent.parent]
    else:
        roots = [Path(p) for p in paths]
    lines: List[str] = [
        "# lock domains inferred by kbt-check tier D (see ANALYSIS.md)",
        "# attr -> domain lock [guarded/unguarded access counts] {roots}",
    ]
    for f in iter_python_files(roots):
        try:
            source = f.read_text()
        except (OSError, UnicodeDecodeError):
            continue
        relpath = _package_relpath(f)
        doms = module_domains(source, relpath)
        if not doms:
            continue
        lines.append(f"{relpath}")
        by_cls: Dict[str, List[Domain]] = {}
        for d in doms:
            by_cls.setdefault(d.cls, []).append(d)
        for cls in sorted(by_cls):
            lines.append(f"  {cls}")
            for d in sorted(by_cls[cls], key=lambda d: d.attr):
                roots = ",".join(sorted(d.roots))
                rw = "rw" if d.written else "ro"
                lines.append(
                    f"    {d.attr:<24} -> {d.lock:<14} "
                    f"[{d.guarded}g/{d.unguarded}u {rw}] {{{roots}}}")
    return "\n".join(lines)


def runtime_domain_specs(structures) -> List[Tuple[str, str, str, str]]:
    """Resolve (module, class, attr) hot-structure triples against the
    STATIC inference: returns (module, class, attr, domain lock attr) for
    the lockdep corroborator.  Raising on a miss is the point — if the
    static map stops agreeing with the instrumented table, the two have
    drifted and the cross-validation is void."""
    from pathlib import Path

    pkg_root = Path(__file__).resolve().parent.parent
    out: List[Tuple[str, str, str, str]] = []
    for module, cls, attr in structures:
        rel = module.split("kube_batch_tpu.", 1)[-1].replace(".", "/") + ".py"
        src = (pkg_root / rel).read_text()
        dom = next((d for d in module_domains(src, rel)
                    if d.cls == cls and d.attr == attr), None)
        if dom is None:
            raise LookupError(
                f"tier D infers no lock domain for {module}.{cls}.{attr} — "
                "the runtime corroborator table and the static map drifted")
        out.append((module, cls, attr, dom.lock))
    return out
