"""The KBT rule set. Every rule is grounded in a bug this codebase actually
shipped (rounds 1–5); the historical incident is named in each docstring and
cataloged in ANALYSIS.md.

Rules report (line, col, message) triples; scoping and suppression live in
the engine.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from kube_batch_tpu.analysis.engine import Rule

# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------


def _leftmost_name(node: ast.AST) -> str:
    """Base identifier of an attribute chain (``a.b.c()`` → ``a``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _terminal_name(node: ast.AST) -> str:
    """Rightmost identifier (``self._lock`` → ``_lock``; ``lock`` → ``lock``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


class _ImportMap(ast.NodeVisitor):
    """Names bound to the time/datetime/numpy/urllib modules anywhere in the
    module (top-level or function-local imports both count)."""

    def __init__(self) -> None:
        self.time_names: Set[str] = set()
        self.datetime_names: Set[str] = set()  # module or datetime class
        self.numpy_names: Set[str] = set()
        self.urllib_names: Set[str] = set()  # urllib / urllib.request module
        # from-imports of individual wall-clock / blocking callables:
        # local name → original attribute name
        self.from_time: Dict[str, str] = {}
        self.from_urllib: Set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            bound = alias.asname or alias.name.split(".")[0]
            if alias.name == "time":
                self.time_names.add(bound)
            elif alias.name == "datetime":
                self.datetime_names.add(bound)
            elif alias.name == "numpy":
                self.numpy_names.add(bound)
            elif alias.name in ("urllib", "urllib.request"):
                self.urllib_names.add(bound)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for alias in node.names:
                self.from_time[alias.asname or alias.name] = alias.name
        elif node.module == "datetime":
            for alias in node.names:
                if alias.name == "datetime":
                    self.datetime_names.add(alias.asname or alias.name)
        elif node.module == "numpy":
            for alias in node.names:
                if alias.name in ("asarray", "array"):
                    self.numpy_names.add(alias.asname or alias.name)
        elif node.module in ("urllib.request", "urllib"):
            for alias in node.names:
                if alias.name in ("urlopen", "request"):
                    self.from_urllib.add(alias.asname or alias.name)


def _walk_skipping_defs(body: Iterable[ast.AST]) -> Iterable[ast.AST]:
    """Yield statements/expressions lexically in ``body`` without descending
    into nested function/class bodies (their code runs later, elsewhere)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------------------
# KBT001 — wall clock outside the Clock seam
# --------------------------------------------------------------------------


class WallClockRule(Rule):
    """Historical bug: the simulator (PR 1) needed a clock seam because the
    Scheduler loop read `time` directly; any direct wall-clock call in the
    scheduler/actions/cache/sim/framework paths silently breaks virtual-time
    replay determinism again. Telemetry that deliberately measures real
    compute (perf_counter spans feeding metrics) stays — annotated."""

    id = "KBT001"
    title = "wall-clock call outside the Clock seam"
    scope = ("scheduler.py", "actions/", "cache/", "sim/", "framework/")

    TIME_ATTRS = {
        "time", "monotonic", "sleep", "perf_counter", "process_time",
        "time_ns", "monotonic_ns", "perf_counter_ns",
    }
    DATETIME_ATTRS = {"now", "utcnow", "today"}

    def check(self, tree: ast.Module, relpath: str):
        imports = _ImportMap()
        imports.visit(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                base = _leftmost_name(func)
                if base in imports.time_names and func.attr in self.TIME_ATTRS:
                    yield (node.lineno, node.col_offset,
                           f"wall-clock call `{base}.{func.attr}()` in a "
                           "clock-seamed path; read the injected clock "
                           "(Scheduler.clock / sim VirtualClock) instead")
                elif (base in imports.datetime_names
                        and func.attr in self.DATETIME_ATTRS):
                    yield (node.lineno, node.col_offset,
                           f"wall-clock call `{base}.{func.attr}()` in a "
                           "clock-seamed path; carry timestamps through the "
                           "injected clock")
            elif isinstance(func, ast.Name):
                orig = imports.from_time.get(func.id)
                if orig in self.TIME_ATTRS:
                    yield (node.lineno, node.col_offset,
                           f"wall-clock call `{func.id}()` (time.{orig}) in a "
                           "clock-seamed path; read the injected clock instead")


# --------------------------------------------------------------------------
# KBT002 — blocking call inside a lock body
# --------------------------------------------------------------------------


class BlockingUnderLockRule(Rule):
    """Historical bug: TokenBucket.take() slept while holding its lock, so
    concurrent waiters (the 16-worker status pool, the binder, the pv-writes
    thread) serialized behind whoever slept first (round-5 ADVICE #3). Any
    call that can block for I/O or scheduling latency inside a
    `with <lock>:` body stalls every other thread contending for that lock."""

    id = "KBT002"
    title = "blocking call while holding a lock"
    scope = ()  # package-wide

    # attribute calls that block regardless of receiver
    BLOCKING_ATTRS = {
        "sleep", "result", "wait", "urlopen", "getresponse", "recv",
        "recvfrom", "accept", "connect", "sendall", "select", "serve_forever",
    }
    # attribute calls that block only on specific receivers (heuristic on the
    # receiver's terminal identifier)
    CONDITIONAL_ATTRS = {
        "get": ("queue", "q"),            # queue.Queue.get, not dict.get
        "join": ("thread", "pool", "proc", "writer"),
        "take": ("bucket",),              # TokenBucket.take may sleep
        "request": ("transport", "conn", "session"),
        "shutdown": ("pool", "executor", "writer"),
    }

    @staticmethod
    def _lockish(expr: ast.AST) -> bool:
        name = _terminal_name(expr).lower()
        return "lock" in name or "mutex" in name

    def _blocking_call(self, call: ast.Call, imports: _ImportMap):
        func = call.func
        if isinstance(func, ast.Attribute):
            recv = _terminal_name(func.value).lower()
            if func.attr in self.BLOCKING_ATTRS:
                return f"`.{func.attr}()`"
            hints = self.CONDITIONAL_ATTRS.get(func.attr)
            if hints and any(h in recv for h in hints if h != "q"):
                return f"`{recv}.{func.attr}()`"
            if hints and recv in hints:  # exact match (the bare `q`)
                return f"`{recv}.{func.attr}()`"
        elif isinstance(func, ast.Name):
            if imports.from_time.get(func.id) == "sleep" or func.id == "sleep":
                return f"`{func.id}()`"
            if func.id in imports.from_urllib:
                return f"`{func.id}()`"
        return None

    def check(self, tree: ast.Module, relpath: str):
        imports = _ImportMap()
        imports.visit(tree)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(self._lockish(item.context_expr) for item in node.items):
                continue
            lock_name = next(
                _terminal_name(i.context_expr)
                for i in node.items if self._lockish(i.context_expr)
            )
            for inner in _walk_skipping_defs(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                what = self._blocking_call(inner, imports)
                if what is not None:
                    yield (inner.lineno, inner.col_offset,
                           f"blocking call {what} inside `with {lock_name}:`; "
                           "reserve state under the lock and block outside it "
                           "(the TokenBucket.take pattern)")


# --------------------------------------------------------------------------
# KBT003 — module-level mutable state in actions/ and framework/
# --------------------------------------------------------------------------


class ModuleStateRule(Rule):
    """Historical bug: allocate published its per-cycle host-discard count in
    a module global that backfill read — a process-global carrying a
    per-session signal, wrong the moment two schedulers/sessions share the
    interpreter (round-5 advisor finding; PR 1 moved it onto the Session).
    Import-time registries are legitimate — annotate them as such."""

    id = "KBT003"
    title = "module-level mutable state in actions/framework"
    scope = ("actions/", "framework/")

    MUTABLE_FACTORIES = {
        "dict", "list", "set", "defaultdict", "deque", "Counter",
        "OrderedDict",
    }

    def _mutable_value(self, value: ast.AST) -> str:
        if isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)):
            return type(value).__name__.lower()
        if isinstance(value, ast.Call):
            name = _terminal_name(value.func)
            if name in self.MUTABLE_FACTORIES:
                return f"{name}()"
        return ""

    @staticmethod
    def _constant_name(name: str) -> bool:
        return name.upper() == name or name.startswith("__")

    def _top_level_statements(self, tree: ast.Module):
        """Module body, descending through If/Try but not into defs."""
        stack = list(tree.body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.If, ast.Try)):
                stack.extend(ast.iter_child_nodes(node))

    def check(self, tree: ast.Module, relpath: str):
        for node in self._top_level_statements(tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets if isinstance(t, ast.Name)]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target] if isinstance(node.target, ast.Name) else []
                value = node.value
            else:
                continue
            kind = self._mutable_value(value)
            if not kind:
                continue
            for t in targets:
                if self._constant_name(t.id):
                    continue
                yield (node.lineno, node.col_offset,
                       f"module-level mutable {kind} `{t.id}` can carry "
                       "per-session/per-cycle state across cycles and "
                       "schedulers; move it onto the Session (the "
                       "last_host_discards fix) or annotate it as an "
                       "import-time registry")
        # writes to module globals from function bodies are the same bug in
        # verb form — the allocate→backfill signal was exactly this
        for node in ast.walk(tree):
            if isinstance(node, ast.Global):
                yield (node.lineno, node.col_offset,
                       f"`global {', '.join(node.names)}` write from a "
                       "function in actions/framework; per-cycle signals "
                       "belong on the Session")


# --------------------------------------------------------------------------
# KBT004 — fail-open defaults in the translate layer
# --------------------------------------------------------------------------


class FailOpenTranslateRule(Rule):
    """Historical bug: unrecognized PV nodeAffinity translated to node=None
    ("reachable from every node"), letting --master mode bind pods onto
    nodes that could not attach the volume (round-5 ADVICE #1). In the
    translate layer, a None/empty return on unrecognized input is a policy
    decision to fail open — it must be written down or fail closed."""

    id = "KBT004"
    title = "translate-layer fail-open default return"
    scope = ("k8s/translate.py", "api/serialize.py")

    @staticmethod
    def _is_failopen_value(value) -> str:
        if value is None:
            return "bare `return`"
        if isinstance(value, ast.Constant):
            if value.value is None:
                return "`return None`"
            if value.value == "":
                return '`return ""`'
        if isinstance(value, (ast.List, ast.Tuple, ast.Set)) and not value.elts:
            return "empty-collection return"
        if isinstance(value, ast.Dict) and not value.keys:
            return "empty-dict return"
        if (isinstance(value, ast.Call) and not value.args
                and not value.keywords
                and _terminal_name(value.func) in ("dict", "list", "tuple", "set")):
            return f"`return {_terminal_name(value.func)}()`"
        return ""

    def check(self, tree: ast.Module, relpath: str):
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            returns = [
                n for n in _walk_skipping_defs(node.body)
                if isinstance(n, ast.Return)
            ]
            # procedures (every return valueless/None) aren't translators
            # with a fail-open default — only value-producing functions are
            if not any(not self._is_failopen_value(r.value) for r in returns):
                continue
            for r in returns:
                what = self._is_failopen_value(r.value)
                if what:
                    yield (r.lineno, r.col_offset,
                           f"{what} in translate-layer `{node.name}` is a "
                           "fail-open default on unrecognized input; fail "
                           "closed (sentinel / raise) or annotate why open "
                           "is sound")


# --------------------------------------------------------------------------
# KBT005 — host-device sync in ops/ hot paths
# --------------------------------------------------------------------------


class HostSyncRule(Rule):
    """Guards the <1s/50k-pod cycle target: a host-device sync inside ops/
    (np.asarray on device arrays, float()/int() materialization,
    .block_until_ready, per-iteration jnp dispatch in Python loops) stalls
    the device pipeline. Deliberate sync points (the solve's single
    readback) are annotated."""

    id = "KBT005"
    title = "host-device sync in ops/ hot path"
    scope = ("ops/",)

    JAX_BASES = {"jnp", "jax", "lax"}
    SYNC_ATTRS = {"block_until_ready", "item", "tolist"}

    def check(self, tree: ast.Module, relpath: str):
        imports = _ImportMap()
        imports.visit(tree)
        loop_spans: List[Tuple[int, int]] = []  # (first, last) line of loop bodies
        for node in ast.walk(tree):
            if isinstance(node, (ast.For, ast.While)):
                end = max(
                    (getattr(n, "end_lineno", None) or n.lineno)
                    for n in _walk_skipping_defs(node.body)
                    if hasattr(n, "lineno")
                )
                loop_spans.append((node.body[0].lineno, end))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                base = _leftmost_name(func)
                if func.attr in self.SYNC_ATTRS:
                    yield (node.lineno, node.col_offset,
                           f"`.{func.attr}()` forces a host-device sync in an "
                           "ops/ hot path; keep results on device or annotate "
                           "the sync point")
                    continue
                if (base in imports.numpy_names or base == "np") \
                        and func.attr in ("asarray", "array"):
                    yield (node.lineno, node.col_offset,
                           f"`{base}.{func.attr}()` materializes device data "
                           "on host in an ops/ hot path; stay in jnp or "
                           "annotate the sync point")
                    continue
                if base in self.JAX_BASES and any(
                    lo <= node.lineno <= hi for lo, hi in loop_spans
                ):
                    yield (node.lineno, node.col_offset,
                           f"`{base}.{func.attr}` dispatched inside a Python "
                           "loop in ops/ — per-iteration device dispatch; "
                           "vectorize, lax.scan, or annotate (trace-time "
                           "unrolls are annotation-worthy, not rewrites)")
            elif isinstance(func, ast.Name) and func.id in ("float", "int"):
                arg = node.args[0] if node.args else None
                if isinstance(arg, (ast.Name, ast.Subscript)):
                    yield (node.lineno, node.col_offset,
                           f"`{func.id}()` on an array value forces a "
                           "host-device sync in an ops/ hot path; keep the "
                           "value on device or annotate the sync point")


# --------------------------------------------------------------------------
# KBT011 — raw transport / ad-hoc retry loop outside k8s/transport.py
# --------------------------------------------------------------------------


class RawTransportRule(Rule):
    """Historical bug: the watch loop hand-rolled a jitterless 1→30s
    doubling backoff while `ApiTransport.request()` had no retry policy at
    all — every apiserver caller invented its own (or no) failure handling.
    The robustness PR centralized classification, capped decorrelated-jitter
    backoff, per-endpoint-class budgets, and the circuit breaker in
    k8s/transport.py; this rule keeps it that way: a raw
    `urllib.request.urlopen` or an ad-hoc `time.sleep` retry loop anywhere
    else in k8s//cmd/ bypasses the classified policy (and the breaker's
    fail-fast), so every apiserver call is forced through the transport."""

    id = "KBT011"
    title = "raw urllib / ad-hoc sleep retry loop outside the transport"
    scope = ("k8s/", "cmd/")

    @staticmethod
    def _exempt(relpath: str) -> bool:
        # the transport module IS the sanctioned home of urlopen + backoff
        return relpath.endswith("k8s/transport.py") or relpath == "transport.py"

    def check(self, tree: ast.Module, relpath: str):
        if self._exempt(relpath):
            return
        imports = _ImportMap()
        imports.visit(tree)
        # lexical spans of loop bodies (retry loops hide sleeps in them)
        loop_spans: List[Tuple[int, int]] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.For, ast.While)):
                lines = [
                    getattr(n, "end_lineno", None) or n.lineno
                    for n in _walk_skipping_defs(node.body)
                    if hasattr(n, "lineno")
                ]
                if lines:
                    loop_spans.append((node.body[0].lineno, max(lines)))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_urlopen = False
            is_sleep = False
            if isinstance(func, ast.Attribute):
                base = _leftmost_name(func)
                if func.attr == "urlopen" and base in imports.urllib_names:
                    is_urlopen = True
                elif func.attr == "sleep" and base in imports.time_names:
                    is_sleep = True
            elif isinstance(func, ast.Name):
                if func.id in imports.from_urllib and func.id == "urlopen":
                    is_urlopen = True
                elif imports.from_time.get(func.id) == "sleep":
                    is_sleep = True
            if is_urlopen:
                yield (node.lineno, node.col_offset,
                       "raw `urlopen()` outside k8s/transport.py bypasses "
                       "the classified retry policy and the circuit "
                       "breaker; route the call through ApiTransport")
            elif is_sleep and any(
                lo <= node.lineno <= hi for lo, hi in loop_spans
            ):
                yield (node.lineno, node.col_offset,
                       "ad-hoc sleep inside a loop looks like a hand-rolled "
                       "retry/backoff; use the transport's RetryPolicy "
                       "(decorrelated jitter, budgets) or annotate why this "
                       "pacing is not a retry")


# --------------------------------------------------------------------------
# KBT012 — MOVED: the pipeline writeback-stage handoff contract is now a
# KBT302 instance (analysis/races.py PublishHandoffRule — the generalized
# publish-then-mutate rule owns the one hardcoded case it grew from).
# `--select KBT012` still works via RULE_ALIASES in races.py.
# --------------------------------------------------------------------------


# --------------------------------------------------------------------------
# KBT013 — bind/evict dispatch site without a sentinel-verdict consumer
# --------------------------------------------------------------------------


class SentinelConsumeRule(Rule):
    """Guard for the result-integrity plane (kube_batch_tpu/guard): every
    action-layer function that dispatches a committed solve — the programs
    whose results become real binds and evictions — must consume the fused
    sentinel's verdict through ``GuardPlane.consume_verdict`` before acting
    on the result.  A dispatch site added without the consumer silently
    re-opens the exact hole the guard plane closed: a condemned solve's
    placements would flow to the binder with zero detection.  The bug
    class is structural (a future action or refactor forgetting the
    verdict), so the rule is structural too: a function in actions/ that
    calls a solve dispatch and never calls a verdict consumer reports.
    ``dispatch_*``-named helpers are the sanctioned SEAM layer: they
    return the un-consumed sentinel to their caller and are skipped here —
    but their names sit in DISPATCH_FNS, so every CALL SITE of the seam is
    still held to the consumer requirement."""

    id = "KBT013"
    title = "solve dispatch without a sentinel-verdict consumer"
    scope = ("actions/",)

    #: callables whose results become binds/evictions — the committed
    #: solve dispatch surface (single-device, sharded, and the actions'
    #: own dispatch helpers)
    DISPATCH_FNS = {
        "dispatch_allocate_solve", "allocate_solve", "allocate_topk_solve",
        "warm_allocate_solve", "warm_allocate_sentinel_solve",
        "allocate_sentinel_solve", "allocate_topk_sentinel_solve",
        "evict_solve", "evict_sentinel_solve",
        "sharded_allocate_solve", "sharded_allocate_topk_solve",
        "sharded_warm_allocate_solve",
        "sharded_evict_solve", "sentinel_sharded_allocate_solve",
        "sentinel_sharded_allocate_topk_solve",
        "sentinel_sharded_warm_allocate_solve",
        "sentinel_sharded_evict_solve",
        "dispatch_enqueue_gate",
    }
    #: verdict consumers: the GuardPlane choke point and the shared
    #: readback-side consumers (guard/plane.consume_sentinel /
    #: consume_assignment_sentinel) — matched by SUBSTRING so an action's
    #: thin wrapper (`_consume_sentinel`) and shaped variants count
    #: without baking private names into the rule
    CONSUME_FNS = {"consume_verdict"}
    CONSUME_SUBSTR = "consume_"

    def check(self, tree: ast.Module, relpath: str):
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name.startswith("dispatch_"):
                continue  # the seam layer (docstring) — call sites checked
            dispatches: List[ast.Call] = []
            consumes = False
            for sub in _walk_skipping_defs(node.body):
                if not isinstance(sub, ast.Call):
                    continue
                name = _terminal_name(sub.func)
                if name in self.DISPATCH_FNS:
                    dispatches.append(sub)
                elif (name in self.CONSUME_FNS
                        or (self.CONSUME_SUBSTR in name
                            and "sentinel" in name)):
                    consumes = True
            if consumes:
                continue
            for call in dispatches:
                yield (call.lineno, call.col_offset,
                       f"`{_terminal_name(call.func)}(...)` dispatches a "
                       "committed solve but this function never consumes a "
                       "sentinel verdict (GuardPlane.consume_verdict) — a "
                       "condemned result could reach the binder; consume "
                       "the verdict, or annotate a dispatch seam that "
                       "returns the un-consumed sentinel to its caller")


# --------------------------------------------------------------------------
# KBT014 — span discipline: spans via obs.trace only, no clock reads in
# span bodies
# --------------------------------------------------------------------------


class SpanDisciplineRule(Rule):
    """Guard for the cycle tracing plane (kube_batch_tpu/obs): spans in the
    clock-seamed paths are created ONLY through the ``obs.trace`` context
    managers (``tracer.span`` / ``device_span`` / ``cycle_span``), and a
    span body contains no clock reads of its own — the span IS the
    measurement.  Two bug classes this kills: (1) a hand-rolled Span (or a
    begin/end pair) that skips the context manager loses exception-safe
    closing and the per-thread nesting stack, producing unbalanced trace
    trees that the Chrome-export validation then rejects at smoke time;
    (2) an ad-hoc ``telemetry.perf_counter`` pair (or worse, raw
    ``time.*``) lexically inside a ``with ...span(...):`` body re-creates
    exactly the scattered-timer drift this plane replaced — the span's own
    stamps and the ad-hoc pair silently diverge, and the virtual-time
    seam is bypassed.  Metrics that want a span's duration read
    ``sp.dur_ms`` / ``sp.dur_us`` AFTER the block (the scheduler's action
    and plugin histograms are the shipped examples)."""

    id = "KBT014"
    title = "span discipline: manual span or clock read in a span body"
    #: the clock-seamed core PLUS every module that may adopt spans later —
    #: obs/ itself is exempt (it IS the implementation)
    scope = ("scheduler.py", "actions/", "cache/", "sim/", "framework/",
             "serve/", "guard/", "plugins/")

    SPAN_FACTORIES = {"span", "device_span", "cycle_span"}
    TIME_ATTRS = WallClockRule.TIME_ATTRS
    DATETIME_ATTRS = WallClockRule.DATETIME_ATTRS

    def _is_span_with(self, node) -> bool:
        for item in node.items:
            ctx = item.context_expr
            if (isinstance(ctx, ast.Call)
                    and isinstance(ctx.func, ast.Attribute)
                    and ctx.func.attr in self.SPAN_FACTORIES):
                return True
        return False

    def check(self, tree: ast.Module, relpath: str):
        imports = _ImportMap()
        imports.visit(tree)
        for node in ast.walk(tree):
            # (1) manual span construction outside the context managers
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name == "Span" or name in ("begin_span", "end_span"):
                    yield (node.lineno, node.col_offset,
                           "manual span construction bypasses the obs.trace "
                           "context managers (nesting stack, exception-safe "
                           "close); use `with tracer.span(...)`")
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not self._is_span_with(node):
                continue
            # (2) clock reads lexically inside the span body
            for inner in _walk_skipping_defs(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                func = inner.func
                what = None
                if isinstance(func, ast.Attribute):
                    base = _leftmost_name(func)
                    if (base in imports.time_names
                            and func.attr in self.TIME_ATTRS):
                        what = f"`{base}.{func.attr}()`"
                    elif (base in imports.datetime_names
                            and func.attr in self.DATETIME_ATTRS):
                        what = f"`{base}.{func.attr}()`"
                    elif base == "telemetry" and func.attr == "perf_counter":
                        what = "`telemetry.perf_counter()`"
                elif isinstance(func, ast.Name):
                    if imports.from_time.get(func.id) in self.TIME_ATTRS:
                        what = f"`{func.id}()`"
                if what is not None:
                    yield (inner.lineno, inner.col_offset,
                           f"clock read {what} inside a span body — the "
                           "span already stamps its own wall/virtual time; "
                           "read `sp.dur_ms`/`sp.dur_us` after the block or "
                           "open a child span")


from kube_batch_tpu.analysis.flowrules import FLOW_RULES  # noqa: E402

ALL_RULES = (
    WallClockRule(),
    BlockingUnderLockRule(),
    ModuleStateRule(),
    FailOpenTranslateRule(),
    HostSyncRule(),
    RawTransportRule(),
    SentinelConsumeRule(),
    SpanDisciplineRule(),
) + FLOW_RULES

RULES_BY_ID = {r.id: r for r in ALL_RULES}
