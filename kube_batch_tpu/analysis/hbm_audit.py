"""Tier C: jaxpr liveness / HBM budget audit of the registered entry points.

Tier B answers "does the traced program contain a hazard primitive"; tier C
answers the question that actually caps the rebuild's scale ceiling: **does
each compiled program FIT** — peak live bytes under a per-device HBM budget
at the shapes production will run, long before any hardware sees the
program.  ROADMAP item 1's contract is that at 1M pods × 100k nodes any
materialized [T, N] plane (~400 GB at f32) is unaffordable, so the steady
dispatch path must stay on the compacted [P, K] candidate geometry; this
tier makes that a CI-enforced invariant instead of a code-review argument.

Mechanism: every tier-B registry entry is re-traced (abstract — no device
work) at a LADDER of shape points (current bench shapes, the 50k×5k
headline, the 1M×100k north star), and each closed jaxpr is walked with a
linear-scan liveness analysis:

- values live from the equation that produces them to their last read
  (or program exit for outputs); constvars and non-donated inputs are
  live throughout; a DONATED input's buffer is free once its last read
  passes (the aliasing credit the budget model claims — KBT203 checks
  it's real);
- ``while``/``scan``/``cond``/``pjit`` sub-jaxprs recurse: a loop body's
  internal peak is transient extra on top of the carry (counted at the
  call site), ``cond`` takes the max over branches, ``scan`` stacked
  outputs are charged at the call site;
- ``shard_map`` bodies are walked at their per-shard LOCAL avals (that's
  what each device holds), and the call-site operands/results are charged
  at global-bytes ÷ (mesh-axis extent) per the in/out specs — so an
  ``all_gather`` result inside the body is charged at its gathered
  (global) size on every device, exactly the collective-materialization
  cost the budget must absorb.

Rules (suppressions are per-(entry, rule, shape-point) allowlist entries
with mandatory reasons — see HBM_ALLOWLIST; stale entries fail the audit):

- **KBT201 over budget** — peak live bytes exceed the backend profile
  (v5e 16 GiB default; ``KB_HBM_BUDGET`` accepts a GiB number or a
  profile name) at a declared shape point.
- **KBT202 full-matrix temporary** — a program declared steady-path
  (EntryPoint.steady) materializes a task-axis × node-axis plane.  This
  is the rule that permanently pins ROADMAP 1.(1) (evict full-matrix
  bids) and 1.(2) (shard_map exhaustion fallback): those corners live in
  the allowlist with ROADMAP cross-references until fixed — the
  allowlist IS the burn-down list.
- **KBT203 unrealized donation** — the registry declares a donated
  argument but no output of the traced jaxpr can alias it (shape+dtype
  match): the savings the budget model credits would not materialize,
  and XLA would warn-and-ignore the donation at runtime.
- **KBT204 node-scaled per-round collective** — a collective inside the
  bidding round loop whose payload carries a node-axis dimension
  (extending utils.jitstats.collective_inventory's per-round/per-solve
  bucketing, nested-loop trip counts included).  The cross-host byte
  contract is O(tasks)/round; an O(nodes)/round collective breaks the
  scaling story even when it fits HBM.

Known slack vs XLA's real allocator (documented, deliberate):

- fusion: XLA fuses elementwise chains so intermediate values never
  materialize; this walk charges each equation output.  Overestimate.
- scheduling: XLA may reorder to shrink live ranges; the walk takes the
  traced order.  Overestimate.
- sub-jaxpr outputs are charged both inside the body (at its internal
  peak) and at the call site.  Small overestimate (~carry size).
- top-level operands of the PJIT-ORACLE sharded entries are charged at
  global bytes — jitted-with-in_shardings functions expose no public
  sharding introspection, so the per-device discount can't be computed.
  The shard_map production path IS discounted via the eqn's in/out specs.

All slack overestimates: a clean tier-C verdict is conservative-safe.

Run via ``python -m kube_batch_tpu.analysis --hbm`` (``--hbm-only`` for
just this tier), the check.sh gate, the tier-1 self-enforcement test, or
``bench.py``'s hbm_headroom section (bytes-vs-budget per entry per point,
tracked across PRs like any perf number).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from kube_batch_tpu.analysis.engine import Finding
from kube_batch_tpu.analysis.jaxpr_audit import (
    REGISTRY,
    EntryPoint,
    ShapePoint,
    shape_point,
    sharded_registry,
)

HBM_RULES = {
    "KBT201": "peak live bytes over the HBM budget at a declared shape point",
    "KBT202": "task-axis × node-axis temporary in a steady-path program",
    "KBT203": "declared donation the traced jaxpr never aliases to an output",
    "KBT204": "per-round collective payload scaling with the node axis",
}

GIB = 2**30

#: per-backend HBM budgets, GiB per device.  v5e is the deployment target
#: (ROADMAP: "assert peak live bytes fit a v5e").
BUDGET_PROFILES: Dict[str, float] = {"v5e": 16.0, "v6e": 32.0, "v5p": 95.0}
DEFAULT_PROFILE = "v5e"


def budget_bytes() -> Tuple[int, str]:
    """(budget in bytes, label).  ``KB_HBM_BUDGET`` overrides: a profile
    name ("v6e") or a GiB number ("24"); anything unparsable falls back to
    the default profile (the audit must never silently relax)."""
    raw = os.environ.get("KB_HBM_BUDGET", "").strip()
    if raw:
        if raw in BUDGET_PROFILES:
            return int(BUDGET_PROFILES[raw] * GIB), raw
        try:
            return int(float(raw) * GIB), f"{raw} GiB (KB_HBM_BUDGET)"
        except ValueError:
            pass
    return int(BUDGET_PROFILES[DEFAULT_PROFILE] * GIB), DEFAULT_PROFILE


_POINTS: Optional[Tuple[ShapePoint, ...]] = None


def shape_points() -> Tuple[ShapePoint, ...]:
    """The audit ladder: the bench's current scale, the <1s/50k-pod
    headline, and ROADMAP item 1's 1M×100k north star."""
    global _POINTS
    if _POINTS is None:
        _POINTS = (
            shape_point("bench-20k", 20_000, 2_000),
            shape_point("headline-50k", 50_000, 5_000),
            shape_point("northstar-1m", 1_000_000, 100_000),
        )
    return _POINTS


# --------------------------------------------------------------------------
# axis classification: which integer extents mean "task-scale" and
# "node-scale" at a given shape point (sharded locals included)
# --------------------------------------------------------------------------

#: node/task axis shard counts the audit meshes can produce
_SHARD_DIVS = (2, 4, 8)


def _axis_dims(sp: ShapePoint) -> Tuple[Set[int], Set[int]]:
    task = {sp.T, sp.P}
    task |= {sp.T // d for d in _SHARD_DIVS if sp.T % d == 0}
    node = {sp.N}
    node |= {sp.N // d for d in _SHARD_DIVS if sp.N % d == 0}
    # extents that are NOT evidence of a task/node axis at this point:
    # other snapshot axes that may numerically collide (e.g. warm_c=512
    # vs N/4=512 at the bench point), and anything below the noise floor.
    # warm_pi is deliberately absent — the top rerank rung IS P.
    ambiguous = {sp.J, sp.Q, sp.R, sp.W, sp.K_aff, sp.topk, sp.warm_w,
                 sp.warm_c, sp.probe_b, sp.probe_g}
    task = {d for d in task if d >= 256} - ambiguous - node
    node = {d for d in node if d >= 256} - ambiguous - {sp.T, sp.P}
    return task, node


def _dim_label(d: int, sp: ShapePoint) -> str:
    names = {sp.T: "T", sp.N: "N", sp.P: "P", sp.J: "J"}
    if d in names:
        return f"{names[d]}={d}"
    for base, tag in ((sp.T, "T"), (sp.N, "N"), (sp.P, "P")):
        for s in _SHARD_DIVS:
            if base % s == 0 and d == base // s:
                return f"{tag}/{s}={d}"
    return str(d)


def _fmt_aval(aval, sp: ShapePoint) -> str:
    shape = tuple(getattr(aval, "shape", ()) or ())
    dtype = str(getattr(aval, "dtype", "?"))
    dims = ", ".join(_dim_label(int(d), sp) for d in shape)
    return f"{dtype}[{dims}]"


# --------------------------------------------------------------------------
# liveness walk
# --------------------------------------------------------------------------


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def _var_bytes(v) -> int:
    return _aval_bytes(getattr(v, "aval", None))


def _sub_jaxprs(eqn) -> List:
    subs = []
    for param in eqn.params.values():
        vals = param if isinstance(param, (list, tuple)) else [param]
        for sub in vals:
            inner = getattr(sub, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                subs.append(inner)
            elif hasattr(sub, "eqns"):
                subs.append(sub)
    return subs


def _mesh_extent(mesh, axes) -> int:
    shape = dict(mesh.shape)
    n = 1
    for ax in axes:
        n *= int(shape.get(ax, 1))
    return n


def _shard_divisors(eqn, names_key: str, count: int) -> List[int]:
    """Per-operand (or per-result) sharding divisor of a shard_map eqn:
    the product of mesh-axis extents the in/out spec maps onto the value's
    dims — global bytes ÷ divisor is what one device holds."""
    mesh = eqn.params.get("mesh")
    names = eqn.params.get(names_key)
    if mesh is None or names is None:
        return [1] * count
    divs = []
    for spec in names:
        axes: List = []
        for dim_axes in dict(spec).values():
            axes.extend(dim_axes)
        divs.append(_mesh_extent(mesh, axes))
    if len(divs) < count:
        divs += [1] * (count - len(divs))
    return divs


@dataclasses.dataclass
class LivenessStats:
    """What one entry-point trace yields at one shape point."""

    peak_bytes: int = 0
    #: rendered task×node planes materialized anywhere in the program
    tn_temps: List[str] = dataclasses.field(default_factory=list)


class _Liveness:
    """Linear-scan liveness over a closed jaxpr, recursing into control-flow
    sub-jaxprs.  ``_scan_program`` returns the peak bytes of values a
    (sub-)program allocates itself — operands are charged by the caller."""

    #: record at most this many [T,N] planes per entry (messages stay short)
    MAX_TN_SAMPLES = 8

    def __init__(self, sp: ShapePoint):
        self.sp = sp
        self.task_dims, self.node_dims = _axis_dims(sp)
        self.tn_temps: List[str] = []
        self.tn_count = 0

    # -- task×node plane detection --------------------------------------

    def _note_tn(self, eqn, v) -> None:
        if not self.task_dims or not self.node_dims:
            return
        aval = getattr(v, "aval", None)
        shape = tuple(getattr(aval, "shape", ()) or ())
        if len(shape) < 2:
            return
        has_t = any(int(d) in self.task_dims for d in shape)
        has_n = any(int(d) in self.node_dims for d in shape)
        if has_t and has_n:
            self.tn_count += 1
            if len(self.tn_temps) < self.MAX_TN_SAMPLES:
                self.tn_temps.append(
                    f"{eqn.primitive} -> {_fmt_aval(aval, self.sp)}"
                    f" ({_var_bytes(v):,} B)")

    # -- sub-jaxpr transient extra ---------------------------------------

    def _eqn_extra(self, eqn) -> int:
        prim = str(eqn.primitive)
        if prim == "cond":
            branches = eqn.params.get("branches", ())
            return max(
                (self._scan_program(getattr(b, "jaxpr", b))
                 for b in branches), default=0)
        if prim == "while":
            cond = eqn.params.get("cond_jaxpr")
            body = eqn.params.get("body_jaxpr")
            return max(
                self._scan_program(getattr(cond, "jaxpr", cond)) if cond else 0,
                self._scan_program(getattr(body, "jaxpr", body)) if body else 0,
            )
        if prim == "scan":
            body = eqn.params.get("jaxpr")
            return (self._scan_program(getattr(body, "jaxpr", body))
                    if body is not None else 0)
        # pjit / closed_call / custom_* / remat / shard_map / pallas_call:
        # walk every reachable sub-jaxpr; shard_map bodies carry per-shard
        # LOCAL avals, so their internal peak is already per-device
        return sum(self._scan_program(s) for s in _sub_jaxprs(eqn))

    # -- the linear scan -------------------------------------------------

    def _scan_program(self, jaxpr) -> int:
        live = sum(_var_bytes(v) for v in jaxpr.constvars)
        peak = live
        n_eqns = len(jaxpr.eqns)
        last: Dict = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for v in eqn.invars:
                if hasattr(v, "aval") and not _is_literal(v):
                    last[v] = i
        for v in jaxpr.outvars:
            if hasattr(v, "aval") and not _is_literal(v):
                last[v] = n_eqns  # outputs survive the program
        owned: Dict = {}
        for i, eqn in enumerate(jaxpr.eqns):
            out_divs = (_shard_divisors(eqn, "out_names", len(eqn.outvars))
                        if str(eqn.primitive) == "shard_map"
                        else [1] * len(eqn.outvars))
            out_b = 0
            for v, d in zip(eqn.outvars, out_divs):
                b = _var_bytes(v) // max(1, d)
                out_b += b
                self._note_tn(eqn, v)
                if last.get(v, -1) > i:
                    owned[v] = b
            extra = self._eqn_extra(eqn)
            live += out_b
            peak = max(peak, live + extra)
            # dead-on-arrival results (DropVars, unused outputs) and
            # operands at their last read free right after the eqn
            for v, d in zip(eqn.outvars, out_divs):
                if last.get(v, -1) <= i:
                    live -= _var_bytes(v) // max(1, d)
            for v in eqn.invars:
                if _is_literal(v):
                    continue
                if v in owned and last.get(v) == i:
                    live -= owned.pop(v)
        return peak

    # -- entry point: the top-level program ------------------------------

    def run(self, closed_jaxpr, donated_flat: Set[int]) -> LivenessStats:
        jaxpr = closed_jaxpr.jaxpr
        n_eqns = len(jaxpr.eqns)
        last: Dict = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for v in eqn.invars:
                if hasattr(v, "aval") and not _is_literal(v):
                    last[v] = i
        outset = set()
        for v in jaxpr.outvars:
            if hasattr(v, "aval") and not _is_literal(v):
                last[v] = n_eqns
                outset.add(v)

        # a top-level invar consumed ONLY by shard_map eqns is resident
        # per-device at its sharded size; everything else at global bytes
        consumers: Dict = {}
        shard_div: Dict = {}
        for eqn in jaxpr.eqns:
            is_sm = str(eqn.primitive) == "shard_map"
            divs = (_shard_divisors(eqn, "in_names", len(eqn.invars))
                    if is_sm else [1] * len(eqn.invars))
            for v, d in zip(eqn.invars, divs):
                if hasattr(v, "aval") and not _is_literal(v):
                    consumers.setdefault(v, set()).add(d if is_sm else 1)
        for v, divs in consumers.items():
            if len(divs) == 1:
                shard_div[v] = next(iter(divs))

        def in_bytes(v) -> int:
            return _var_bytes(v) // max(1, shard_div.get(v, 1))

        live = sum(_var_bytes(v) for v in jaxpr.constvars)
        live += sum(in_bytes(v) for v in jaxpr.invars)
        peak = live
        owned: Dict = {}
        for idx, v in enumerate(jaxpr.invars):
            if idx in donated_flat and v not in outset:
                if v in last and last[v] < n_eqns:
                    owned[v] = in_bytes(v)
                else:
                    live -= in_bytes(v)  # donated and never read: free now

        for i, eqn in enumerate(jaxpr.eqns):
            out_divs = (_shard_divisors(eqn, "out_names", len(eqn.outvars))
                        if str(eqn.primitive) == "shard_map"
                        else [1] * len(eqn.outvars))
            out_b = 0
            for v, d in zip(eqn.outvars, out_divs):
                b = _var_bytes(v) // max(1, d)
                out_b += b
                self._note_tn(eqn, v)
                if last.get(v, -1) > i and v not in shard_div:
                    owned[v] = b
                    shard_div[v] = d  # results keep their sharded residency
                elif last.get(v, -1) > i:
                    owned[v] = b
            extra = self._eqn_extra(eqn)
            live += out_b
            peak = max(peak, live + extra)
            for v, d in zip(eqn.outvars, out_divs):
                if last.get(v, -1) <= i:
                    live -= _var_bytes(v) // max(1, d)
            for v in eqn.invars:
                if _is_literal(v):
                    continue
                if v in owned and last.get(v) == i:
                    live -= owned.pop(v)
        return LivenessStats(peak_bytes=peak, tn_temps=list(self.tn_temps))


def _is_literal(v) -> bool:
    return hasattr(v, "val")


def peak_live_bytes(closed_jaxpr, donated_flat: Iterable[int] = (),
                    sp: Optional[ShapePoint] = None) -> int:
    """Peak live bytes of one closed jaxpr (donated flat-invar indices get
    the free-after-last-read credit).  The raw engine behind KBT201,
    exposed for tests and ad-hoc what-fits probes."""
    from kube_batch_tpu.analysis.jaxpr_audit import _AUDIT_POINT

    lv = _Liveness(sp or _AUDIT_POINT)
    return lv.run(closed_jaxpr, set(donated_flat)).peak_bytes


# --------------------------------------------------------------------------
# donation mapping + realization (KBT203)
# --------------------------------------------------------------------------


def _flat_ranges(args, n_flat: int) -> Optional[List[Tuple[int, int]]]:
    """Per-argument (start, stop) ranges into the traced flat invars, by
    counting array-typed pytree leaves (static config objects and python
    scalars contribute none).  None when the count disagrees with the
    trace — the caller then skips donation modeling rather than guess."""
    import jax

    ranges: List[Tuple[int, int]] = []
    i = 0
    for a in args:
        leaves = jax.tree_util.tree_leaves(a)
        c = sum(1 for leaf in leaves
                if hasattr(leaf, "shape") and hasattr(leaf, "dtype"))
        ranges.append((i, i + c))
        i += c
    return ranges if i == n_flat else None


def _donated_flat(entry: EntryPoint, args, n_flat: int) -> Optional[Set[int]]:
    """Flat invar indices of the entry's DECLARED accelerator donation
    (donate["*"] — CPU wrappers gate donation off, but the budget models
    the accelerator).  None when the argnum→flat mapping is ambiguous."""
    declared = entry.donate.get("*", ())
    if not declared:
        return set()
    ranges = _flat_ranges(args, n_flat)
    if ranges is None:
        return None
    flat: Set[int] = set()
    for argnum in declared:
        if argnum >= len(ranges):
            return None
        lo, hi = ranges[argnum]
        flat.update(range(lo, hi))
    return flat


def _unrealized_donations(entry: EntryPoint, args,
                          closed_jaxpr) -> List[Tuple[int, List[str]]]:
    """[(argnum, descriptions)] for declared donated args where NO flat
    component can alias any output (shape+dtype match, each output slot
    consumed once — mirroring XLA's buffer-donation matching)."""
    declared = entry.donate.get("*", ())
    if not declared:
        return []
    jaxpr = closed_jaxpr.jaxpr
    ranges = _flat_ranges(args, len(jaxpr.invars))
    if ranges is None:
        return []
    pool: List = []
    for v in jaxpr.outvars:
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            pool.append((tuple(aval.shape), str(aval.dtype)))
    out: List[Tuple[int, List[str]]] = []
    for argnum in sorted(declared):
        if argnum >= len(ranges):
            continue
        lo, hi = ranges[argnum]
        avals = [getattr(jaxpr.invars[i], "aval", None) for i in range(lo, hi)]
        matched_any = False
        for aval in avals:
            key = (tuple(aval.shape), str(aval.dtype))
            if key in pool:
                pool.remove(key)  # each output aliases at most one input
                matched_any = True
        if avals and not matched_any:
            out.append((argnum, [
                f"{str(a.dtype)}{list(a.shape)}" for a in avals]))
    return out


# --------------------------------------------------------------------------
# per-entry, per-point audit
# --------------------------------------------------------------------------


@dataclasses.dataclass
class EntryReport:
    """One (entry, shape point) audit result — stats plus raw findings
    (allowlist not yet applied)."""

    entry: str
    point: str
    steady: bool
    traced: bool
    peak_bytes: int = 0
    budget: int = 0
    findings: List[Tuple[str, str]] = dataclasses.field(default_factory=list)


def _fmt_bytes(b: int) -> str:
    if b >= GIB:
        return f"{b / GIB:.2f} GiB"
    return f"{b / 2**20:.1f} MiB"


def audit_entry_at(entry: EntryPoint, sp: ShapePoint,
                   budget: Optional[int] = None,
                   label: Optional[str] = None) -> EntryReport:
    """Trace one entry at one shape point and run KBT201-204 over the
    closed jaxpr.  A build/trace failure is a KBT000 finding naming the
    shape point (a broken entry must not read as clean OR kill the tier —
    a shape-derived python branch blowing up at 1M×100k is exactly the
    regression class this audit exists to surface)."""
    from kube_batch_tpu.utils.jitstats import collective_inventory

    if budget is None:
        budget, label = budget_bytes()
    rep = EntryReport(entry=entry.name, point=sp.name, steady=entry.steady,
                      traced=False, budget=budget)
    try:
        fn, args = entry.build(sp)
        traced = fn.trace(*args)
        closed = traced.jaxpr
    except Exception as e:  # noqa: BLE001 — report, don't crash the tier
        rep.findings.append((
            "KBT000",
            f"entry point failed to trace at shape point {sp.name} "
            f"(T={sp.T}, N={sp.N}): {type(e).__name__}: {e}"))
        return rep
    rep.traced = True

    donated = _donated_flat(entry, args, len(closed.jaxpr.invars))
    lv = _Liveness(sp)
    stats = lv.run(closed, donated or set())
    rep.peak_bytes = stats.peak_bytes

    # KBT201: fit the per-device budget
    if stats.peak_bytes > budget:
        rep.findings.append((
            "KBT201",
            f"peak live bytes {_fmt_bytes(stats.peak_bytes)} exceed the "
            f"{label or 'v5e'} budget {_fmt_bytes(budget)} at shape point "
            f"{sp.name} (T={sp.T}, N={sp.N}) — "
            f"{stats.peak_bytes / budget:.1f}x over"))

    # KBT202: steady-path programs must stay off task×node planes
    if entry.steady and lv.tn_count:
        sample = "; ".join(stats.tn_temps[:3])
        rep.findings.append((
            "KBT202",
            f"{lv.tn_count} task-axis × node-axis temporar"
            f"{'y' if lv.tn_count == 1 else 'ies'} in a steady-path "
            f"program at {sp.name} (e.g. {sample}) — the steady dispatch "
            "contract is the compacted [P, K] candidate geometry "
            "(ROADMAP 1)"))

    # KBT203: declared donations must be aliasable into outputs
    for argnum, avals in _unrealized_donations(entry, args, closed):
        rep.findings.append((
            "KBT203",
            f"declared donation of arg {argnum} ({', '.join(avals)}) has "
            "no shape/dtype-matching output to alias — XLA would ignore "
            "it and the budget's free-after-last-read credit is fiction"))

    # KBT204: per-round collectives must not scale with the node axis
    _, node_dims = _axis_dims(sp)
    inv = collective_inventory(closed, detail=True)
    node_sites = [
        s for s in inv.get("sites", ())
        if s["depth"] >= 1 and any(int(d) in node_dims for d in s["shape"])
    ]
    if node_sites:
        parts = []
        for s in node_sites[:4]:
            dims = ", ".join(_dim_label(int(d), sp) for d in s["shape"])
            trip = (f" ×{s['inner_trips']}/round" if s["inner_trips"] > 1
                    else "")
            trip += " ×unbounded-inner-loop" if s["unbounded_trips"] else ""
            parts.append(f"{s['prim']}[{s['dtype']}[{dims}]] = "
                         f"{s['bytes']:,} B{trip}")
        rep.findings.append((
            "KBT204",
            f"{len(node_sites)} per-round collective(s) with node-axis "
            f"payloads at {sp.name}: {'; '.join(parts)} — the cross-host "
            "contract is O(tasks) bytes per bidding round"))
    return rep


# --------------------------------------------------------------------------
# allowlist: (entry glob, rule, point glob) → mandatory reason
# --------------------------------------------------------------------------

#: The tier-C suppression registry — and deliberately ALSO the burn-down
#: list for ROADMAP item 1 (sparse-first scale jump): every entry names the
#: ROADMAP sub-item that deletes it.  Stale entries (nothing matched) fail
#: the audit, so a fix can't leave its waiver behind.
HBM_ALLOWLIST: Dict[Tuple[str, str, str], str] = {
    # -- ROADMAP 1.(1): evict still scores full-matrix [T, N] bid planes --
    # (single-device, sentinel-fused, and both sharded impls inherit them;
    # the sharded bodies hold [T, N/shards] per device — same verdict)
    ("ops.eviction.evict_solve[*]", "KBT202", "*"):
        "ROADMAP 1.(1): eviction scores full [T, N] bid planes; the "
        "candidate-table + warm-carry rebuild over per-(queue, node) "
        "capacity keys is the planned fix",
    ("ops.eviction.evict_solve[*]", "KBT201", "northstar-1m"):
        "ROADMAP 1.(1): the full-matrix bid planes blow the v5e budget at "
        "1M\u00d7100k; evict is gated to \u2264headline scale until sparse "
        "eviction lands",
    ("ops.invariants.evict_sentinel_solve[*]", "KBT202", "*"):
        "ROADMAP 1.(1): sentinel-fused evict inherits the bare solve's "
        "full-matrix bid planes",
    ("ops.invariants.evict_sentinel_solve[*]", "KBT201", "northstar-1m"):
        "ROADMAP 1.(1): sentinel-fused evict inherits the bare solve's "
        "over-budget planes at 1M\u00d7100k",
    ("parallel.mesh.*sharded_evict_solve[*]", "KBT202", "*"):
        "ROADMAP 1.(1): sharded evict (both impls, sentinel-fused "
        "included) shards the bid planes over nodes but still holds "
        "[T, N/shards] per device",
    ("parallel.mesh.*sharded_evict_solve[*]", "KBT201", "northstar-1m"):
        "ROADMAP 1.(1): [T, N/8] per device is ~200 GiB at 1M\u00d7100k "
        "\u2014 sharding alone cannot absorb a full-matrix plane",
    # -- ROADMAP 1.(2): the compacted topk path's table build + shard_map
    #    exhaustion fallback keep [P, N] score/hash planes ----------------
    ("ops.assignment.allocate_topk_solve", "KBT202", "*"):
        "ROADMAP 1.(2): the candidate-table build scores [P, N] planes "
        "(and the exhaustion fallback re-enters them); blocked/pallas "
        "table rebuild is the planned fix",
    ("ops.assignment.allocate_topk_solve", "KBT201", "northstar-1m"):
        "ROADMAP 1.(2): the [P, N] build planes are ~26 GiB each at "
        "P=65536, N=100k \u2014 over v5e budget until the blocked rebuild",
    ("ops.invariants.allocate_topk_sentinel_solve", "KBT202", "*"):
        "ROADMAP 1.(2): sentinel-fused topk inherits the table build's "
        "[P, N] planes",
    ("ops.invariants.allocate_topk_sentinel_solve", "KBT201",
     "northstar-1m"):
        "ROADMAP 1.(2): sentinel-fused topk inherits the over-budget "
        "build planes at 1M\u00d7100k",
    ("parallel.mesh.*sharded_allocate_topk_solve[*]", "KBT202", "*"):
        "ROADMAP 1.(2): the sharded topk build/fallback holds "
        "[P, N/shards] score/hash planes per device (pjit oracle: "
        "unsharded [P, N] \u2014 charged at global bytes, documented "
        "slack)",
    ("parallel.mesh.*sharded_allocate_topk_solve[*]", "KBT201",
     "northstar-1m"):
        "ROADMAP 1.(2): the sharded build planes still exceed v5e at "
        "1M\u00d7100k; re-enter via blocked table REBUILD instead",
    ("ops.assignment.warm_allocate_solve", "KBT202", "*"):
        "ROADMAP 1.(2): the warm refresh escalates to the cold table "
        "build ([P, N] planes) when the carry is invalid; same fix",
    ("ops.assignment.warm_allocate_solve", "KBT201", "northstar-1m"):
        "ROADMAP 1.(2): warm's cold-escalation branch carries the build "
        "planes past v5e at 1M\u00d7100k",
    ("ops.invariants.warm_allocate_sentinel_solve", "KBT202", "*"):
        "ROADMAP 1.(2): sentinel-fused warm inherits the cold-escalation "
        "[P, N] planes",
    ("ops.invariants.warm_allocate_sentinel_solve", "KBT201",
     "northstar-1m"):
        "ROADMAP 1.(2): sentinel-fused warm inherits the over-budget "
        "escalation planes at 1M\u00d7100k",
    ("parallel.mesh.*sharded_warm_allocate_solve[*]", "KBT202", "*"):
        "ROADMAP 1.(2): sharded warm (both impls, sentinel-fused "
        "included) inherits the build/fallback planes per device",
    ("parallel.mesh.*sharded_warm_allocate_solve[*]", "KBT201",
     "northstar-1m"):
        "ROADMAP 1.(2): sharded warm's escalation planes still exceed "
        "v5e at 1M\u00d7100k",
    # -- cold oracles + diagnostics: not steady-path (no KBT202 claim),
    #    but their full-matrix peaks are on the same ROADMAP 1 burn-down --
    ("ops.assignment.allocate_solve", "KBT201", "northstar-1m"):
        "ROADMAP 1: the full-matrix allocate is the COLD bit-exactness "
        "oracle; at 1M\u00d7100k only the compacted path dispatches \u2014 "
        "the oracle runs at \u2264headline scale",
    ("ops.invariants.allocate_sentinel_solve", "KBT201", "northstar-1m"):
        "ROADMAP 1: sentinel-fused full-matrix oracle, same scale gate as "
        "the bare oracle",
    ("parallel.mesh.sharded_allocate_solve[*]", "KBT201", "northstar-1m"):
        "ROADMAP 1: sharded full-matrix oracle (incl. the 2-D mesh "
        "variant): [T, N/shards] per device cannot fit at 1M\u00d7100k; "
        "cross-check runs at \u2264headline scale",
    ("parallel.mesh.sentinel_sharded_allocate_solve[*]", "KBT201",
     "northstar-1m"):
        "ROADMAP 1: sentinel-fused sharded oracle, same scale gate",
    ("ops.assignment.failure_histogram_solve", "KBT201", "northstar-1m"):
        "ROADMAP 1: the full-walk failure histogram is an on-demand "
        "diagnostic (not dispatched per cycle); the bucket variant is the "
        "at-scale surface and the node axis still wants compaction",
    ("parallel.mesh.sharded_failure_histogram[*]", "KBT201",
     "northstar-1m"):
        "ROADMAP 1: sharded full-walk histogram, same on-demand diagnostic "
        "verdict",
    ("ops.assignment.failure_histogram_bucket_solve", "KBT201",
     "northstar-1m"):
        "ROADMAP 1: the bucket histogram still walks [P, N] reason "
        "planes; per-(reason, node-shard) partials are the planned "
        "compaction",
    ("parallel.mesh.sharded_failure_histogram_bucket[*]", "KBT201",
     "northstar-1m"):
        "ROADMAP 1: sharded bucket histogram holds [P, N/shards] reason "
        "planes per device \u2014 1.2\u00d7 over v5e at 1M\u00d7100k, "
        "closest corner to done",
}


def _glob_match(name: str, pat: str) -> bool:
    """fnmatch-style ``*`` wildcards with NO character classes — entry
    names contain literal brackets (``evict_solve[reclaim]``), so the
    pattern language is: ``*`` matches anything, all else is literal."""
    rx = re.escape(pat).replace(r"\*", ".*")
    return re.fullmatch(rx, name) is not None


def _allowlist_reason(allowlist, entry_name: str, rule: str,
                      point: str) -> Optional[Tuple[Tuple, str]]:
    for key, reason in allowlist.items():
        e_pat, a_rule, p_pat = key
        if (a_rule == rule and _glob_match(entry_name, e_pat)
                and _glob_match(point, p_pat)):
            return key, reason
    return None


# --------------------------------------------------------------------------
# the tier driver
# --------------------------------------------------------------------------


def run_hbm_audit(
    registry: Optional[Sequence[EntryPoint]] = None,
    points: Optional[Sequence[ShapePoint]] = None,
    select: Optional[Sequence[str]] = None,
    allowlist: Optional[Dict[Tuple[str, str, str], str]] = None,
) -> List[Finding]:
    """Audit every registered entry point at every ladder point.  Returns
    engine Findings at paths ``<hbm:entry@point>`` — allowlisted ones
    dropped, empty-reason and STALE allowlist entries surfaced as KBT000
    (same contract as tier A/B suppressions: a waiver that no longer
    waives anything must be deleted, not accumulate)."""
    if registry is None:
        registry = tuple(REGISTRY) + sharded_registry()
    if points is None:
        points = shape_points()
    if allowlist is None:
        allowlist = HBM_ALLOWLIST

    findings: List[Finding] = []
    used: Set[Tuple] = set()
    for entry in registry:
        for sp in points:
            rep = audit_entry_at(entry, sp)
            path = f"<hbm:{entry.name}@{sp.name}>"
            for rule, message in rep.findings:
                hit = (None if rule == "KBT000" else
                       _allowlist_reason(allowlist, entry.name, rule, sp.name))
                if hit is not None:
                    key, reason = hit
                    used.add(key)
                    if not reason.strip():
                        findings.append(Finding(
                            "KBT000", path, 0, 0,
                            f"allowlist[{key}] has no reason — "
                            "suppression ignored"))
                    continue
                findings.append(Finding(rule, path, 0, 0, message))

    # stale allowlist entries: only judged when the corresponding entries
    # and points were actually in this run (a single-device run must not
    # flag sharded-namespace waivers, nor a one-point run the rest of the
    # ladder)
    entry_names = [e.name for e in registry]
    point_names = [sp.name for sp in points]
    for key, reason in allowlist.items():
        if key in used:
            continue
        e_pat, _rule, p_pat = key
        covered = (
            any(_glob_match(n, e_pat) for n in entry_names)
            and any(_glob_match(n, p_pat) for n in point_names)
        )
        if covered:
            findings.append(Finding(
                "KBT000", "<hbm:allowlist>", 0, 0,
                f"stale allowlist entry {key}: matched no finding — the "
                "corner it waived is fixed; delete the entry "
                f"(reason was: {reason})"))

    if select is not None:
        wanted = set(select) | {"KBT000"}
        findings = [f for f in findings if f.rule in wanted]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def headroom_report(
    registry: Optional[Sequence[EntryPoint]] = None,
    points: Optional[Sequence[ShapePoint]] = None,
) -> Dict:
    """bytes-vs-budget per entry per shape point — the bench's
    hbm_headroom section records this so the headroom trajectory is
    tracked across PRs like any other perf number."""
    if registry is None:
        registry = tuple(REGISTRY) + sharded_registry()
    if points is None:
        points = shape_points()
    budget, label = budget_bytes()
    entries: Dict[str, Dict[str, Dict]] = {}
    for entry in registry:
        per_point: Dict[str, Dict] = {}
        for sp in points:
            rep = audit_entry_at(entry, sp, budget=budget, label=label)
            per_point[sp.name] = {
                "traced": rep.traced,
                "peak_bytes": rep.peak_bytes,
                "headroom_bytes": budget - rep.peak_bytes,
                "over_budget": rep.peak_bytes > budget,
                "findings": [r for r, _ in rep.findings],
            }
        entries[entry.name] = per_point
    return {
        "budget_bytes": budget,
        "budget_profile": label,
        "points": [
            {"name": sp.name, "tasks": sp.tasks, "nodes": sp.nodes,
             "T": sp.T, "N": sp.N, "P": sp.P, "topk": sp.topk}
            for sp in points
        ],
        "entries": entries,
    }
