"""Flow-aware analysis infrastructure for the kbt-check engine.

PR 2's rules were line-local AST matchers; the PR 3 device-resident hot
path breeds bugs those cannot see — a donated buffer read three statements
after the donating call, a jit wrapper constructed per cycle, a telemetry
clock value leaking into control flow.  `go vet` closes this class for the
Go reference with SSA-based passes; this module is the sized-for-us analog:

- :class:`ImportTable` — import resolution: every local name bound by an
  ``import``/``from .. import`` anywhere in the module maps to its dotted
  origin, so a rule asks "does this call resolve to ``jax.jit``?" instead
  of string-matching on whatever alias the module happened to pick.
- :class:`ModuleContext` — the per-module symbol table the engine builds
  once and shares across every flow rule: the parsed tree, resolved
  imports, last top-level binding per module-global name, and the flat
  list of function bodies to analyze.
- :func:`walk_function` — intra-procedural def-use tracking: an ordered
  walk of one function body in evaluation order, maintaining a name →
  *cell* environment.  A cell models the underlying buffer/value: plain
  ``y = x`` aliasing shares x's cell, any other assignment rebinds to a
  fresh cell — so taint set through one name is visible through its
  aliases and cleared by reassignment.  Branches fork the environment and
  merge may-style (a taint set in either branch survives the join); loop
  bodies run twice so state created at the bottom of an iteration is
  observed by reads at the top of the next.

Deliberately intra-procedural (the `go vet` passes this mirrors are too):
a value escaping into an attribute, a return, or a foreign call is treated
as leaving the analysis — rules stay conservative there and rely on the
suppression contract for the rare annotated escape.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

# --------------------------------------------------------------------------
# import resolution
# --------------------------------------------------------------------------


class ImportTable:
    """Local name → dotted origin for every import binding in the module.

    ``import jax`` binds ``jax → jax``; ``import numpy as np`` binds
    ``np → numpy``; ``from jax import jit as J`` binds ``J → jax.jit``.
    Function-local imports count too — the resolution is name-based, which
    is exact enough for lint purposes (shadowing an import with a local
    variable of the same name is its own smell)."""

    def __init__(self, tree: ast.Module):
        self.names: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.names[alias.asname] = alias.name
                    else:
                        # `import a.b` binds the TOP name `a` to module `a`
                        top = alias.name.split(".")[0]
                        self.names[top] = top
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    bound = alias.asname or alias.name
                    self.names[bound] = f"{node.module}.{alias.name}"

    def dotted(self, node: ast.AST) -> str:
        """Canonical dotted path of a Name/Attribute chain, resolved through
        the import table (``np.asarray`` → ``numpy.asarray``). Empty string
        when the base is not an imported name (a local variable, a call
        result, ...)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return ""
        base = self.names.get(node.id)
        if base is None:
            return ""
        parts.append(base)
        return ".".join(reversed(parts))


# --------------------------------------------------------------------------
# module symbol table
# --------------------------------------------------------------------------


class ModuleContext:
    """Everything the flow rules need about one module, built once per file
    by the engine and shared across rules (five rules re-walking the tree
    for imports would be pure waste at package scale)."""

    def __init__(self, tree: ast.Module, relpath: str):
        self.tree = tree
        self.relpath = relpath
        self.imports = ImportTable(tree)
        #: last top-level assignment expression per module-global name
        #: (descending through If/Try at module level, the KBT003 idiom)
        self.module_assigns: Dict[str, ast.expr] = {}
        #: every function/method body in the module (nested defs included —
        #: each is analyzed as its own scope)
        self.functions: List[ast.FunctionDef] = []
        stack: List[ast.AST] = list(tree.body)
        while stack:
            node = stack.pop(0)
            if isinstance(node, (ast.If, ast.Try)):
                stack = list(ast.iter_child_nodes(node)) + stack
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_assigns[t.id] = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name):
                    self.module_assigns[node.target.id] = node.value
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.append(node)

    def resolve_call(self, call: ast.Call) -> str:
        """Dotted origin of a call's callee ('' when unresolvable)."""
        return self.imports.dotted(call.func)


# --------------------------------------------------------------------------
# intra-procedural def-use walk
# --------------------------------------------------------------------------

#: a cell is the mutable record shared by every alias of one value; rules
#: stash taint under their own keys ("donated", "telemetry", "device", ...)
Cell = Dict[str, object]


@dataclasses.dataclass
class FlowEvent:
    """One observation during the walk, in evaluation order."""

    kind: str               # "load" | "call" | "bind"
    node: ast.AST
    name: str = ""          # load/bind: the Name involved
    cell: Optional[Cell] = None
    #: enclosing expression contexts, outermost first — e.g. ("test",
    #: "compare") for a load inside `while a - b > x:`
    where: Tuple[str, ...] = ()


class FlowVisitor:
    """Subclass hooks for :func:`walk_function`.  All hooks receive the
    live environment so they can read/alias/taint cells."""

    def on_load(self, ev: FlowEvent, env: Dict[str, Cell]) -> None: ...

    def on_call(self, ev: FlowEvent, env: Dict[str, Cell]) -> None: ...

    def on_bind(self, ev: FlowEvent, env: Dict[str, Cell],
                value: Optional[ast.expr]) -> None:
        """After the default binding action (alias copy or fresh cell)."""


def _merge_envs(base: Dict[str, Cell], forks: List[Dict[str, Cell]]) -> Dict[str, Cell]:
    """May-style join: a name maps to its fork cell when all forks agree,
    else to a fresh union cell carrying every fork's taint keys (so taint
    set in either branch survives; a clean rebind in ONE branch does not
    launder taint flowing around it)."""
    names: Set[str] = set(base)
    for f in forks:
        names.update(f)
    out: Dict[str, Cell] = {}
    for name in names:
        cells = [f[name] for f in forks if name in f]
        if name in base:
            cells.append(base[name])
        first = cells[0]
        if all(c is first for c in cells):
            out[name] = first
            continue
        union: Cell = {}
        for c in cells:
            union.update(c)
        out[name] = union
    return out


class _Walker:
    def __init__(self, visitor: FlowVisitor):
        self.v = visitor

    # -- expressions ------------------------------------------------------
    def expr(self, node: ast.AST, env: Dict[str, Cell],
             where: Tuple[str, ...]) -> None:
        if node is None or isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.Lambda, ast.ClassDef)):
            return  # nested scopes run later, elsewhere
        inner = where
        if isinstance(node, ast.Compare):
            inner = where + ("compare",)
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            self.v.on_load(
                FlowEvent("load", node, name=node.id, cell=env.get(node.id),
                          where=where), env)
            return
        for child in ast.iter_child_nodes(node):
            self.expr(child, env, inner)
        if isinstance(node, ast.Call):
            self.v.on_call(FlowEvent("call", node, where=where), env)

    # -- binding ----------------------------------------------------------
    def bind(self, target: ast.AST, value: Optional[ast.expr],
             env: Dict[str, Cell]) -> None:
        if isinstance(target, ast.Name):
            if isinstance(value, ast.Name) and value.id in env:
                env[target.id] = env[value.id]  # alias: share the cell
            else:
                env[target.id] = {}
            self.v.on_bind(
                FlowEvent("bind", target, name=target.id,
                          cell=env[target.id]), env, value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts_v = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                      and len(value.elts) == len(target.elts) else None)
            for i, t in enumerate(target.elts):
                # element-wise when shapes line up; otherwise every element
                # binds against the whole RHS (conservative: unpacking a
                # tainted call taints every target name)
                self.bind(t, elts_v[i] if elts_v else value, env)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, None, env)
        # attribute/subscript stores don't (re)bind a local name; the value
        # escaped — rules observe that through on_call/on_load if they care

    # -- statements -------------------------------------------------------
    def body(self, stmts: Iterable[ast.stmt], env: Dict[str, Cell]) -> None:
        for s in stmts:
            self.stmt(s, env)

    def stmt(self, s: ast.stmt, env: Dict[str, Cell]) -> None:
        if isinstance(s, ast.Assign):
            self.expr(s.value, env, ())
            for t in s.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    self.expr(t, env, ("store",))
                self.bind(t, s.value, env)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.expr(s.value, env, ())
                self.bind(s.target, s.value, env)
        elif isinstance(s, ast.AugAssign):
            self.expr(s.value, env, ())
            # target is read-modify-write: observe the read, keep the cell
            self.expr(ast.copy_location(
                ast.Name(id=s.target.id, ctx=ast.Load()), s.target)
                if isinstance(s.target, ast.Name) else s.target, env, ())
        elif isinstance(s, (ast.Expr, ast.Return)):
            self.expr(s.value, env, ())
        elif isinstance(s, ast.If):
            self.expr(s.test, env, ("test",))
            fork_a = dict(env)
            self.body(s.body, fork_a)
            fork_b = dict(env)
            self.body(s.orelse, fork_b)
            merged = _merge_envs(env, [fork_a, fork_b])
            env.clear()
            env.update(merged)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            self.expr(s.iter, env, ())
            # two passes: taint created at the bottom of the body reaches
            # reads at the top on the second iteration
            for _ in range(2):
                self.bind(s.target, None, env)
                self.body(s.body, env)
            self.body(s.orelse, env)
        elif isinstance(s, ast.While):
            for _ in range(2):
                self.expr(s.test, env, ("test",))
                self.body(s.body, env)
            self.body(s.orelse, env)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self.expr(item.context_expr, env, ())
                if item.optional_vars is not None:
                    self.bind(item.optional_vars, None, env)
            self.body(s.body, env)
        elif isinstance(s, ast.Try):
            self.body(s.body, env)
            for h in s.handlers:
                fork = dict(env)
                if h.name:
                    fork[h.name] = {}
                self.body(h.body, fork)
                merged = _merge_envs(env, [fork])
                env.clear()
                env.update(merged)
            self.body(s.orelse, env)
            self.body(s.finalbody, env)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            return  # separate scope
        elif isinstance(s, ast.Match):
            self.expr(s.subject, env, ())
            forks: List[Dict[str, Cell]] = []
            for case in s.cases:
                fork = dict(env)
                # pattern captures (MatchAs/MatchStar names, MatchMapping
                # rest) bind fresh cells in the arm's scope
                for p in ast.walk(case.pattern):
                    name = getattr(p, "name", None) or getattr(p, "rest", None)
                    if isinstance(name, str):
                        fork[name] = {}
                if case.guard is not None:
                    self.expr(case.guard, fork, ("test",))
                self.body(case.body, fork)
                forks.append(fork)
            merged = _merge_envs(env, forks)
            env.clear()
            env.update(merged)
        elif isinstance(s, (ast.Raise, ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(s):
                self.expr(child, env, ())
            if isinstance(s, ast.Delete):
                for t in s.targets:
                    if isinstance(t, ast.Name):
                        env.pop(t.id, None)
        # Pass/Break/Continue/Global/Nonlocal/Import: nothing to track


def walk_function(func: ast.AST, visitor: FlowVisitor) -> None:
    """Run `visitor` over one function body in evaluation order (module
    docstring has the semantics: alias cells, may-merge joins, two-pass
    loops).  Parameters start with fresh cells so loads of them resolve."""
    env: Dict[str, Cell] = {}
    args = func.args
    for a in (list(args.posonlyargs) + list(args.args)
              + list(args.kwonlyargs)
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        env[a.arg] = {}
    _Walker(visitor).body(func.body, env)


# --------------------------------------------------------------------------
# shared small helpers (used by the flow rules)
# --------------------------------------------------------------------------


def const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Evaluate a constant int-tuple expression — the shapes donate_argnums
    takes.  Conditional expressions fold may-style (union of both arms:
    the lint cares whether a position CAN be donated)."""
    if isinstance(node, ast.IfExp):
        a = const_int_tuple(node.body)
        b = const_int_tuple(node.orelse)
        if a is None and b is None:
            return None
        return tuple(sorted(set(a or ()) | set(b or ())))
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    return None


def call_keyword(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None
