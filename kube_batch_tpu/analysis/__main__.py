"""`python -m kube_batch_tpu.analysis` — run the kbt-check lint rules.

Exit status: 0 clean, 1 findings, 2 usage error. `--jsonl` emits one JSON
object per finding on stdout for CI consumption; the human format is
`path:line:col: RULE message` (clickable in most editors).
"""

from __future__ import annotations

import argparse
import json
import sys

from kube_batch_tpu.analysis.engine import run_paths
from kube_batch_tpu.analysis.rules import ALL_RULES, RULES_BY_ID


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kube_batch_tpu.analysis",
        description="kbt-check: project-specific static analysis "
                    "(rule catalog: ANALYSIS.md)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to analyze (default: the kube_batch_tpu "
             "package tree)",
    )
    parser.add_argument(
        "--jsonl", action="store_true",
        help="machine-readable output: one JSON object per finding",
    )
    parser.add_argument(
        "--select", metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            scope = ", ".join(rule.scope) if rule.scope else "package-wide"
            print(f"{rule.id}  {rule.title}  [{scope}]")
        return 0

    rules = None
    if args.select:
        ids = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in ids if r not in RULES_BY_ID]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
        rules = [RULES_BY_ID[r] for r in ids]

    findings = run_paths(args.paths, rules=rules)
    for f in findings:
        if args.jsonl:
            print(json.dumps(f.to_dict(), sort_keys=True))
        else:
            print(f.render())
    if not args.jsonl:
        n = len(findings)
        print(f"kbt-check: {n} finding{'s' if n != 1 else ''}"
              if n else "kbt-check: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
