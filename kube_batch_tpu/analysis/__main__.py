"""`python -m kube_batch_tpu.analysis` — run the kbt-check lint tiers.

Tier A (default): the static AST/flow rules over the package tree.
Tier B (``--jaxpr``): the jaxpr-level audit of the registered jitted entry
points (analysis/jaxpr_audit.py) — added to the static run; ``--jaxpr-only``
skips tier A.
Tier C (``--hbm``): the liveness/HBM-budget audit (analysis/hbm_audit.py) —
traces every registered entry point at the abstract shape ladder up to the
1M×100k north star and checks peak live bytes against the backend budget;
``--hbm-only`` runs just that tier.
Tier D (``--races``): the thread/lock-domain race rules (analysis/races.py,
KBT301–304) — added to the static run; ``--races-only`` runs just that
tier, and ``--domains`` prints the inferred per-class lock-domain map
instead of findings.  ``--select``/``--jsonl`` apply to all tiers
uniformly (``KBT012`` is accepted as an alias for ``KBT302``).

Exit status: 0 clean, 1 findings, 2 usage error.  `--jsonl` emits one JSON
object per finding on stdout for CI consumption; the human format is
`path:line:col: RULE message` (clickable in most editors).
"""

from __future__ import annotations

import argparse
import json
import sys

from kube_batch_tpu.analysis.engine import run_paths
from kube_batch_tpu.analysis.races import (
    RACE_RULES, RACE_RULES_BY_ID, RULE_ALIASES, domains_report,
)
from kube_batch_tpu.analysis.rules import ALL_RULES, RULES_BY_ID


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kube_batch_tpu.analysis",
        description="kbt-check: project-specific static analysis "
                    "(rule catalog: ANALYSIS.md)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to analyze (default: the kube_batch_tpu "
             "package tree)",
    )
    parser.add_argument(
        "--jsonl", action="store_true",
        help="machine-readable output: one JSON object per finding",
    )
    parser.add_argument(
        "--select", metavar="RULES",
        help="comma-separated rule ids to run (default: all); KBT10x ids "
             "select jaxpr-audit checks, KBT20x ids select HBM-audit "
             "checks, KBT30x ids select the race tier",
    )
    parser.add_argument(
        "--jaxpr", action="store_true",
        help="additionally run the jaxpr-level audit of the registered "
             "jitted entry points (imports jax)",
    )
    parser.add_argument(
        "--jaxpr-only", action="store_true",
        help="run only the jaxpr audit tier",
    )
    parser.add_argument(
        "--hbm", action="store_true",
        help="additionally run the liveness/HBM-budget audit of every "
             "registered entry point at the abstract shape ladder "
             "(imports jax; CPU-safe — traces only, never allocates)",
    )
    parser.add_argument(
        "--hbm-only", action="store_true",
        help="run only the HBM audit tier",
    )
    parser.add_argument(
        "--races", action="store_true",
        help="additionally run the tier-D thread/lock-domain race rules "
             "(KBT301-304; pure AST, no jax import)",
    )
    parser.add_argument(
        "--races-only", action="store_true",
        help="run only the race tier",
    )
    parser.add_argument(
        "--domains", action="store_true",
        help="print the tier-D inferred per-class lock-domain map "
             "(reviewable form of the model the race rules check against) "
             "and exit 0",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog",
    )
    args = parser.parse_args(argv)

    # the audit-rule ids live here, not in rules.py — keep the static tier
    # importable without jax
    from kube_batch_tpu.analysis.hbm_audit import HBM_RULES
    from kube_batch_tpu.analysis.jaxpr_audit import AUDIT_RULES

    if args.list_rules:
        for rule in ALL_RULES:
            scope = ", ".join(rule.scope) if rule.scope else "package-wide"
            print(f"{rule.id}  {rule.title}  [{scope}]")
        for rid, title in AUDIT_RULES.items():
            print(f"{rid}  {title}  [jaxpr audit]")
        for rid, title in HBM_RULES.items():
            print(f"{rid}  {title}  [hbm audit]")
        for rule in RACE_RULES:
            print(f"{rule.id}  {rule.title}  [race analysis]")
        for alias, target in sorted(RULE_ALIASES.items()):
            print(f"{alias}  alias for {target}")
        return 0

    if args.domains:
        print(domains_report(args.paths))
        return 0

    static_rules = None
    audit_select = None
    hbm_select = None
    race_rules = None
    if args.select:
        ids = [r.strip() for r in args.select.split(",") if r.strip()]
        ids = [RULE_ALIASES.get(r, r) for r in ids]
        unknown = [r for r in ids
                   if r not in RULES_BY_ID and r not in AUDIT_RULES
                   and r not in HBM_RULES and r not in RACE_RULES_BY_ID]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
        static_ids = [r for r in ids if r in RULES_BY_ID]
        audit_ids = [r for r in ids if r in AUDIT_RULES]
        hbm_ids = [r for r in ids if r in HBM_RULES]
        race_ids = [r for r in ids if r in RACE_RULES_BY_ID]
        # with an explicit selection, each tier runs exactly its selected
        # rules: naming audit rules implies the audit tier, and a selection
        # with NO audit ids skips the audit entirely even under --jaxpr —
        # tracing six entry points only to discard every finding would
        # both waste the cost and let CI believe the tier ran
        audit_select = audit_ids
        hbm_select = hbm_ids
        args.jaxpr = bool(audit_ids)
        args.hbm = bool(hbm_ids)
        args.races = bool(race_ids)
        only_implied = not static_ids
        args.jaxpr_only = bool(audit_ids) and only_implied
        args.hbm_only = bool(hbm_ids) and only_implied
        args.races_only = bool(race_ids) and only_implied
        if static_ids:
            static_rules = [RULES_BY_ID[r] for r in static_ids]
        if race_ids:
            race_rules = [RACE_RULES_BY_ID[r] for r in race_ids]

    skip_static = args.jaxpr_only or args.hbm_only or args.races_only
    if args.select:
        skip_static = static_rules is None

    findings = []
    if not skip_static:
        findings.extend(run_paths(args.paths, rules=static_rules))
    if args.races or args.races_only:
        findings.extend(
            run_paths(args.paths, rules=race_rules or list(RACE_RULES))
        )
    if args.jaxpr or args.jaxpr_only:
        from kube_batch_tpu.analysis.jaxpr_audit import run_audit

        findings.extend(run_audit(select=audit_select))
    if args.hbm or args.hbm_only:
        from kube_batch_tpu.analysis.hbm_audit import run_hbm_audit

        findings.extend(run_hbm_audit(select=hbm_select))

    # tiers A and D both flow through run_paths, so engine-level findings
    # (KBT000: bad suppression, missing path, broken module) would repeat
    # once per tier — dedupe identical findings, order preserved
    seen = set()
    deduped = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            deduped.append(f)
    findings = deduped

    for f in findings:
        if args.jsonl:
            print(json.dumps(f.to_dict(), sort_keys=True))
        else:
            print(f.render())
    if not args.jsonl:
        n = len(findings)
        print(f"kbt-check: {n} finding{'s' if n != 1 else ''}"
              if n else "kbt-check: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
