"""Runtime lock-order validator — the Linux lockdep idea, sized for this
codebase.

Go gives the reference `go test -race`; this port's PR 1 writer-executor
race and the TokenBucket sleep-under-lock both slipped past review. The
validator instruments the locks our concurrent modules create and, while
the ordinary test suite runs, records per-thread held-lock sets to build
the lock-acquisition-order graph:

- **Order inversion**: thread 1 acquires A then B, thread 2 acquires B then
  A — a deadlock waiting for the right interleaving. Locks are grouped by
  CREATION SITE (module:line), the analog of lockdep's lock classes, so an
  inversion between any two instances of the same site pair is caught even
  when the individual test never deadlocks.  Detection is TRANSITIVE over
  the recorded acquisition graph: a new edge A→B is a violation whenever a
  path B→…→A already exists, so the 3-lock cycle A→B→C→A (no direct
  two-lock inversion anywhere) reports the moment its closing edge lands,
  with the full chain and each edge's first-observed stack.
- **Blocking under lock**: `time.sleep` / `Future.result` / `Event.wait`
  reached while the thread holds any tracked lock (the TokenBucket bug, as
  a runtime check).
- **Lock-hold / contention profile**: every tracked acquire records its
  acquire-WAIT (time blocked entering the lock) and, on release, its
  HOLD time, accumulated per lock class (creation site).  This is the
  profile the ROADMAP's "striped per-kind ingest locks (profile first)"
  item asks for: ``profile_report()`` ranks sites by total wait, so the
  bench's ``lock_profile`` section (and any lockdep-instrumented test
  run) can say whether the single staging buffer actually contends
  before anyone pays for striping.  Accumulation is PER-THREAD (merged
  at report time), so profiling adds no cross-thread synchronization to
  the very contention it measures.

`install()` patches `threading.Lock`/`RLock` with factories that return
instrumented locks ONLY when the creating frame belongs to one of the
target modules (default: cache/, cache/volume, cmd/server, k8s/watch,
metrics/) — stdlib and third-party locks are untouched. The pytest plugin
(`kube_batch_tpu.analysis.pytest_plugin`) installs this for the whole
suite and fails the run on violations.

Same-site nesting (two instances of one lock class held at once) is a
violation unless the region is wrapped in
``utils.blocking.allow_nesting("reason")``: two instances of one class
have no defined order between them, so undeclared nesting is an ordering
claim nobody wrote down (PR 2 skipped this case wholesale; the annotation
turns the skip into a validated declaration).  Sanctioned nesting records
no self-edge — an instance-level order inside one class is the
annotation's claim, not the graph's.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

from kube_batch_tpu.utils import blocking as _blocking

#: modules whose locks are instrumented by default — the concurrent core
DEFAULT_MODULE_PREFIXES = (
    "kube_batch_tpu.cache",
    "kube_batch_tpu.cmd.server",
    "kube_batch_tpu.k8s.watch",
    "kube_batch_tpu.metrics",
    # the pipelined loop's locks (the CycleTrigger condition guard): the
    # dirty-advance hook notifies UNDER the cache's big lock, so the
    # big→trigger edge — and any future reverse nesting — must be observed
    "kube_batch_tpu.scheduler",
    # the observability plane (tracer/recorder/alerts leaf locks) and the
    # guard plane: spans close from the cycle AND writeback threads, and
    # alert evaluation reads the guard's lock — their edges belong in the
    # graph
    "kube_batch_tpu.obs",
    "kube_batch_tpu.guard",
)

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_SLEEP = time.sleep
_REAL_FUTURE_RESULT = concurrent.futures.Future.result
_REAL_EVENT_WAIT = threading.Event.wait
#: wall clock for the contention profile — captured at import so the
#: profile is immune to any clock patching (lockdep itself patches sleep)
_REAL_PERF = time.perf_counter

# re-exported for detector-side callers; runtime code imports it from
# utils/blocking.py directly so annotating a region never pulls the lint
# engine into a scheduler process
allow_blocking = _blocking.allow_blocking


@dataclasses.dataclass
class Violation:
    kind: str  # "order-inversion" | "blocking-under-lock" |
    #            "undeclared-nesting" | "unguarded-access"
    description: str
    stack: str

    def render(self) -> str:
        return f"[{self.kind}] {self.description}\n{self.stack}"


def _stack(skip: int = 2, limit: int = 14) -> str:
    frames = traceback.format_stack()[:-skip]
    return "".join(frames[-limit:])


class LockdepState:
    """The acquisition-order graph + per-thread held sets + violations."""

    def __init__(self) -> None:
        # internal bookkeeping lock: a REAL lock, created before any
        # patching, never visible to the graph
        self._mu = _REAL_LOCK()
        # (site_a, site_b) -> stack where a->b was first observed
        self.edges: Dict[Tuple[str, str], str] = {}
        # site -> successor sites (the same graph as `edges`, shaped for
        # the transitive-cycle search)
        self._adj: Dict[str, set] = {}
        self.violations: List[Violation] = []
        # sites whose undeclared same-site nesting already reported (one
        # report per site, not one per occurrence)
        self._nested_sites: set = set()
        self._local = threading.local()
        # per-thread contention/hold accumulators (merged by
        # profile_report); entries: site → [n, wait_s, wait_max, hold_s,
        # hold_max] — per-thread so profiling never serializes the very
        # contention it measures
        self._profs: List[Dict[str, list]] = []

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """A site path src → … → dst over the recorded acquisition edges
        (iterative DFS; the class graph is tiny), or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._adj.get(node, ()):
                if nxt == dst:
                    return path + [dst]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- held-set helpers --------------------------------------------------
    def _held(self) -> List[list]:
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []
        return held  # entries: [site, lock_id, depth, t_acquired]

    def _prof(self) -> Dict[str, list]:
        prof = getattr(self._local, "prof", None)
        if prof is None:
            prof = self._local.prof = {}
            with self._mu:
                self._profs.append(prof)
        return prof

    def _note_wait(self, site: str, wait: float) -> None:
        prof = self._prof()
        rec = prof.get(site)
        if rec is None:
            rec = prof[site] = [0, 0.0, 0.0, 0.0, 0.0]
        rec[0] += 1
        rec[1] += wait
        if wait > rec[2]:
            rec[2] = wait


    def held_sites(self) -> List[str]:
        return [e[0] for e in self._held()]

    # -- events ------------------------------------------------------------
    def on_acquired(self, site: str, lock_id: int,
                    wait: float = 0.0) -> None:
        self._note_wait(site, wait)
        held = self._held()
        for entry in held:
            if entry[1] == lock_id:
                entry[2] += 1  # reentrant RLock acquire
                return
        # same-site nesting: a DIFFERENT instance of this lock class is
        # already held.  Two instances of one class have no defined order,
        # so the nesting is an ordering claim — valid only when declared
        # via utils.blocking.allow_nesting("reason")
        if (
            any(e[0] == site for e in held)
            and not _blocking.nesting_allowed()
            and site not in self._nested_sites
        ):
            stack = _stack(skip=3)
            with self._mu:
                if site not in self._nested_sites:
                    self._nested_sites.add(site)
                    self.violations.append(Violation(
                        "same-site-nesting",
                        f"two instances of lock class {site} held by one "
                        "thread without an allow_nesting declaration — "
                        "per-object locks of one class have no defined "
                        "order; wrap the region in utils.blocking."
                        "allow_nesting(\"<order invariant>\") or impose a "
                        "global order",
                        stack,
                    ))
        # membership probe OUTSIDE the bookkeeping lock and BEFORE paying
        # traceback formatting: steady state (every edge already recorded —
        # the cache bind loops re-acquire the same pairs constantly) is a
        # couple of dict lookups; the GIL makes the dict read safe and the
        # locked re-check below closes the race
        candidates = [
            (hsite, site)
            for hsite, _hid, _d, _t in held
            # same-site pairs never enter the graph: a self-edge would be
            # an instant cycle, and declared nesting (allow_nesting) is an
            # instance-level claim, not a class-order edge
            if hsite != site
            and (hsite, site) not in self.edges
        ]
        if candidates:
            stack = _stack(skip=3)
            inversions = []
            with self._mu:
                for edge in candidates:
                    a, b = edge
                    if edge in self.edges:
                        continue  # raced in since the unlocked probe
                    # a NEW a->b edge closes a deadlock cycle iff a path
                    # b ->* a already exists — length 1 is the direct
                    # inversion, longer is the transitive A→B→C→A case
                    cycle = self._path(b, a)
                    self.edges[edge] = stack
                    self._adj.setdefault(a, set()).add(b)
                    if cycle is not None:
                        inversions.append((edge, cycle))
                for (a, b), cycle in inversions:
                    if len(cycle) == 2:
                        desc = (
                            f"lock order inverted: this thread acquired "
                            f"{a} then {b}, but {b} -> {a} was previously "
                            f"observed"
                        )
                        detail = (
                            f"--- {a} -> {b} acquired at:\n{stack}"
                            f"--- {b} -> {a} first observed at:\n"
                            f"{self.edges[(b, a)]}"
                        )
                    else:
                        chain = " -> ".join(cycle)
                        desc = (
                            f"lock order inverted (transitive): this thread "
                            f"acquired {a} then {b}, closing the cycle "
                            f"{a} -> {b} against the previously observed "
                            f"chain {chain}"
                        )
                        parts = [f"--- {a} -> {b} acquired at:\n{stack}"]
                        parts.extend(
                            f"--- {x} -> {y} first observed at:\n"
                            f"{self.edges[(x, y)]}"
                            for x, y in zip(cycle, cycle[1:])
                        )
                        detail = "".join(parts)
                    self.violations.append(
                        Violation("order-inversion", desc, detail)
                    )
        held.append([site, lock_id, 1, _REAL_PERF()])

    def on_released(self, lock_id: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] == lock_id:
                held[i][2] -= 1
                if held[i][2] == 0:
                    hold = _REAL_PERF() - held[i][3]
                    rec = self._prof().get(held[i][0])
                    if rec is not None:
                        rec[3] += hold
                        if hold > rec[4]:
                            rec[4] = hold
                    del held[i]
                return

    def on_blocking_call(self, what: str) -> None:
        held = self.held_sites()
        if not held or _blocking.blocking_allowed():
            return
        with self._mu:
            self.violations.append(Violation(
                "blocking-under-lock",
                f"{what} while holding {', '.join(held)}",
                _stack(skip=3),
            ))

    def report(self) -> str:
        lines = [
            f"lockdep: {len(self.edges)} lock-order edges, "
            f"{len(self.violations)} violation(s)"
        ]
        for v in self.violations:
            lines.append(v.render())
        return "\n".join(lines)

    def profile_report(self) -> Dict[str, Dict[str, float]]:
        """Merged per-site contention/hold profile: site → {acquires,
        wait_ms_total, wait_ms_max, hold_ms_total, hold_ms_max}, the
        per-thread accumulators folded together."""
        with self._mu:
            profs = list(self._profs)
        merged: Dict[str, list] = {}
        for prof in profs:
            for site, rec in list(prof.items()):
                m = merged.setdefault(site, [0, 0.0, 0.0, 0.0, 0.0])
                m[0] += rec[0]
                m[1] += rec[1]
                m[2] = max(m[2], rec[2])
                m[3] += rec[3]
                m[4] = max(m[4], rec[4])
        return {
            site: {
                "acquires": m[0],
                "wait_ms_total": round(m[1] * 1e3, 3),
                "wait_ms_max": round(m[2] * 1e3, 3),
                "hold_ms_total": round(m[3] * 1e3, 3),
                "hold_ms_max": round(m[4] * 1e3, 3),
            }
            for site, m in sorted(
                merged.items(), key=lambda kv: -kv[1][1]
            )
        }


class TrackedLock:
    """A Lock/RLock wrapper feeding the lockdep state. `site` is the
    creation site (module:line) — the lock's class in lockdep terms."""

    def __init__(self, state: LockdepState, site: str, reentrant: bool = False):
        self._state = state
        self.site = site
        self._lock = _REAL_RLOCK() if reentrant else _REAL_LOCK()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = _REAL_PERF()
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._state.on_acquired(self.site, id(self),
                                    wait=_REAL_PERF() - t0)
        return ok

    def release(self) -> None:
        self._state.on_released(id(self))
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._lock, "locked", None)
        return locked() if locked is not None else False

    def __repr__(self) -> str:
        return f"<TrackedLock {self.site}>"


_installed: Optional["_Installation"] = None


class _Installation:
    def __init__(self, state: LockdepState, prefixes: Tuple[str, ...]):
        self.state = state
        self.prefixes = prefixes

    def _creation_site(self):
        """(module, module:line) of the frame that called the patched
        factory — two frames up from here: [0]=_creation_site, [1]=the
        factory, [2]=the code running `threading.Lock()`."""
        try:
            frame = sys._getframe(2)
        except ValueError:
            return "", "?"
        mod = frame.f_globals.get("__name__", "")
        return mod, f"{mod or '?'}:{frame.f_lineno}"

    def _tracked(self, mod: str) -> bool:
        return any(mod == p or mod.startswith(p + ".") for p in self.prefixes)

    # the patched factories (bound methods keep `self` out of the signature)
    def make_lock(self):
        mod, site = self._creation_site()
        if self._tracked(mod):
            return TrackedLock(self.state, site, reentrant=False)
        return _REAL_LOCK()

    def make_rlock(self):
        mod, site = self._creation_site()
        if self._tracked(mod):
            return TrackedLock(self.state, site, reentrant=True)
        return _REAL_RLOCK()


def install(prefixes: Tuple[str, ...] = DEFAULT_MODULE_PREFIXES) -> LockdepState:
    """Patch the lock factories + blocking primitives. Idempotent: a second
    install returns the active state."""
    global _installed
    if _installed is not None:
        return _installed.state
    state = LockdepState()
    inst = _Installation(state, prefixes)
    _installed = inst

    threading.Lock = inst.make_lock
    threading.RLock = inst.make_rlock

    def checked_sleep(seconds):
        state.on_blocking_call(f"time.sleep({seconds!r})")
        return _REAL_SLEEP(seconds)

    def checked_result(self, timeout=None):
        # an already-done future can't block — only flag a real wait
        if not self.done():
            state.on_blocking_call("Future.result()")
        return _REAL_FUTURE_RESULT(self, timeout)

    def checked_wait(self, timeout=None):
        if not self.is_set():
            state.on_blocking_call("Event.wait()")
        return _REAL_EVENT_WAIT(self, timeout)

    time.sleep = checked_sleep
    concurrent.futures.Future.result = checked_result
    threading.Event.wait = checked_wait
    return state


def uninstall() -> Optional[LockdepState]:
    """Restore the real primitives; returns the state for reporting."""
    global _installed
    if _installed is None:
        return None
    state = _installed.state
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    time.sleep = _REAL_SLEEP
    concurrent.futures.Future.result = _REAL_FUTURE_RESULT
    threading.Event.wait = _REAL_EVENT_WAIT
    _installed = None
    return state


def current_state() -> Optional[LockdepState]:
    return _installed.state if _installed is not None else None


# ---------------------------------------------------------------------------
# guarded-access corroborator (kbt-check tier D, analysis/races.py)
#
# The static analyzer infers, per class, which lock attribute dominates each
# shared attribute ("lock domains").  This runtime leg cross-validates the
# map the same way tier B's jaxpr audit corroborates tier A: hot shared
# structures are instrumented with a data descriptor that asserts, at access
# time, that the statically inferred domain lock is actually held by the
# accessing thread.  Static says "every access site holds _lock"; runtime
# says "and every access the suite actually executed did".
#
# Enforcement semantics:
# - An instance is CONFINED until a second distinct thread touches it —
#   single-thread instances (most unit-test fixtures) never enforce, so the
#   check only fires where a race is physically possible.
# - Ownership must be attributable: TrackedLock (held-set lookup) and
#   RLock/Condition (_is_owned) qualify; a plain untracked Lock records no
#   owner, so access under one is skipped rather than misreported.
# - `utils.blocking.allow_unguarded("reason")` regions are exempt — the
#   runtime analog of `# kbt: allow[KBT301]`.
# - Violations dedupe per (class, attr) and land in LockdepState.violations,
#   so the pytest plugin fails the run exactly like an order inversion.
# ---------------------------------------------------------------------------

_REAL_GET_IDENT = threading.get_ident


def _owned_by_current(lock) -> Optional[bool]:
    """Does the calling thread own `lock`?  None = ownership cannot be
    attributed (plain Lock, or a foreign object) — callers skip, never
    report, on None."""
    if lock is None:
        return None
    if isinstance(lock, TrackedLock):
        return any(e[1] == id(lock) for e in lock._state._held())
    owned = getattr(lock, "_is_owned", None)
    if owned is not None:
        try:
            return bool(owned())
        except Exception:  # noqa: BLE001 — a foreign _is_owned never reports
            return None
    return None


class _GuardedAttr:
    """Class-level data descriptor standing in for one instrumented plain
    instance attribute.  Values keep living in the instance `__dict__`
    under the same name (a data descriptor shadows the instance dict), so
    uninstalling the descriptor restores direct attribute access with the
    last value intact."""

    def __init__(self, install: "GuardedAccessInstallation", cls: type,
                 attr: str, lock_attr: str, sample: int = 1):
        self._install = install
        self._cls = cls
        self.attr = attr
        self.lock_attr = lock_attr
        self.sample = max(1, int(sample))
        self._count = 0  # benign data race: sampling only needs "roughly Nth"

    def __get__(self, inst, objtype=None):
        if inst is None:
            return self
        self._check(inst, "read")
        try:
            return inst.__dict__[self.attr]
        except KeyError:
            raise AttributeError(
                f"{type(inst).__name__!r} object has no attribute "
                f"{self.attr!r}"
            ) from None

    def __set__(self, inst, value) -> None:
        self._check(inst, "write")
        inst.__dict__[self.attr] = value

    def __delete__(self, inst) -> None:
        self._check(inst, "delete")
        inst.__dict__.pop(self.attr, None)

    def _check(self, inst, op: str) -> None:
        d = inst.__dict__
        idents = d.get("_kbt_guard_idents")
        if idents is None:
            idents = d.setdefault("_kbt_guard_idents", set())
        idents.add(_REAL_GET_IDENT())  # own-ident add: GIL-atomic
        if len(idents) < 2:
            return  # thread-confined so far — no race is possible yet
        self._count += 1
        if self.sample > 1 and self._count % self.sample:
            return
        if _blocking.unguarded_allowed():
            return
        # read the lock straight from the instance dict: the lock attr is
        # never itself instrumented, and __init__ ordering (value set
        # before the lock exists) degrades to a skip, not a crash
        if _owned_by_current(d.get(self.lock_attr)) is False:
            self._install._report(self, inst, op)


class GuardedAccessInstallation:
    """One batch of instrumented (class, attr, domain-lock) triples."""

    def __init__(self, state: LockdepState):
        self.state = state
        self._patched: List[Tuple[type, str]] = []
        self._reported: set = set()
        self._mu = _REAL_LOCK()

    def _report(self, desc: _GuardedAttr, inst, op: str) -> None:
        key = (desc._cls.__name__, desc.attr)
        if key in self._reported:
            return
        stack = _stack(skip=4)
        with self._mu:
            if key in self._reported:
                return
            self._reported.add(key)
        with self.state._mu:
            self.state.violations.append(Violation(
                "unguarded-access",
                f"{op} of {desc._cls.__name__}.{desc.attr} without holding "
                f"its inferred domain lock self.{desc.lock_attr} (tier D "
                "lock-domain map, analysis/races.py) on an instance already "
                "shared across threads — hold the lock or wrap the region "
                "in utils.blocking.allow_unguarded(\"<reason>\")",
                stack,
            ))

    def uninstall(self) -> None:
        for cls, attr in self._patched:
            if isinstance(cls.__dict__.get(attr), _GuardedAttr):
                delattr(cls, attr)
        self._patched = []


def install_guarded_access(specs, state: Optional[LockdepState] = None,
                           sample: int = 1) -> GuardedAccessInstallation:
    """Instrument `(module, class_name, attr, lock_attr)` tuples (the shape
    `races.runtime_domain_specs` returns, so the table is always the
    STATICALLY inferred one).  `state` defaults to the active lockdep
    state; violations appended there fail the plugin run."""
    import importlib

    if state is None:
        state = current_state()
    if state is None:
        state = LockdepState()
    inst = GuardedAccessInstallation(state)
    for module, cls_name, attr, lock_attr in specs:
        cls = getattr(importlib.import_module(module), cls_name)
        if isinstance(cls.__dict__.get(attr), _GuardedAttr):
            continue  # already instrumented (idempotent re-install)
        setattr(cls, attr, _GuardedAttr(inst, cls, attr, lock_attr, sample))
        inst._patched.append((cls, attr))
    return inst
