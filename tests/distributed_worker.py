"""Worker process for the two-process distributed smoke test
(tests/test_distributed.py). NOT a pytest module.

Each of the two ranks: joins the jax.distributed cluster over the given
coordinator, builds the IDENTICAL deterministic snapshot, distributes it
over the global 8-device mesh with the production shardings, runs the
sharded allocate solve, and (every rank — the outputs are replicated)
compares the assignment against the purely-local single-process solve.
Prints "MATCH placed=<n>" on success.
"""

import os
import sys


def main() -> None:
    coordinator, rank = sys.argv[1], int(sys.argv[2])
    # envutil owns the axon workaround: env hardening BEFORE the first jax
    # import, plus deregistration of the axon PJRT factory sitecustomize may
    # already have registered at interpreter start (a wedged tunnel would
    # otherwise hang backend init even on CPU)
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from kube_batch_tpu.envutil import (
        apply_hardened_cpu_env,
        deregister_axon_backend,
    )

    apply_hardened_cpu_env(n_devices=4)
    deregister_axon_backend()
    import jax

    from kube_batch_tpu.parallel.distributed import global_mesh, initialize

    initialize(coordinator=coordinator, num_processes=2, process_id=rank)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert len(jax.local_devices()) == 4

    import numpy as np

    from kube_batch_tpu import plugins as _p  # noqa: F401 — registers
    from kube_batch_tpu.actions.allocate import (
        build_session_snapshot,
        session_allocate_config,
    )
    from kube_batch_tpu.framework.conf import load_scheduler_conf
    from kube_batch_tpu.framework.session import close_session, open_session
    from kube_batch_tpu.ops.assignment import allocate_solve
    from kube_batch_tpu.parallel.mesh import (
        sharded_allocate_solve,
        snapshot_shardings,
    )
    from kube_batch_tpu.testing.synthetic import synthetic_cluster

    # deterministic: both ranks build the same cluster (seed=0) — the
    # multi-controller contract: every process runs the same program
    cache = synthetic_cluster(n_tasks=128, n_nodes=300, gang_size=4,
                              n_queues=2, seed=0)
    conf = load_scheduler_conf(None)
    ssn = open_session(cache, conf.tiers)
    try:
        snap, meta = build_session_snapshot(ssn)
        config = session_allocate_config(ssn)

        # local single-process reference solve (local 4-device jit, no mesh)
        local = jax.device_get(allocate_solve(snap, config).assigned)

        mesh = global_mesh()
        assert mesh.devices.size == 8
        shardings = snapshot_shardings(mesh)

        def distribute(x, sharding):
            arr = np.asarray(x)
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx]
            )

        gsnap = jax.tree.map(distribute, snap, shardings)
        result = sharded_allocate_solve(gsnap, config, mesh)
        dist = jax.device_get(result.assigned)  # replicated output

        # BOTH sharded implementations, explicitly: the shard_map body's
        # authored collectives must cross the real two-process boundary
        # (ICI within a rank, DCN between) and still match the pjit oracle
        # and the local solve bit-for-bit
        from kube_batch_tpu.parallel.mesh import allocate_solve_fn

        with mesh:
            sm = jax.device_get(
                allocate_solve_fn(mesh, config, impl="shard_map")(gsnap)
                .assigned
            )
            pj = jax.device_get(
                allocate_solve_fn(mesh, config, impl="pjit")(gsnap).assigned
            )

        # per-host sharded residency: each process diffs the full host
        # column but SHIPS only its own shards' rows (the
        # make_array_from_callback path) — the scatter-refreshed device
        # columns must round-trip bit-exact on every host's local shards
        from kube_batch_tpu.api.resident import ShardedPerCycleDeviceCache

        rc = ShardedPerCycleDeviceCache(mesh)
        with mesh:
            rc.swap(snap)
            host = np.asarray(snap.node_idle).copy()
            host[5] += 1.0
            host[257] += 2.0  # a row on the other process's shard
            snap2 = snap._replace(node_idle=host)
            sw2 = rc.swap(snap2)
        resident_ok = rc.scatter_updates > 0
        for s in sw2.node_idle.addressable_shards:
            if not np.array_equal(np.asarray(s.data), host[s.index]):
                resident_ok = False
    finally:
        close_session(ssn)

    if not np.array_equal(local, dist):
        diff = int((local != dist).sum())
        print(f"MISMATCH rank={rank} differing={diff}", flush=True)
        sys.exit(1)
    if not (np.array_equal(local, sm) and np.array_equal(local, pj)):
        print(f"IMPL MISMATCH rank={rank}"
              f" shard_map={np.array_equal(local, sm)}"
              f" pjit={np.array_equal(local, pj)}", flush=True)
        sys.exit(1)
    if not resident_ok:
        print(f"RESIDENT MISMATCH rank={rank}", flush=True)
        sys.exit(1)
    print("RESIDENT OK", flush=True)
    placed = int((dist >= 0).sum())
    assert placed > 0
    print(f"MATCH placed={placed}", flush=True)


if __name__ == "__main__":
    main()
