"""Runtime lock-order detector: the synthetic A→B/B→A inversion and the
lock-held-across-sleep case must be caught; consistent ordering and
reentrant acquires must stay clean.

Every test here builds a PRIVATE LockdepState — the session-global one the
pytest plugin installed (tests/conftest.py) watches the real suite and must
never see these provoked violations."""

import threading

from kube_batch_tpu.analysis import lockdep
from kube_batch_tpu.analysis.lockdep import LockdepState, TrackedLock


def _locks(state, *sites):
    return [TrackedLock(state, site) for site in sites]


class TestOrderInversion:
    def test_ab_ba_inversion_is_flagged(self):
        state = LockdepState()
        a, b = _locks(state, "mod.cache:10", "mod.volume:20")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        kinds = [v.kind for v in state.violations]
        assert kinds == ["order-inversion"]
        assert "mod.cache:10" in state.violations[0].description
        assert "mod.volume:20" in state.violations[0].description
        # both acquisition stacks are carried for diagnosis
        assert "first observed at" in state.violations[0].stack

    def test_inversion_across_threads_is_flagged(self):
        state = LockdepState()
        a, b = _locks(state, "A", "B")

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        th1 = threading.Thread(target=t1)
        th1.start()
        th1.join()
        th2 = threading.Thread(target=t2)
        th2.start()
        th2.join()
        assert [v.kind for v in state.violations] == ["order-inversion"]

    def test_consistent_order_is_clean(self):
        state = LockdepState()
        a, b, c = _locks(state, "A", "B", "C")
        for _ in range(3):
            with a:
                with b:
                    with c:
                        pass
        assert state.violations == []
        assert ("A", "B") in state.edges and ("B", "C") in state.edges

    def test_same_instance_reentrant_rlock_is_clean(self):
        state = LockdepState()
        r = TrackedLock(state, "R", reentrant=True)
        with r:
            with r:
                pass
        assert state.violations == []
        assert state.edges == {}

    def test_same_site_nesting_without_declaration_is_flagged(self):
        # two instances of one lock class have no defined order — PR 2
        # skipped this wholesale; since PR 4 undeclared nesting reports
        state = LockdepState()
        x1 = TrackedLock(state, "S")
        x2 = TrackedLock(state, "S")
        with x1:
            with x2:
                pass
        assert [v.kind for v in state.violations] == ["same-site-nesting"]
        assert "S" in state.violations[0].description
        assert "allow_nesting" in state.violations[0].description
        # no self-edge enters the order graph (it would be an instant cycle)
        assert ("S", "S") not in state.edges

    def test_same_site_nesting_reported_once_per_site(self):
        state = LockdepState()
        x1 = TrackedLock(state, "S")
        x2 = TrackedLock(state, "S")
        for _ in range(3):
            with x1:
                with x2:
                    pass
        assert len(state.violations) == 1

    def test_allow_nesting_declares_the_order(self):
        from kube_batch_tpu.utils.blocking import allow_nesting

        state = LockdepState()
        x1 = TrackedLock(state, "S")
        x2 = TrackedLock(state, "S")
        with allow_nesting("aggregate lock order: acquired sorted by uid"):
            with x1:
                with x2:
                    pass
        assert state.violations == []

    def test_allow_nesting_requires_a_reason(self):
        import pytest

        from kube_batch_tpu.utils.blocking import allow_nesting

        with pytest.raises(ValueError):
            with allow_nesting("  "):
                pass

    def test_allow_nesting_does_not_sanction_blocking(self):
        # the two annotations are separate switches: a nesting-sanctioned
        # region still reports blocking-under-lock
        from kube_batch_tpu.utils.blocking import allow_nesting

        state = LockdepState()
        x1 = TrackedLock(state, "S")
        x2 = TrackedLock(state, "S")
        with allow_nesting("declared nesting for this test"):
            with x1:
                with x2:
                    state.on_blocking_call("time.sleep(0.1)")
        assert [v.kind for v in state.violations] == ["blocking-under-lock"]

    def test_cross_site_order_still_checked_inside_allow_nesting(self):
        # the annotation declares SAME-site nesting only; a cross-site
        # inversion inside the region must still report
        from kube_batch_tpu.utils.blocking import allow_nesting

        state = LockdepState()
        a, b = _locks(state, "A", "B")
        with a:
            with b:
                pass
        with allow_nesting("same-site declaration must not mask this"):
            with b:
                with a:
                    pass
        assert [v.kind for v in state.violations] == ["order-inversion"]

    def test_transitive_three_lock_cycle_is_flagged(self):
        # A→B, B→C recorded with no direct two-lock inversion anywhere;
        # the closing C→A edge completes A→B→C→A and must report with the
        # full chain (the pre-PR detector only caught direct A→B/B→A)
        state = LockdepState()
        a, b, c = _locks(state, "mod.cache:1", "mod.volume:2", "mod.server:3")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:
                pass
        kinds = [v.kind for v in state.violations]
        assert kinds == ["order-inversion"]
        v = state.violations[0]
        assert "transitive" in v.description
        for site in ("mod.cache:1", "mod.volume:2", "mod.server:3"):
            assert site in v.description
        # every chain edge carries its first-observed stack for diagnosis
        assert v.stack.count("first observed at") == 2

    def test_transitive_dag_without_cycle_is_clean(self):
        # A→B, B→C, A→C is a DAG — consistent global order, no report
        state = LockdepState()
        a, b, c = _locks(state, "A", "B", "C")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with a:
            with c:
                pass
        assert state.violations == []

    def test_transitive_cycle_reported_once(self):
        state = LockdepState()
        a, b, c = _locks(state, "A", "B", "C")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        for _ in range(3):
            with c:
                with a:
                    pass
        # the closing edge is recorded on first sight; repeats are cache
        # hits in the unlocked probe and must not re-report
        assert len(state.violations) == 1

    def test_duplicate_inversions_not_double_reported(self):
        state = LockdepState()
        a, b = _locks(state, "A", "B")
        with a:
            with b:
                pass
        for _ in range(3):
            with b:
                with a:
                    pass
        # the b->a edge is recorded after the first report, so the
        # inversion fires once, not once per repetition
        assert len(state.violations) == 1


class TestBlockingUnderLock:
    def test_sleep_while_holding_lock_is_flagged(self):
        state = LockdepState()
        (a,) = _locks(state, "mod.server:30")
        with a:
            state.on_blocking_call("time.sleep(0.1)")
        assert [v.kind for v in state.violations] == ["blocking-under-lock"]
        assert "mod.server:30" in state.violations[0].description

    def test_sleep_outside_lock_is_clean(self):
        state = LockdepState()
        (a,) = _locks(state, "A")
        with a:
            pass
        state.on_blocking_call("time.sleep(0.1)")
        assert state.violations == []

    def test_release_order_need_not_be_lifo(self):
        state = LockdepState()
        a, b = _locks(state, "A", "B")
        a.acquire()
        b.acquire()
        a.release()
        state.on_blocking_call("time.sleep(0.1)")  # still holds B
        b.release()
        state.on_blocking_call("time.sleep(0.1)")  # holds nothing
        assert len(state.violations) == 1
        assert "B" in state.violations[0].description


class TestInstallation:
    def test_suite_runs_under_the_global_detector(self):
        # tests/conftest.py wires the plugin; unless explicitly disabled the
        # whole tier-1 suite is a lockdep run — the go test -race analog
        import os

        if os.environ.get("KBT_LOCKDEP", "1").lower() in ("0", "false", "no"):
            return
        state = lockdep.current_state()
        assert state is not None
        # the patched factories only instrument target-module locks:
        # a lock created here (tests.*) must be a real primitive
        lk = threading.Lock()
        assert not isinstance(lk, TrackedLock)

    def test_tracked_lock_api_matches_threading(self):
        state = LockdepState()
        lk = TrackedLock(state, "A")
        assert lk.acquire() is True
        assert lk.locked()
        lk.release()
        assert not lk.locked()
        assert lk.acquire(blocking=False) is True
        lk.release()


class TestPipelineLockRegistration:
    """The event-driven pipelined loop's new locks must be REGISTERED with
    the runtime detector (created inside tracked modules → TrackedLock),
    and its documented order — cache big lock → trigger condition guard,
    with the ingest-staging buffer and dispatch-futures mutex as leaves —
    must hold; the reverse nesting is exactly what lockdep would report."""

    def test_pipeline_locks_are_tracked(self):
        import os

        if os.environ.get("KBT_LOCKDEP", "1").lower() in ("0", "false", "no"):
            return
        from kube_batch_tpu.cache.cache import SchedulerCache
        from kube_batch_tpu.scheduler import CycleTrigger

        cache = SchedulerCache()
        trig = CycleTrigger()
        # cache/cache.py and scheduler.py are tracked module prefixes: the
        # staging buffer lock, the dispatch-futures mutex, and the trigger's
        # explicitly created condition guard all instrument
        assert isinstance(cache._ingest_lock, TrackedLock)
        assert isinstance(cache._dispatch_mu, TrackedLock)
        assert isinstance(trig._cond._lock, TrackedLock)

    def test_big_lock_to_trigger_order_is_clean(self):
        """Model the real order on a private state: notify() fires under
        the big lock (the dirty-advance hook), wait_for_work holds only the
        condition guard.  Consistent → no violations."""
        state = LockdepState()
        big = TrackedLock(state, "cache.cache:big", reentrant=True)
        cond = TrackedLock(state, "scheduler:trigger-cond")
        staging = TrackedLock(state, "cache.cache:ingest-staging")
        # ingest thread: staging alone, then the wake outside it
        with staging:
            pass
        with cond:
            pass
        # dirty-advance wake: big → cond
        with big:
            with cond:
                pass
        # cycle thread: big alone (drain), cond alone (wait)
        with big:
            pass
        with cond:
            pass
        assert state.violations == []

    def test_reverse_nesting_would_be_flagged(self):
        state = LockdepState()
        big = TrackedLock(state, "cache.cache:big", reentrant=True)
        cond = TrackedLock(state, "scheduler:trigger-cond")
        with big:
            with cond:
                pass
        # a trigger callback that re-entered the cache would invert it
        with cond:
            with big:
                pass
        assert [v.kind for v in state.violations] == ["order-inversion"]


class TestLockProfile:
    """Lock-hold / contention profiling on TrackedLock (the ROADMAP's
    'striped per-kind ingest locks (profile first)' item): acquire-wait
    and hold times accumulate per lock class, merged across threads."""

    def test_hold_time_recorded(self):
        import time as _t

        state = LockdepState()
        (lk,) = _locks(state, "mod.cache:1")
        with lk:
            _t.sleep(0.01)
        rec = state.profile_report()["mod.cache:1"]
        assert rec["acquires"] == 1
        assert rec["hold_ms_total"] >= 8.0
        assert rec["hold_ms_max"] >= 8.0
        assert rec["wait_ms_total"] < 8.0, "uncontended acquire ~free"

    def test_contended_acquire_records_wait(self):
        import time as _t

        state = LockdepState()
        (lk,) = _locks(state, "mod.cache:2")
        entered = threading.Event()

        def holder():
            with lk:
                entered.set()
                _t.sleep(0.05)

        t = threading.Thread(target=holder)
        t.start()
        entered.wait(timeout=5)
        with lk:          # blocks until the holder releases
            pass
        t.join(timeout=5)
        rec = state.profile_report()["mod.cache:2"]
        assert rec["acquires"] == 2
        assert rec["wait_ms_max"] >= 20.0, (
            "the contended acquire's wait must be attributed"
        )

    def test_reentrant_acquires_count_once_for_hold(self):
        state = LockdepState()
        lk = TrackedLock(state, "mod.cache:3", reentrant=True)
        with lk:
            with lk:
                pass
        rec = state.profile_report()["mod.cache:3"]
        assert rec["acquires"] == 2      # each acquire's wait is recorded
        assert rec["hold_ms_total"] >= 0.0

    def test_suite_installed_state_profiles_cache_locks(self):
        """The pytest-plugin-installed lockdep (the whole-suite watcher)
        carries the profile too — the cache's big lock shows up after any
        ingest."""
        import pytest as _pytest

        from kube_batch_tpu.api.pod import Queue
        from kube_batch_tpu.cache.cache import SchedulerCache

        state = lockdep.current_state()
        if state is None:
            _pytest.skip("lockdep disabled (KBT_LOCKDEP=0)")
        cache = SchedulerCache()
        cache.add_queue(Queue(name="lp", uid="ulp", weight=1))
        prof = state.profile_report()
        assert any("kube_batch_tpu.cache.cache" in site for site in prof), (
            "the cache big lock's class must appear in the merged profile"
        )
