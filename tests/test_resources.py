"""Table-driven tests of Resource arithmetic — the rebuild's analog of
pkg/scheduler/api/resource_info_test.go (epsilon semantics, add/sub/setmax,
fit comparisons)."""

import numpy as np
import pytest

from kube_batch_tpu.api.resources import (
    DEFAULT_SPEC,
    GPU,
    MIN_MEMORY,
    MIN_MILLI_CPU,
    Resource,
    ResourceSpec,
)
from kube_batch_tpu.utils.assertions import InvariantError


def R(cpu=0.0, mem=0.0, pods=0.0, gpu=None):
    return DEFAULT_SPEC.build(
        cpu_milli=cpu, memory=mem, pods=pods, scalars={GPU: gpu} if gpu is not None else None
    )


class TestIsEmpty:
    def test_zero_is_empty(self):
        assert R().is_empty()

    def test_below_quantum_is_empty(self):
        assert R(cpu=MIN_MILLI_CPU - 1, mem=MIN_MEMORY - 1).is_empty()

    def test_at_quantum_not_empty(self):
        assert not R(cpu=MIN_MILLI_CPU).is_empty()

    def test_is_zero_per_dim(self):
        r = R(cpu=100)
        assert not r.is_zero("cpu")
        assert r.is_zero("memory")


class TestArithmetic:
    def test_add(self):
        assert R(cpu=100, mem=10).add(R(cpu=50, mem=5)) == R(cpu=150, mem=15)

    def test_sub(self):
        assert R(cpu=100, mem=10).sub(R(cpu=40, mem=10)) == R(cpu=60, mem=0)

    def test_sub_underflow_asserts(self):
        with pytest.raises(InvariantError):
            R(cpu=100).sub(R(cpu=200))

    def test_sub_tolerates_subquantum_excess(self):
        # LessEqual tolerance (resource_info.go:269-284): excess below the
        # quantum doesn't count as underflow, and the result clamps at 0.
        out = R(cpu=100).sub(R(cpu=100 + MIN_MILLI_CPU / 2))
        assert out.milli_cpu == 0.0

    def test_multi(self):
        assert R(cpu=100, mem=10).multi(1.2) == R(cpu=120, mem=12)

    def test_set_max(self):
        r = R(cpu=100, mem=5)
        r.set_max_(R(cpu=50, mem=10))
        assert r == R(cpu=100, mem=10)

    def test_min(self):
        assert R(cpu=100, mem=5).min(R(cpu=50, mem=10)) == R(cpu=50, mem=5)

    def test_diff(self):
        inc, dec = R(cpu=100, mem=5).diff(R(cpu=40, mem=8))
        assert inc == R(cpu=60)
        assert dec == R(mem=3)


class TestComparisons:
    def test_less(self):
        assert R(cpu=1, mem=1).less(R(cpu=2, mem=2))
        assert not R(cpu=1, mem=3).less(R(cpu=2, mem=2))

    def test_less_equal_tolerant(self):
        assert R(cpu=100).less_equal(R(cpu=100))
        assert R(cpu=100 + MIN_MILLI_CPU - 1).less_equal(R(cpu=100))
        assert not R(cpu=100 + MIN_MILLI_CPU).less_equal(R(cpu=100))

    def test_fit_delta(self):
        short = R(cpu=100, mem=0).fit_delta(R(cpu=40, mem=50))
        assert short.milli_cpu == 100 - 40 + MIN_MILLI_CPU
        assert short.memory == 0  # nothing requested → no shortfall

    def test_share(self):
        total = R(cpu=1000, mem=1000)
        assert R(cpu=500, mem=250).share(total) == pytest.approx(0.5)
        assert R().share(total) == 0.0


class TestSpec:
    def test_unknown_scalar_rejected(self):
        with pytest.raises(KeyError):
            DEFAULT_SPEC.build(scalars={"example.com/fpga": 1})

    def test_custom_spec(self):
        spec = ResourceSpec(scalar_names=("nvidia.com/gpu", "cloud.com/npu"))
        r = spec.build(scalars={"cloud.com/npu": 4000})
        assert r.get("cloud.com/npu") == 4000

    def test_spec_mismatch_asserts(self):
        other = ResourceSpec(scalar_names=())
        with pytest.raises(InvariantError):
            R(cpu=1).add(other.build(cpu_milli=1))
