"""Pallas round-head kernel parity vs the XLA path (interpret mode on CPU)."""

from __future__ import annotations

import numpy as np
import pytest

from kube_batch_tpu.ops.assignment import AllocateConfig, allocate_solve
from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot


def test_masked_best_node_matches_xla():
    import jax.numpy as jnp

    from kube_batch_tpu.ops.assignment import NEG, _best_node, _tie_break_hash
    from kube_batch_tpu.ops.feasibility import fits, static_predicates
    from kube_batch_tpu.ops.pallas_kernels import masked_best_node
    from kube_batch_tpu.ops.scoring import ScoreWeights, score_matrix

    snap, meta = synthetic_device_snapshot(
        n_tasks=256, n_nodes=64, gang_size=4, n_queues=2, gpu_task_frac=0.3
    )
    score = score_matrix(snap, ScoreWeights())
    static_ok = static_predicates(snap)
    pending = jnp.asarray(snap.task_pending)

    best_k, has_k, chose_idle_k = masked_best_node(
        score, static_ok, snap.task_req, snap.node_idle, snap.node_releasing,
        pending, snap.quanta, interpret=True,
    )

    fit_idle = fits(snap.task_req, snap.node_idle, snap.quanta)
    fit_rel = fits(snap.task_req, snap.node_releasing, snap.quanta)
    feas = static_ok & (fit_idle | fit_rel) & pending[:, None]
    masked = jnp.where(feas, score, NEG)
    T, N = masked.shape
    best_x, has_x = _best_node(masked, _tie_break_hash(T, N))
    chose_idle_x = jnp.take_along_axis(fit_idle, best_x[:, None], axis=1)[:, 0]

    np.testing.assert_array_equal(np.asarray(has_k), np.asarray(has_x))
    np.testing.assert_array_equal(
        np.asarray(best_k)[np.asarray(has_x)], np.asarray(best_x)[np.asarray(has_x)]
    )
    np.testing.assert_array_equal(
        np.asarray(chose_idle_k)[np.asarray(has_x)],
        np.asarray(chose_idle_x)[np.asarray(has_x)],
    )


@pytest.mark.parametrize("gpu_frac", [0.0, 0.25])
def test_full_solve_parity(gpu_frac):
    """The whole allocate solve must produce identical placements with the
    pallas round head enabled."""
    snap, meta = synthetic_device_snapshot(
        n_tasks=512, n_nodes=64, gang_size=4, n_queues=3, gpu_task_frac=gpu_frac
    )
    r_xla = allocate_solve(snap, AllocateConfig())
    r_pls = allocate_solve(snap, AllocateConfig(use_pallas=True))
    np.testing.assert_array_equal(np.asarray(r_xla.assigned), np.asarray(r_pls.assigned))
    np.testing.assert_array_equal(np.asarray(r_xla.pipelined), np.asarray(r_pls.pipelined))


def test_raw_kernel_block_offsets_match_global_slice():
    """The (t0, n0) offsets make a block invocation agree with the global
    matrix: running the kernel on a [T_blk, N_blk] sub-block with its
    global origin must reproduce the winner value/hash/pick of the XLA
    two-key argmax over that exact slice of the FULL tie-hash matrix —
    the contract the shard_map round head relies on."""
    import jax.numpy as jnp

    from kube_batch_tpu.ops.assignment import NEG, _tie_break_hash
    from kube_batch_tpu.ops.feasibility import fits, static_predicates
    from kube_batch_tpu.ops.pallas_kernels import masked_best_node_raw
    from kube_batch_tpu.ops.scoring import ScoreWeights, score_matrix

    snap, _meta = synthetic_device_snapshot(
        n_tasks=512, n_nodes=128, gang_size=4, n_queues=2, gpu_task_frac=0.2
    )
    score = score_matrix(snap, ScoreWeights())
    static_ok = static_predicates(snap)
    pending = jnp.asarray(snap.task_pending)
    T, N = score.shape
    t0, n0, T_blk, N_blk = 256, 64, 256, 64

    best_k, val_k, hash_k, chose_k = masked_best_node_raw(
        score[t0:t0 + T_blk, n0:n0 + N_blk],
        static_ok[t0:t0 + T_blk, n0:n0 + N_blk],
        snap.task_req[t0:t0 + T_blk],
        snap.node_idle[n0:n0 + N_blk],
        snap.node_releasing[n0:n0 + N_blk],
        pending[t0:t0 + T_blk],
        snap.quanta, t0=t0, n0=n0, interpret=True,
    )

    # XLA reference over the same block with the GLOBAL tie-hash slice
    fit_idle = fits(snap.task_req[t0:t0 + T_blk],
                    snap.node_idle[n0:n0 + N_blk], snap.quanta)
    fit_rel = fits(snap.task_req[t0:t0 + T_blk],
                   snap.node_releasing[n0:n0 + N_blk], snap.quanta)
    feas = (
        static_ok[t0:t0 + T_blk, n0:n0 + N_blk]
        & (fit_idle | fit_rel) & pending[t0:t0 + T_blk, None]
    )
    masked = jnp.where(feas, score[t0:t0 + T_blk, n0:n0 + N_blk], NEG)
    tie = _tie_break_hash(T, N)[t0:t0 + T_blk, n0:n0 + N_blk]
    lval = jnp.max(masked, axis=1)
    cand = jnp.where(masked >= lval[:, None], tie, -1)
    pick = jnp.argmax(cand, axis=1).astype(jnp.int32)
    lkey = jnp.max(cand, axis=1)

    has = np.asarray(lval > NEG)
    np.testing.assert_array_equal(np.asarray(val_k)[has], np.asarray(lval)[has])
    np.testing.assert_array_equal(
        np.asarray(hash_k)[has], np.asarray(lkey).astype(np.float32)[has]
    )
    np.testing.assert_array_equal(np.asarray(best_k)[has], np.asarray(pick)[has])


def test_compiled_vs_interpret_agree_on_tpu():
    """The ROADMAP straggler: on a real TPU backend the kernel compiles
    for real (interpret=False) and must agree with interpret mode; other
    backends keep interpret=True as the fallback and skip here."""
    import jax

    if jax.default_backend() != "tpu":
        pytest.skip("compiled Pallas path requires the TPU backend")
    import jax.numpy as jnp

    from kube_batch_tpu.ops.pallas_kernels import masked_best_node
    from kube_batch_tpu.ops.feasibility import static_predicates
    from kube_batch_tpu.ops.scoring import ScoreWeights, score_matrix

    snap, _meta = synthetic_device_snapshot(
        n_tasks=512, n_nodes=512, gang_size=4, n_queues=2, gpu_task_frac=0.2
    )
    score = score_matrix(snap, ScoreWeights())
    static_ok = static_predicates(snap)
    pending = jnp.asarray(snap.task_pending)
    args = (score, static_ok, snap.task_req, snap.node_idle,
            snap.node_releasing, pending, snap.quanta)
    compiled = masked_best_node(*args, interpret=False)
    interp = masked_best_node(*args, interpret=True)
    for c, i in zip(compiled, interp):
        np.testing.assert_array_equal(np.asarray(c), np.asarray(i))
