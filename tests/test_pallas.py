"""Pallas round-head kernel parity vs the XLA path (interpret mode on CPU)."""

from __future__ import annotations

import numpy as np
import pytest

from kube_batch_tpu.ops.assignment import AllocateConfig, allocate_solve
from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot


def test_masked_best_node_matches_xla():
    import jax.numpy as jnp

    from kube_batch_tpu.ops.assignment import NEG, _best_node, _tie_break_hash
    from kube_batch_tpu.ops.feasibility import fits, static_predicates
    from kube_batch_tpu.ops.pallas_kernels import masked_best_node
    from kube_batch_tpu.ops.scoring import ScoreWeights, score_matrix

    snap, meta = synthetic_device_snapshot(
        n_tasks=256, n_nodes=64, gang_size=4, n_queues=2, gpu_task_frac=0.3
    )
    score = score_matrix(snap, ScoreWeights())
    static_ok = static_predicates(snap)
    pending = jnp.asarray(snap.task_pending)

    best_k, has_k, chose_idle_k = masked_best_node(
        score, static_ok, snap.task_req, snap.node_idle, snap.node_releasing,
        pending, snap.quanta, interpret=True,
    )

    fit_idle = fits(snap.task_req, snap.node_idle, snap.quanta)
    fit_rel = fits(snap.task_req, snap.node_releasing, snap.quanta)
    feas = static_ok & (fit_idle | fit_rel) & pending[:, None]
    masked = jnp.where(feas, score, NEG)
    T, N = masked.shape
    best_x, has_x = _best_node(masked, _tie_break_hash(T, N))
    chose_idle_x = jnp.take_along_axis(fit_idle, best_x[:, None], axis=1)[:, 0]

    np.testing.assert_array_equal(np.asarray(has_k), np.asarray(has_x))
    np.testing.assert_array_equal(
        np.asarray(best_k)[np.asarray(has_x)], np.asarray(best_x)[np.asarray(has_x)]
    )
    np.testing.assert_array_equal(
        np.asarray(chose_idle_k)[np.asarray(has_x)],
        np.asarray(chose_idle_x)[np.asarray(has_x)],
    )


@pytest.mark.parametrize("gpu_frac", [0.0, 0.25])
def test_full_solve_parity(gpu_frac):
    """The whole allocate solve must produce identical placements with the
    pallas round head enabled."""
    snap, meta = synthetic_device_snapshot(
        n_tasks=512, n_nodes=64, gang_size=4, n_queues=3, gpu_task_frac=gpu_frac
    )
    r_xla = allocate_solve(snap, AllocateConfig())
    r_pls = allocate_solve(snap, AllocateConfig(use_pallas=True))
    np.testing.assert_array_equal(np.asarray(r_xla.assigned), np.asarray(r_pls.assigned))
    np.testing.assert_array_equal(np.asarray(r_xla.pipelined), np.asarray(r_pls.pipelined))
