"""Smoke tests for the benchmark matrix (testing/benchmark.py) at tiny sizes."""

from __future__ import annotations

from kube_batch_tpu.testing.benchmark import _device_case, _overcommit_case, _percentiles


def test_percentiles():
    p = _percentiles([1.0, 2.0, 3.0, 4.0])
    assert p["p50_ms"] == 2.5 and p["p99_ms"] <= 4.0


def test_device_case_tiny():
    r = _device_case("tiny", 64, 16).run(1)
    assert r["placed"] > 0
    assert r["p50_ms"] > 0


def test_overcommit_case_tiny():
    r = _overcommit_case("tiny", n_running=40, n_pending=16, n_nodes=8).run(1)
    # q1's pending gangs must trigger cross-queue reclaim of q0's running pods
    assert r["evicted"] > 0
    assert r["p50_ms"] > 0


def test_startup_latency_case_tiny():
    from kube_batch_tpu.testing.benchmark import _startup_latency_case
    r = _startup_latency_case("tiny", n_latency_pods=30, n_nodes=4, batch=10,
                              gang_size=4, period=0.02).run(1)
    assert r["scheduled"] == r["pods"] == 34
    assert r["p50_ms"] > 0
