"""Runs the live-apiserver e2e driver (kube_batch_tpu/testing/e2e.py) in
--stub mode: the REAL CLI scheduler process in --master mode against a real
HTTP apiserver (the kubelet-simulating stub), executing the reference's
core scenarios (test/e2e/job.go:82,118,189; queue.go:26,458; predicates.go:35,84,161).

Against an actual cluster:  python -m kube_batch_tpu.testing.e2e --master URL
"""

import os
import subprocess
import sys

import pytest


def _run_e2e(*args, timeout=650):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    return subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.testing.e2e", "--stub", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=repo,
    )


@pytest.mark.slow
def test_e2e_scenarios_against_stub_apiserver():
    r = _run_e2e()
    assert r.returncode == 0, f"e2e driver failed:\n{r.stdout[-6000:]}\n{r.stderr[-2000:]}"
    assert "9/9 scenarios passed" in r.stdout, r.stdout[-3000:]


@pytest.mark.slow
def test_density_benchmark_against_stub():
    """The kubemark density benchmark (reduced) through the live protocol:
    a 100-pod gang (the driver's min(100, pods)) + 150 latency pods on 30
    hollow nodes, all scheduled.  Subprocess timeout exceeds run_density's
    own 600s wait so a stall still surfaces the scheduler diagnostics."""
    r = _run_e2e("--density", "--density-pods", "150", "--density-nodes", "30",
                 timeout=800)
    assert r.returncode == 0, f"{r.stdout[-4000:]}\n{r.stderr[-2000:]}"
    import json as _json

    out = _json.loads(r.stdout.strip().splitlines()[-1])
    assert out["pods"] == 150 and out["startup_p99_ms"] > 0
