"""Runs the live-apiserver e2e driver (kube_batch_tpu/testing/e2e.py) in
--stub mode: the REAL CLI scheduler process in --master mode against a real
HTTP apiserver (the kubelet-simulating stub), executing the reference's
core scenarios (test/e2e/job.go:82,118,189; queue.go:26,458; predicates.go:35,84,161).

Against an actual cluster:  python -m kube_batch_tpu.testing.e2e --master URL
"""

import os
import subprocess
import sys

import pytest


def _run_e2e(*args, timeout=650):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    return subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.testing.e2e", "--stub", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=repo,
    )


@pytest.mark.slow
def test_e2e_scenarios_against_stub_apiserver():
    r = _run_e2e()
    assert r.returncode == 0, f"e2e driver failed:\n{r.stdout[-6000:]}\n{r.stderr[-2000:]}"
    assert "10/10 scenarios passed" in r.stdout, r.stdout[-3000:]


@pytest.mark.slow
def test_density_benchmark_against_stub():
    """The kubemark density benchmark (reduced) through the live protocol:
    a 100-pod gang (the driver's min(100, pods)) + 150 latency pods on 30
    hollow nodes, all scheduled.  Subprocess timeout exceeds run_density's
    own 600s wait so a stall still surfaces the scheduler diagnostics."""
    r = _run_e2e("--density", "--density-pods", "150", "--density-nodes", "30",
                 timeout=800)
    assert r.returncode == 0, f"{r.stdout[-4000:]}\n{r.stderr[-2000:]}"
    import json as _json

    out = _json.loads(r.stdout.strip().splitlines()[-1])
    assert out["pods"] == 150 and out["startup_p99_ms"] > 0


@pytest.mark.slow
def test_ha_failover_against_stub_apiserver():
    """Active/passive HA through the FULL stack (server.go:106-151): two
    real CLI scheduler processes contend for the coordination.k8s.io Lease
    on the stub apiserver; the leader schedules, the standby does not.
    Killing the leader lets the standby take over after lease expiry
    (15s/10s/5s reference timings) and schedule new work."""
    import time

    from kube_batch_tpu.testing.e2e import Cluster, StubApiServer, scheduler_process

    stub = StubApiServer()
    master = stub.start()
    try:
        c = Cluster(master)
        c.apply_crds()
        c.ensure_namespace("ha")
        c.queue("ha-q", 1)
        from kube_batch_tpu.testing.e2e import _COLLECTIONS

        c.create(_COLLECTIONS["nodes"], c.node_obj("ha-n1"))
        ha_args = ("--leader-elect", "--lock-object-namespace", "kube-system")
        with scheduler_process(master, extra_args=ha_args) as a, \
                scheduler_process(master, extra_args=ha_args) as b:
            c.podgroup("ha", "j1", 1, "ha-q")
            c.pod("ha", "p1", "j1")
            c.wait(lambda: c.n_on_nodes("ha", "p1") == 1, timeout=90,
                   what="leader schedules")
            lease = stub._store["leases"].get("kube-system/kube-batch-tpu")
            assert lease, "no Lease taken"
            holder1 = lease["spec"]["holderIdentity"]
            assert holder1
            # kill whichever process leads (we can't tell which Popen won —
            # kill A; if A was the standby, the leader keeps scheduling and
            # the test still must see p2 bound, so kill BOTH candidates'
            # ambiguity by checking progress either way)
            a.kill()
            a.wait(timeout=10)
            time.sleep(1.0)
            c.podgroup("ha", "j2", 1, "ha-q")
            c.pod("ha", "p2", "j2")
            # if A led: B takes over after <= lease_duration (15s) + retries.
            # if B led: scheduling continues immediately. Either way p2 binds.
            c.wait(lambda: c.n_on_nodes("ha", "p2") == 1, timeout=60,
                   what="standby takeover schedules")
    finally:
        stub.stop()
