"""Runs the live-apiserver e2e driver (kube_batch_tpu/testing/e2e.py) in
--stub mode: the REAL CLI scheduler process in --master mode against a real
HTTP apiserver (the kubelet-simulating stub), executing the reference's
core scenarios (test/e2e/job.go:82,118,189; queue.go:26,458; predicates.go:35,84,161).

Against an actual cluster:  python -m kube_batch_tpu.testing.e2e --master URL
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_e2e_scenarios_against_stub_apiserver():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    r = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu.testing.e2e", "--stub"],
        capture_output=True, text=True, timeout=560, env=env, cwd=repo,
    )
    assert r.returncode == 0, f"e2e driver failed:\n{r.stdout[-6000:]}\n{r.stderr[-2000:]}"
    assert "9/9 scenarios passed" in r.stdout, r.stdout[-3000:]
