"""Property tests for the fairness tensors (ops/fairness.py) and their host
twins — the cost-tensor rows SURVEY.md §4.1 calls out as trivially
property-testable (the reference ships zero plugin unit tests)."""

from __future__ import annotations

import numpy as np
import pytest

from kube_batch_tpu.ops import fairness


def _random_case(rng, Q=5, R=4):
    total = rng.uniform(100, 10_000, R).astype(np.float32)
    weight = rng.integers(1, 8, Q).astype(np.float32)
    request = (total[None, :] * rng.uniform(0, 0.8, (Q, R))).astype(np.float32)
    valid = np.ones(Q, bool)
    return total, weight, request, valid


class TestProportionDeserved:
    @pytest.mark.parametrize("seed", range(8))
    def test_invariants(self, seed):
        rng = np.random.default_rng(seed)
        total, weight, request, valid = _random_case(rng)
        d = np.asarray(
            fairness.proportion_deserved(total, weight, request, valid)
        )
        # 1. never hand out more than the cluster has (per dim)
        assert np.all(d.sum(axis=0) <= total * (1 + 1e-5) + 1e-3)
        # 2. a met queue is capped at its request
        met = np.all(request <= d + 1e-3, axis=-1)
        assert np.all(d[met] <= request[met] + 1e-3)
        # 3. non-negative
        assert np.all(d >= 0)

    def test_weighted_split_when_scarce(self):
        """Two queues wanting everything split the cluster by weight."""
        total = np.array([1000.0, 1000.0, 10.0, 0.0], np.float32)
        weight = np.array([1.0, 3.0], np.float32)
        request = np.tile(total, (2, 1)).astype(np.float32)
        valid = np.ones(2, bool)
        d = np.asarray(fairness.proportion_deserved(total, weight, request, valid))
        np.testing.assert_allclose(d[0, 0], 250.0, rtol=1e-3)
        np.testing.assert_allclose(d[1, 0], 750.0, rtol=1e-3)

    def test_excess_redistributed(self):
        """A small queue's unused share flows to the hungry queue
        (proportion.go:101-154's cap-and-return loop)."""
        total = np.array([1000.0, 1000.0, 10.0, 0.0], np.float32)
        weight = np.array([1.0, 1.0], np.float32)
        request = np.array(
            [[100.0, 100.0, 1.0, 0.0], [1000.0, 1000.0, 9.0, 0.0]], np.float32
        )
        d = np.asarray(fairness.proportion_deserved(total, weight, request, valid=np.ones(2, bool)))
        np.testing.assert_allclose(d[0, 0], 100.0, rtol=1e-3)   # capped
        assert d[1, 0] >= 900.0 * (1 - 1e-3)                     # got the rest

    def test_many_queues_one_cap_per_iteration(self):
        """Adversarial Q=64 case where every iteration retires exactly ONE
        queue — the true worst case needing Q iterations (the reference loops
        to convergence, proportion.go:101-154; a fixed 16-iteration bound
        under-serves queues 17..64)."""
        Q, R = 64, 4
        total0 = 1_000_000.0
        total = np.array([total0, 0.0, 0.0, 0.0], np.float32)
        weight = np.ones(Q, np.float32)
        # request_i = 99% of the equal-share grant at iteration i, so queue i
        # is the only one capped in round i
        request = np.zeros((Q, R), np.float32)
        remaining = total0
        for i in range(Q - 1):
            grant = remaining / (Q - i)
            request[i, 0] = 0.99 * grant
            remaining -= request[i, 0]
        request[Q - 1, 0] = 2 * total0  # never met; absorbs the rest
        d = np.asarray(fairness.proportion_deserved(
            total, weight, request, np.ones(Q, bool)))
        # every capped queue got exactly its request…
        np.testing.assert_allclose(d[: Q - 1, 0], request[: Q - 1, 0], rtol=1e-4)
        # …and the hungry queue got everything left (pool fully drained)
        np.testing.assert_allclose(d[:, 0].sum(), total0, rtol=1e-4)

    @pytest.mark.parametrize("seed", range(4))
    def test_q64_skewed_weights_match_host_oracle(self, seed):
        """Q=64, weights skewed over 3 decades: device waterfill must agree
        with an independent run-to-convergence numpy oracle."""
        Q, R = 64, 4
        rng = np.random.default_rng(seed)
        total = rng.uniform(1e4, 1e6, R).astype(np.float32)
        weight = (10.0 ** rng.uniform(0, 3, Q)).astype(np.float32)
        request = (total[None, :] * rng.uniform(0, 0.2, (Q, R))).astype(np.float32)
        valid = np.ones(Q, bool)

        # oracle: plain python waterfill to fixpoint
        deserved = np.zeros((Q, R), np.float64)
        met = np.zeros(Q, bool)
        remaining = total.astype(np.float64).copy()
        for _ in range(Q + 1):
            if not np.any(remaining > 1e-6) or np.all(met):
                break
            w = np.where(~met, weight, 0.0)
            frac = w / w.sum() if w.sum() > 0 else w
            new = deserved + remaining[None, :] * frac[:, None]
            now_met = np.all(request <= new + 1e-6, axis=-1)
            capped = np.where(now_met[:, None], np.minimum(new, request), new)
            remaining = np.maximum(remaining - (capped - deserved).sum(axis=0), 0.0)
            deserved, met = capped, met | now_met
        dev = np.asarray(fairness.proportion_deserved(total, weight, request, valid))
        np.testing.assert_allclose(dev, deserved, rtol=2e-3, atol=1.0)

    @pytest.mark.parametrize("seed", range(4))
    def test_host_twin_agrees(self, seed):
        """plugins/proportion's numpy waterfill must match the device one."""
        import kube_batch_tpu.plugins  # register builders
        from kube_batch_tpu.api.pod import Node, PodGroup, Queue
        from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod
        from kube_batch_tpu.api.types import PodPhase
        from kube_batch_tpu.cache.cache import SchedulerCache
        from kube_batch_tpu.framework.conf import load_scheduler_conf
        from kube_batch_tpu.framework.session import open_session

        rng = np.random.default_rng(seed)
        cache = SchedulerCache()
        weights = [int(rng.integers(1, 5)) for _ in range(3)]
        for q, w in enumerate(weights):
            cache.add_queue(Queue(name=f"q{q}", weight=w))
        for i in range(4):
            cache.add_node(Node(name=f"n{i}", allocatable={
                "cpu": 8000.0, "memory": float(16 << 30), "pods": 110.0}))
        for j in range(12):
            cache.add_pod_group(PodGroup(name=f"pg{j}", namespace="t",
                                         min_member=1, queue=f"q{j % 3}"))
            cache.add_pod(Pod(
                name=f"p{j}", namespace="t",
                requests={"cpu": float(rng.choice([500, 1000, 2000])),
                          "memory": float(rng.choice([1, 2, 4])) * (1 << 30)},
                annotations={GROUP_NAME_ANNOTATION: f"pg{j}"},
                phase=PodPhase.PENDING,
            ))
        ssn = open_session(cache, load_scheduler_conf(None).tiers)
        host = {
            qn: attr.deserved.vec.astype(np.float32)
            for p in ssn.plugins if p.name == "proportion"
            for qn, attr in p.queue_attrs.items()
        }
        from kube_batch_tpu.actions.reclaim import _cluster_view
        from kube_batch_tpu.api.snapshot import build_snapshot

        snap, meta = build_snapshot(_cluster_view(ssn))
        dev = np.asarray(fairness.proportion_deserved(
            snap.total, snap.queue_weight, snap.queue_request, snap.queue_valid
        ))
        for qi, qn in enumerate(meta.queue_names):
            np.testing.assert_allclose(dev[qi], host[qn], rtol=2e-3, atol=1.0)


class TestShares:
    def test_dominant_share(self):
        alloc = np.array([[500.0, 0.0, 3.0, 0.0], [0.0, 800.0, 1.0, 0.0]], np.float32)
        total = np.array([1000.0, 1000.0, 10.0, 0.0], np.float32)
        s = np.asarray(fairness.dominant_share(alloc, total))
        np.testing.assert_allclose(s, [0.5, 0.8], rtol=1e-5)

    def test_queue_share_prefers_underserved(self):
        deserved = np.array([[1000.0, 1000.0, 5.0, 0.0]] * 2, np.float32)
        alloc = np.array(
            [[100.0, 0.0, 1.0, 0.0], [900.0, 0.0, 1.0, 0.0]], np.float32
        )
        s = np.asarray(fairness.queue_share(alloc, deserved))
        assert s[0] < s[1]

    def test_overused(self):
        deserved = np.array([[100.0, 100.0, 1.0, 0.0]], np.float32)
        quanta = np.array([10.0, 10 << 20, 0.1, 10.0], np.float32)
        assert bool(np.asarray(fairness.overused(
            deserved, np.array([[200.0, 200.0, 2.0, 0.0]], np.float32), quanta))[0])
        assert not bool(np.asarray(fairness.overused(
            deserved, np.array([[50.0, 200.0, 2.0, 0.0]], np.float32), quanta))[0])
