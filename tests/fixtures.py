"""Shared test fixtures — the rebuild's pkg/scheduler/util/test_utils.go:
builders that feed synthetic objects through the real cache handlers, plus
fake-backend assembly."""

from __future__ import annotations

from typing import Dict, Optional

from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup, Queue
from kube_batch_tpu.api.resources import DEFAULT_SPEC
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.cache.fake import FakeBinder, FakeEvictor

GiB = 2**30
_counter = [0]


def build_resource_list(cpu_milli: float, memory: float, gpu: float = 0.0) -> Dict[str, float]:
    """BuildResourceList[WithGPU] (test_utils.go:34-52)."""
    r = {"cpu": cpu_milli, "memory": memory}
    if gpu:
        r["nvidia.com/gpu"] = gpu
    return r


def build_node(name: str, cpu: float = 8000, mem: float = 16 * GiB, pods: int = 110,
               labels=None, taints=None, **kw) -> Node:
    alloc = {"cpu": cpu, "memory": mem, "pods": pods}
    return Node(name=name, allocatable=alloc, labels=labels or {}, taints=taints or [], **kw)


def build_pod(
    namespace: str,
    name: str,
    node_name: Optional[str],
    phase: PodPhase,
    requests: Dict[str, float],
    group_name: Optional[str] = None,
    priority: int = 0,
    **kw,
) -> Pod:
    """BuildPod (test_utils.go:60-92): sets the group-name annotation."""
    _counter[0] += 1
    annotations = {}
    if group_name:
        annotations[GROUP_NAME_ANNOTATION] = group_name
    return Pod(
        name=name,
        namespace=namespace,
        requests=requests,
        node_name=node_name,
        phase=phase,
        annotations=annotations,
        priority=priority,
        creation_index=_counter[0],
        **kw,
    )


def build_cache(
    nodes=(),
    pods=(),
    pod_groups=(),
    queues=(),
) -> SchedulerCache:
    """The canonical fake-backend cache assembly (allocate_test.go:150-163):
    real SchedulerCache + Fake seams, objects fed through real handlers."""
    cache = SchedulerCache(
        spec=DEFAULT_SPEC,
        binder=FakeBinder(),
        evictor=FakeEvictor(),
    )
    for q in queues:
        cache.add_queue(q if isinstance(q, Queue) else Queue(name=q))
    for pg in pod_groups:
        cache.add_pod_group(pg)
    for n in nodes:
        cache.add_node(n)
    for p in pods:
        cache.add_pod(p)
    return cache
