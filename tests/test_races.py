"""kbt-check tier D (analysis/races.py): per-rule planted fixtures with a
true negative each, the suppression contract, the --domains report, CLI
routing/alias/exit-code parity, the tier-1 self-enforcement check that
keeps the package race-clean, and the runtime guarded-access corroborator
(including the planted unguarded access it must catch)."""

import json
import textwrap
import threading

import pytest

from kube_batch_tpu.analysis import check_source, run_paths
from kube_batch_tpu.analysis import lockdep
from kube_batch_tpu.analysis.races import (
    RACE_RULES, RACE_RULES_BY_ID, RULE_ALIASES, module_domains,
    domains_report, runtime_domain_specs,
)
from kube_batch_tpu.utils import blocking


def findings_for(src: str, relpath: str = "serve/x.py"):
    return check_source(textwrap.dedent(src), relpath, rules=RACE_RULES)


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# KBT301 — shared attribute accessed off its inferred lock domain
# ---------------------------------------------------------------------------


class TestKBT301:
    BAD = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()

        def _run(self):
            while True:
                self.count += 1

        def snapshot(self):
            with self._lock:
                return self.count
    """

    def test_lock_free_write_on_worker_root_triggers(self):
        findings = findings_for(self.BAD)
        assert rule_ids(findings) == ["KBT301"]
        assert "_lock" in findings[0].message

    def test_guarded_everywhere_is_clean(self):
        src = self.BAD.replace(
            "            while True:\n                self.count += 1",
            "            while True:\n                with self._lock:\n"
            "                    self.count += 1",
        )
        assert findings_for(src) == []

    def test_wrong_lock_is_still_a_finding(self):
        src = """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._other = threading.Lock()
                self.count = 0
                t = threading.Thread(target=self._run)
                t.start()

            def _run(self):
                with self._other:
                    self.count += 1

            def snapshot(self):
                with self._lock:
                    return self.count
        """
        findings = findings_for(src)
        assert rule_ids(findings) == ["KBT301"]
        assert "instead" in findings[0].message

    def test_init_writes_are_exempt(self):
        # construction happens-before every spawn — __init__ accesses are
        # never findings (the BAD fixture's __init__ writes don't report)
        findings = findings_for(self.BAD)
        assert all(f.line > 10 for f in findings)

    def test_single_root_class_is_clean(self):
        # no second thread root -> nothing is concurrent, even unguarded
        src = """
        import threading

        class Tally:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def _bump(self):
                self.count += 1

            def _read(self):
                with self._lock:
                    return self.count
        """
        assert findings_for(src) == []


# ---------------------------------------------------------------------------
# KBT302 — publish-then-mutate handoff (generalized StatusFlush contract)
# ---------------------------------------------------------------------------


class TestKBT302:
    def test_live_container_submitted_then_mutated_triggers(self):
        src = """
        import threading

        class Producer:
            def __init__(self, pool):
                self._lock = threading.Lock()
                self.buf = []
                self.pool = pool

            def flush(self):
                self.pool.submit(self._consume, self.buf)
                with self._lock:
                    self.buf.append(1)

            def _consume(self, items):
                return len(items)
        """
        findings = findings_for(src)
        assert rule_ids(findings) == ["KBT302"]

    def test_snapshot_handoff_under_lock_is_clean(self):
        src = """
        import threading

        class Producer:
            def __init__(self, pool):
                self._lock = threading.Lock()
                self.buf = []
                self.pool = pool

            def flush(self):
                with self._lock:
                    snap = list(self.buf)
                self.pool.submit(self._consume, snap)

            def _consume(self, items):
                return len(items)
        """
        assert findings_for(src) == []

    def test_thread_args_publication_triggers(self):
        src = """
        import threading

        class Producer:
            def __init__(self):
                self._lock = threading.Lock()
                self.buf = []

            def go(self):
                t = threading.Thread(target=consume, args=(self.buf,))
                t.start()
                with self._lock:
                    self.buf.append(1)
        """
        findings = findings_for(src)
        assert "KBT302" in rule_ids(findings)


class TestKBT302Legacy:
    """The writeback-stage contract KBT302 grew from (formerly KBT012):
    the overlapped stage may only touch the value-snapshotted StatusFlush
    handoff, never the live stores."""

    def test_writeback_reading_live_jobs_triggers(self):
        src = """
        class SchedulerCache:
            def run_status_flush(self, flush):
                for pg in flush.to_write:
                    self.status_updater.update_pod_group(pg)
                for uid in self.jobs:
                    pass
        """
        assert rule_ids(findings_for(src, "cache/cache.py")) == ["KBT302"]

    def test_worker_body_reading_cache_columns_triggers(self):
        src = """
        class Scheduler:
            def _writeback(self, flush):
                if flush:
                    self.cache.run_status_flush(flush)
                self.cache.columns.j_touched.fill(False)
        """
        assert rule_ids(findings_for(src, "scheduler.py")) == ["KBT302"]

    def test_snapshotted_handoff_is_clean(self):
        src = """
        class SchedulerCache:
            def run_status_flush(self, flush):
                updater = self.status_updater
                for pg in flush.to_write:
                    updater.update_pod_group(pg)
                for name, c in flush.qwrites:
                    updater.update_queue_status(name, c)
        """
        assert findings_for(src, "cache/cache.py") == []

    def test_out_of_scope_unflagged(self):
        src = """
        def run_status_flush(self, flush):
            return self.jobs
        """
        assert findings_for(src, "sim/runner.py") == []

    def test_legacy_allow_comment_still_suppresses(self):
        # migration contract: an allow written against the old id keeps
        # suppressing the rule it migrated into
        src = """
        class SchedulerCache:
            def run_status_flush(self, flush):
                # kbt: allow[KBT012] frozen at stage time, stage owns it
                for uid in self.jobs:
                    pass
        """
        assert findings_for(src, "cache/cache.py") == []


# ---------------------------------------------------------------------------
# KBT303 — check-then-act outside the guarding lock
# ---------------------------------------------------------------------------


class TestKBT303:
    def test_lock_free_check_then_act_triggers(self):
        src = """
        import threading

        class Broker:
            def __init__(self):
                self._lock = threading.Lock()
                self.pending = []
                t = threading.Thread(target=self._drain)
                t.start()

            def _drain(self):
                if self.pending:
                    self.pending.pop()

            def add(self, x):
                with self._lock:
                    self.pending.append(x)
        """
        findings = findings_for(src)
        assert rule_ids(findings) == ["KBT303"]
        assert "interleave" in findings[0].message

    def test_check_then_act_under_the_lock_is_clean(self):
        src = """
        import threading

        class Broker:
            def __init__(self):
                self._lock = threading.Lock()
                self.pending = []
                t = threading.Thread(target=self._drain)
                t.start()

            def _drain(self):
                with self._lock:
                    if self.pending:
                        self.pending.pop()

            def add(self, x):
                with self._lock:
                    self.pending.append(x)
        """
        assert findings_for(src) == []


# ---------------------------------------------------------------------------
# KBT304 — unguarded lazy init
# ---------------------------------------------------------------------------


class TestKBT304:
    def test_unguarded_lazy_init_triggers(self):
        src = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._table = None
                t = threading.Thread(target=self._refresh)
                t.start()

            def _refresh(self):
                if self._table is None:
                    self._table = dict()

            def get(self):
                with self._lock:
                    return self._table
        """
        findings = findings_for(src)
        assert rule_ids(findings) == ["KBT304"]
        assert "lazy init" in findings[0].message

    def test_double_checked_init_is_clean(self):
        # the sanctioned idiom: lock-free reference peek, re-verified
        # under the lock before the write
        src = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._table = None
                t = threading.Thread(target=self._refresh)
                t.start()

            def _refresh(self):
                if self._table is None:
                    with self._lock:
                        if self._table is None:
                            self._table = dict()

            def get(self):
                with self._lock:
                    return self._table
        """
        assert findings_for(src) == []

    def test_fully_guarded_lazy_init_is_clean(self):
        src = """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self._table = None
                t = threading.Thread(target=self._refresh)
                t.start()

            def _refresh(self):
                with self._lock:
                    if self._table is None:
                        self._table = dict()

            def get(self):
                with self._lock:
                    return self._table
        """
        assert findings_for(src) == []


# ---------------------------------------------------------------------------
# suppression contract
# ---------------------------------------------------------------------------


class TestSuppression:
    def test_allow_with_reason_suppresses(self):
        src = TestKBT301.BAD.replace(
            "self.count += 1",
            "self.count += 1  # kbt: allow[KBT301] stat counter, torn "
            "reads tolerated",
        )
        assert findings_for(src) == []

    def test_allow_without_reason_does_not_suppress(self):
        # the PR 2 contract: a reasonless allow[] is ignored AND reported
        src = TestKBT301.BAD.replace(
            "self.count += 1",
            "self.count += 1  # kbt: allow[KBT301]",
        )
        assert rule_ids(findings_for(src)) == ["KBT000", "KBT301"]

    def test_allow_for_a_different_rule_does_not_suppress(self):
        src = TestKBT301.BAD.replace(
            "self.count += 1",
            "self.count += 1  # kbt: allow[KBT304] wrong rule id",
        )
        assert "KBT301" in rule_ids(findings_for(src))

    def test_pytest_only_roots_are_excluded(self):
        # testing/ spawns threads for harnesses — tier D skips the tree
        assert findings_for(TestKBT301.BAD, "testing/harness.py") == []


# ---------------------------------------------------------------------------
# the --domains report (the reviewable inference)
# ---------------------------------------------------------------------------


class TestDomains:
    def test_module_domains_infer_the_dominating_lock(self):
        doms = module_domains(
            textwrap.dedent(TestKBT301.BAD), "serve/x.py")
        dom = next(d for d in doms if d.attr == "count")
        assert dom.cls == "Worker"
        assert dom.lock == "_lock"
        assert dom.written
        assert any(r.startswith("worker:") for r in dom.roots)

    def test_package_report_names_the_hot_structures(self):
        report = domains_report()
        assert "SchedulerCache" in report
        assert "_ingest_staged" in report
        assert "_ingest_lock" in report
        assert "LeaseBroker" in report

    def test_runtime_specs_resolve_against_the_static_map(self):
        specs = runtime_domain_specs([
            ("kube_batch_tpu.cache.cache", "SchedulerCache",
             "_ingest_staged"),
        ])
        assert specs == [("kube_batch_tpu.cache.cache", "SchedulerCache",
                          "_ingest_staged", "_ingest_lock")]

    def test_runtime_specs_raise_on_static_drift(self):
        with pytest.raises(LookupError):
            runtime_domain_specs([
                ("kube_batch_tpu.cache.cache", "SchedulerCache",
                 "no_such_attribute"),
            ])

    def test_plugin_hot_structure_table_has_not_drifted(self):
        # the corroborator's instrumentation table must stay resolvable
        # against the static inference (LookupError here = drift)
        from kube_batch_tpu.analysis.pytest_plugin import HOT_STRUCTURES

        specs = runtime_domain_specs(HOT_STRUCTURES)
        assert len(specs) == len(HOT_STRUCTURES)


# ---------------------------------------------------------------------------
# CLI: --races/--races-only, select routing, alias, exit codes, jsonl
# ---------------------------------------------------------------------------


class TestRacesCli:
    def _main(self, *args):
        from kube_batch_tpu.analysis import __main__ as cli

        return cli.main(list(args))

    @pytest.fixture()
    def bad_file(self, tmp_path):
        p = tmp_path / "racy.py"
        p.write_text(textwrap.dedent(TestKBT301.BAD))
        return str(p)

    def test_races_only_reports_and_exits_one(self, bad_file, capsys):
        assert self._main("--races-only", bad_file) == 1
        out = capsys.readouterr().out
        assert "KBT301" in out

    def test_races_only_clean_package_exits_zero(self, capsys):
        assert self._main("--races-only", "kube_batch_tpu/analysis") == 0
        assert "clean" in capsys.readouterr().out

    def test_select_race_id_implies_the_tier(self, bad_file, capsys):
        # a KBT30x selection routes to tier D without an explicit --races
        assert self._main("--select", "KBT301", bad_file) == 1
        out = capsys.readouterr().out
        assert "KBT301" in out

    def test_select_other_race_rule_filters(self, bad_file):
        assert self._main("--select", "KBT303", bad_file) == 0

    def test_kbt012_alias_selects_kbt302(self, tmp_path, capsys):
        p = tmp_path / "cache"
        p.mkdir()
        f = p / "cache.py"
        f.write_text(textwrap.dedent("""
        class SchedulerCache:
            def run_status_flush(self, flush):
                return self.jobs
        """))
        assert self._main("--select", "KBT012", str(f)) == 1
        out = capsys.readouterr().out
        assert "KBT302" in out

    def test_jsonl_parses_and_carries_the_rule(self, bad_file, capsys):
        assert self._main("--races-only", "--jsonl", bad_file) == 1
        rows = [json.loads(line)
                for line in capsys.readouterr().out.splitlines() if line]
        assert rows and all(r["rule"] == "KBT301" for r in rows)

    def test_unknown_rule_is_a_usage_error(self):
        assert self._main("--select", "KBT399") == 2

    def test_nonexistent_path_reports_not_clean(self, capsys):
        assert self._main("--races-only", "/nonexistent/z.py") == 1
        assert "KBT000" in capsys.readouterr().out

    def test_broken_module_reports_not_clean(self, tmp_path, capsys):
        p = tmp_path / "broken.py"
        p.write_text("def f(:\n")
        assert self._main("--races-only", str(p)) == 1
        assert "KBT000" in capsys.readouterr().out

    def test_domains_flag_prints_the_map(self, capsys):
        assert self._main("--domains") == 0
        out = capsys.readouterr().out
        assert "SchedulerCache" in out and "_ingest_lock" in out

    def test_list_rules_includes_tier_d_and_alias(self, capsys):
        assert self._main("--list-rules") == 0
        out = capsys.readouterr().out
        for rid in RACE_RULES_BY_ID:
            assert rid in out
        assert "KBT012" in out and "alias" in out

    def test_static_only_select_skips_the_race_tier(self, monkeypatch):
        # mirror of the tier-B/C contract: a KBT001-only selection must
        # not run tier D only to discard its findings
        import kube_batch_tpu.analysis.__main__ as cli

        calls = []
        real = cli.run_paths

        def spy(paths=None, rules=None):
            calls.append([r.id for r in (rules or [])])
            return real(paths, rules=rules)

        monkeypatch.setattr(cli, "run_paths", spy)
        assert self._main("--races", "--select", "KBT001",
                          "kube_batch_tpu/analysis") == 0
        assert all("KBT301" not in ids for ids in calls)


# ---------------------------------------------------------------------------
# tier-1 self-enforcement: the package is race-clean
# ---------------------------------------------------------------------------


class TestSelfEnforcement:
    def test_package_is_race_clean(self):
        findings = run_paths(rules=list(RACE_RULES))
        assert findings == [], "\n" + "\n".join(
            f.render() for f in findings)

    def test_alias_table_points_at_live_rules(self):
        for alias, target in RULE_ALIASES.items():
            assert target in RACE_RULES_BY_ID
            assert alias not in RACE_RULES_BY_ID

    def test_every_rule_has_title_and_grounding_doc(self):
        for rule in RACE_RULES:
            assert rule.title
            assert rule.__doc__ and len(rule.__doc__.strip()) > 40


# ---------------------------------------------------------------------------
# runtime corroborator (lockdep.install_guarded_access)
# ---------------------------------------------------------------------------


class _PlantedBox:
    """Corroborator fixture: a lock-owning class the tests instrument
    against a private LockdepState (never the session-global one)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._items = []


class TestGuardedAccessCorroborator:
    @pytest.fixture()
    def instrumented(self):
        state = lockdep.LockdepState()
        inst = lockdep.install_guarded_access(
            [(__name__, "_PlantedBox", "_items", "_lock")], state=state)
        try:
            yield state
        finally:
            inst.uninstall()

    @staticmethod
    def _share(box):
        # touch from a second thread (under the lock — itself clean) so
        # the instance counts as shared and enforcement arms
        def toucher():
            with box._lock:
                box._items.append("shared")

        t = threading.Thread(target=toucher)
        t.start()
        t.join()

    def test_planted_unguarded_access_is_caught(self, instrumented):
        box = _PlantedBox()
        self._share(box)
        box._items.append("unguarded")  # planted violation
        kinds = [v.kind for v in instrumented.violations]
        assert kinds == ["unguarded-access"]
        assert "_items" in instrumented.violations[0].description
        assert "_lock" in instrumented.violations[0].description

    def test_guarded_access_is_clean(self, instrumented):
        box = _PlantedBox()
        self._share(box)
        with box._lock:
            box._items.append("guarded")
        assert instrumented.violations == []

    def test_thread_confined_instance_never_enforces(self, instrumented):
        box = _PlantedBox()
        box._items.append(1)  # only ever one thread — no enforcement
        assert instrumented.violations == []

    def test_allow_unguarded_region_is_exempt(self, instrumented):
        box = _PlantedBox()
        self._share(box)
        with blocking.allow_unguarded("test: torn read tolerated"):
            box._items.append("sanctioned")
        assert instrumented.violations == []

    def test_allow_unguarded_requires_a_reason(self):
        with pytest.raises(ValueError):
            with blocking.allow_unguarded(""):
                pass

    def test_violations_dedupe_per_class_attr(self, instrumented):
        box = _PlantedBox()
        self._share(box)
        box._items.append(1)
        box._items.append(2)
        assert len(instrumented.violations) == 1

    def test_uninstall_restores_plain_attribute_access(self):
        state = lockdep.LockdepState()
        inst = lockdep.install_guarded_access(
            [(__name__, "_PlantedBox", "_items", "_lock")], state=state)
        box = _PlantedBox()
        box._items.append(1)
        inst.uninstall()
        assert "_items" not in vars(_PlantedBox)
        assert box._items == [1]  # value survived in the instance dict
