"""kbt-check static analyzer: fixture-driven good/bad snippets per rule,
suppression contract, CLI, and the tier-1 self-enforcement check that keeps
the whole package clean."""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

from kube_batch_tpu.analysis import check_source, run_paths
from kube_batch_tpu.analysis.rules import RULES_BY_ID


def findings_for(src: str, relpath: str):
    return check_source(textwrap.dedent(src), relpath)


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# KBT001 — wall clock outside the Clock seam
# ---------------------------------------------------------------------------


class TestKBT001:
    BAD = """
    import time

    def pace():
        time.sleep(1.0)
        return time.monotonic()
    """

    def test_bad_snippet_triggers_exactly_kbt001(self):
        findings = findings_for(self.BAD, "actions/x.py")
        assert rule_ids(findings) == ["KBT001"]
        assert len(findings) == 2

    def test_from_import_alias_is_caught(self):
        findings = findings_for(
            "from time import sleep as zzz\ndef f():\n    zzz(1)\n",
            "sim/x.py",
        )
        assert rule_ids(findings) == ["KBT001"]

    def test_datetime_now_is_caught(self):
        findings = findings_for(
            "import datetime\ndef f():\n    return datetime.datetime.now()\n",
            "cache/x.py",
        )
        assert rule_ids(findings) == ["KBT001"]

    def test_injected_clock_is_the_sanctioned_path(self):
        good = """
        class S:
            def pace(self):
                t = self.clock.monotonic()
                self.clock.sleep(1.0)
                return t
        """
        assert findings_for(good, "scheduler.py") == []

    def test_out_of_scope_paths_unflagged(self):
        # cmd/ owns real wall-clock concerns (leases, rate limits)
        assert findings_for(self.BAD, "cmd/x.py") == []

    def test_annotation_suppresses(self):
        src = """
        import time

        def f():
            # kbt: allow[KBT001] measures real compute for the bench
            return time.perf_counter()
        """
        assert findings_for(src, "actions/x.py") == []


# ---------------------------------------------------------------------------
# KBT002 — blocking call under a lock
# ---------------------------------------------------------------------------


class TestKBT002:
    def test_sleep_under_lock_triggers(self):
        src = """
        import time

        def take(self):
            with self._lock:
                time.sleep(0.1)
        """
        # KBT002 everywhere; out of KBT001 scope so only the lock rule fires
        findings = findings_for(src, "cmd/server.py")
        assert rule_ids(findings) == ["KBT002"]

    def test_future_result_and_queue_get_under_lock_trigger(self):
        src = """
        def drain(self):
            with self._lock:
                self.future.result()
                item = work_queue.get()
        """
        findings = findings_for(src, "k8s/x.py")
        assert len(findings) == 2 and rule_ids(findings) == ["KBT002"]

    def test_tokenbucket_pattern_is_clean(self):
        src = """
        def take(self):
            with self._lock:
                self._tokens -= 1.0
                wait = max(0.0, -self._tokens / self._qps)
            if wait:
                self._time.sleep(wait)
        """
        assert findings_for(src, "cmd/server.py") == []

    def test_dict_get_under_lock_is_not_blocking(self):
        src = """
        def read(self):
            with self._lock:
                return self.index.get("k")
        """
        assert findings_for(src, "k8s/x.py") == []

    def test_nested_def_body_is_not_under_the_lock(self):
        src = """
        import time

        def sched(self):
            with self._lock:
                def later():
                    time.sleep(1)
                return later
        """
        assert findings_for(src, "cmd/x.py") == []

    def test_non_lock_with_is_ignored(self):
        src = """
        import time

        def f():
            with open("x") as fh:
                time.sleep(1)
                return fh
        """
        assert findings_for(src, "cmd/x.py") == []


# ---------------------------------------------------------------------------
# KBT003 — module-level mutable state in actions/framework
# ---------------------------------------------------------------------------


class TestKBT003:
    def test_module_dict_and_global_write_trigger(self):
        src = """
        last_host_discards = {}

        def execute(ssn):
            global cycle_count
            cycle_count = 1
        """
        findings = findings_for(src, "actions/x.py")
        assert rule_ids(findings) == ["KBT003"]
        assert len(findings) == 2

    def test_constants_and_dunders_are_fine(self):
        src = """
        OVERCOMMIT = {"cpu": 1.2}
        __all__ = ["execute"]
        logger = get_logger("x")
        """
        assert findings_for(src, "framework/x.py") == []

    def test_annotated_registry_is_fine(self):
        src = """
        # kbt: allow[KBT003] import-time registry, read-only after import
        _builders = {}
        """
        assert findings_for(src, "framework/x.py") == []

    def test_out_of_scope_module_state_unflagged(self):
        assert findings_for("cache = {}\n", "plugins/x.py") == []


# ---------------------------------------------------------------------------
# KBT004 — translate-layer fail-open defaults
# ---------------------------------------------------------------------------


class TestKBT004:
    def test_none_fallback_in_value_function_triggers(self):
        src = """
        def node_from(spec):
            if spec.get("kind") == "node":
                return spec["name"]
            return None
        """
        findings = findings_for(src, "k8s/translate.py")
        assert rule_ids(findings) == ["KBT004"]

    def test_empty_collection_fallback_triggers(self):
        src = """
        def terms_from(spec):
            if "terms" in spec:
                return list(spec["terms"])
            return []
        """
        assert rule_ids(findings_for(src, "k8s/translate.py")) == ["KBT004"]

    def test_procedures_with_bare_returns_are_fine(self):
        src = """
        def apply(cache, obj):
            if obj is None:
                return
            cache.add(obj)
        """
        assert findings_for(src, "k8s/translate.py") == []

    def test_fail_closed_sentinel_is_fine(self):
        src = """
        SENTINEL = "__restricted__"

        def node_from(spec):
            if spec.get("kind") == "node":
                return spec["name"]
            return SENTINEL
        """
        assert findings_for(src, "k8s/translate.py") == []

    def test_annotated_default_is_fine(self):
        src = """
        def owner_of(meta):
            for ref in meta.get("ownerReferences") or []:
                return ref["uid"]
            # kbt: allow[KBT004] ownerless pods are a valid spec state
            return None
        """
        assert findings_for(src, "k8s/translate.py") == []

    def test_out_of_scope_none_returns_unflagged(self):
        src = "def f(x):\n    if x:\n        return x\n    return None\n"
        assert findings_for(src, "cache/x.py") == []


# ---------------------------------------------------------------------------
# KBT005 — host-device sync in ops/
# ---------------------------------------------------------------------------


class TestKBT005:
    def test_sync_calls_trigger(self):
        src = """
        import numpy as np

        def solve(x):
            y = np.asarray(x)
            x.block_until_ready()
            return float(y)
        """
        findings = findings_for(src, "ops/x.py")
        assert rule_ids(findings) == ["KBT005"]
        assert len(findings) == 3

    def test_jnp_dispatch_in_python_loop_triggers(self):
        src = """
        import jax.numpy as jnp

        def f(keys):
            total = 0
            for k in keys:
                total = total + jnp.sum(k)
            return total
        """
        assert rule_ids(findings_for(src, "ops/x.py")) == ["KBT005"]

    def test_vectorized_jnp_is_fine(self):
        src = """
        import jax.numpy as jnp

        def f(x):
            return jnp.sum(x, axis=0)
        """
        assert findings_for(src, "ops/x.py") == []

    def test_annotated_trace_time_unroll_is_fine(self):
        src = """
        import jax.numpy as jnp

        def f(xs):
            acc = xs[0]
            for x in xs[1:]:
                # kbt: allow[KBT005] trace-time unroll over a static tuple
                acc = jnp.maximum(acc, x)
            return acc
        """
        assert findings_for(src, "ops/x.py") == []

    def test_out_of_scope_numpy_unflagged(self):
        src = "import numpy as np\ndef f(x):\n    return np.asarray(x)\n"
        assert findings_for(src, "cache/x.py") == []


# ---------------------------------------------------------------------------
# engine: suppression contract
# ---------------------------------------------------------------------------


class TestSuppressions:
    def test_allow_without_reason_does_not_suppress(self):
        src = """
        import time

        def f():
            return time.time()  # kbt: allow[KBT001]
        """
        findings = findings_for(src, "actions/x.py")
        # the original finding survives AND the empty allow is itself flagged
        assert rule_ids(findings) == ["KBT000", "KBT001"]

    def test_multiline_annotation_block_covers_next_statement(self):
        src = """
        import time

        def f():
            # kbt: allow[KBT001] long explanation of why this wall-clock
            # read is deliberate, spilling onto a second comment line
            return time.time()
        """
        assert findings_for(src, "actions/x.py") == []

    def test_allow_only_suppresses_its_own_rule(self):
        src = """
        import time

        def f(self):
            with self._lock:
                # kbt: allow[KBT002] reason that names the wrong rule
                time.sleep(1)
        """
        findings = findings_for(src, "actions/x.py")
        assert rule_ids(findings) == ["KBT001"]  # KBT002 suppressed, 001 not

    def test_syntax_error_reports_kbt000(self):
        findings = findings_for("def f(:\n", "actions/x.py")
        assert rule_ids(findings) == ["KBT000"]


# ---------------------------------------------------------------------------
# self-enforcement: the package must be clean (tier-1)
# ---------------------------------------------------------------------------


class TestSelfEnforcement:
    def test_package_has_zero_unsuppressed_findings(self):
        findings = run_paths()  # defaults to the kube_batch_tpu tree
        assert findings == [], "\n" + "\n".join(f.render() for f in findings)

    def test_every_rule_has_title_and_grounding_doc(self):
        for rule in RULES_BY_ID.values():
            assert rule.title
            # each rule documents the incident that motivated it
            assert rule.__doc__ and len(rule.__doc__.strip()) > 40


# ---------------------------------------------------------------------------
# CLI: exit codes + JSONL
# ---------------------------------------------------------------------------


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "kube_batch_tpu.analysis", *args],
            capture_output=True, text=True,
            cwd=str(Path(__file__).resolve().parent.parent),
        )

    def test_clean_tree_exits_zero(self):
        proc = self._run("kube_batch_tpu/analysis")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_findings_exit_nonzero_and_jsonl_parses(self, tmp_path):
        bad = tmp_path / "ops" / "hot.py"
        bad.parent.mkdir()
        bad.write_text("def f(x):\n    x.block_until_ready()\n")
        proc = self._run("--jsonl", str(bad))
        assert proc.returncode == 1
        rows = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
        assert rows and rows[0]["rule"] == "KBT005"
        assert rows[0]["line"] == 2

    def test_select_unknown_rule_is_usage_error(self):
        proc = self._run("--select", "KBT999")
        assert proc.returncode == 2

    def test_nonexistent_path_is_a_finding_not_clean(self):
        # a typo'd CI path must not report clean/exit 0
        proc = self._run("no/such/dir")
        assert proc.returncode == 1
        assert "does not exist" in proc.stdout
